"""Chaos harness: drive a mini-cluster through a scenario and verify
recovery invariants from the telemetry event log alone.

:func:`run_scenario` launches the same supervision tree production
uses — ``tpurun`` spawns a local master subprocess, runs the elastic
agent in-process, and the agent spawns/monitors the toy train loop —
with ``DLROVER_CHAOS`` exported so every process of the job arms the
scenario, and ``DLROVER_EVENT_LOG`` collecting one JSONL stream from
all of them.  Afterwards the :class:`Invariant` checkers read ONLY
that event log (plus a /proc scan for the orphan check): if an
invariant cannot be decided from telemetry, the telemetry is the bug.

Invariants shipped here:

- :class:`WorkerRestarted` — the fault produced a supervised restart.
- :class:`RendezvousReconverged` — an elastic-training rendezvous
  completed AFTER the fault, within a bound.
- :class:`BoundedStepLoss` — steps lost across the fault ≤ one
  checkpoint interval (from ``train_step`` + ``chaos_inject`` events).
- :class:`TrainingCompleted` — the step budget finished and the final
  checkpoint committed.
- :class:`DeterministicTimeline` — the ``chaos_inject`` sequence
  matches a reference timeline (cross-run determinism).
- :class:`NoOrphanProcesses` — nothing spawned for the job outlives
  it (forkserver children included).
"""

import json
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.chaos.scenarios import (
    CHAOS_TRAIN_SCRIPT,
    CKPT_EVERY_ENV,
    DISK_EVERY_ENV,
    RESIZE_TRAIN_SCRIPT,
    RL_TRAIN_SCRIPT,
    RUN_OPTIONS,
    SHARD_DATASET_ENV,
    SPARSE_RESHARD_TRAIN_SCRIPT,
    SPARSE_RESIZE_TRAIN_SCRIPT,
    SPARSE_SERVING_TRAIN_SCRIPT,
    SPARSE_TRAIN_SCRIPT,
    STEP_SLEEP_ENV,
    TOTAL_STEPS_ENV,
    resize_reference_losses,
    rl_reference_losses,
    sparse_reference_losses,
)
from dlrover_tpu.chaos.schedule import Scenario, load_scenario
from dlrover_tpu.common.env_utils import proc_stat_fields
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry import timeline as flight
from dlrover_tpu.telemetry.events import (
    EVENT_LOG_ENV,
    EVENTS_AGGREGATE_ENV,
    collect_events,
)

CHAOS_EVENT = "chaos_inject"

# toy train loops a scenario can select via RUN_OPTIONS["train_script"]
# (single-node harness defaults to the GPT loop, the resize harness to
# the GSPMD resize loop)
TRAIN_SCRIPTS = {
    "default": CHAOS_TRAIN_SCRIPT,
    "sparse": SPARSE_TRAIN_SCRIPT,
    "resize": RESIZE_TRAIN_SCRIPT,
    "sparse_resize": SPARSE_RESIZE_TRAIN_SCRIPT,
    "sparse_serving": SPARSE_SERVING_TRAIN_SCRIPT,
    "sparse_reshard": SPARSE_RESHARD_TRAIN_SCRIPT,
    "rl": RL_TRAIN_SCRIPT,
}


def seed_sparse_world_checkpoint(
    ckpt_dir: str,
    world: int = 2,
    step: int = 4,
    out_json: str = "",
    n_keys: int = 1200,
    dim: int = 16,
) -> Dict:
    """Write a COMMITTED ``world``-rank sparse checkpoint directly in
    the storage layout (rank_N.ckpt/rank_N.meta + tracker) — no shm,
    no saver — so a world-1 job restoring from ``ckpt_dir`` must run
    the cross-world STREAMING reshard on its first load.  Each rank's
    table holds exactly the keys ``owner_of_keys`` assigns it (a
    distinct slice of the logical table), trained a few GroupAdam
    steps so values/freq/slots are non-trivial.  Returns (and writes
    to ``out_json``) the per-table additive digest sums and the
    distinct-union row count the exactly-once invariant checks
    against."""
    import pickle

    import numpy as np

    from dlrover_tpu.checkpoint.saver import (
        meta_file,
        shard_file,
        step_dirname,
    )
    from dlrover_tpu.checkpoint.shm_handler import (
        CheckpointConfig,
        TensorMeta,
        _flatten_state_dict,
    )
    from dlrover_tpu.checkpoint.sparse import (
        KV_STATE_KEY,
        SparseStateAdapter,
        owner_of_keys,
        rows_digest,
    )
    from dlrover_tpu.common.constants import CheckpointConstant
    from dlrover_tpu.ops.kv_variable import (
        GroupAdamOptimizer,
        KvVariable,
    )

    def _serialize(state_dict, rank: int) -> Tuple[Dict, bytes]:
        """state dict -> (meta, raw) in the exact shm/storage layout
        the engine's restore reads back."""
        flat = _flatten_state_dict(state_dict)
        entries, scalars = [], {}
        for key, leaf in flat.items():
            if isinstance(leaf, (np.ndarray, np.generic)):
                entries.append((key, np.ascontiguousarray(leaf)))
            else:
                scalars[key] = leaf
        blob = pickle.dumps(scalars)
        metas, offset = {}, 0
        for key, arr in entries:
            metas[key] = TensorMeta(
                shape=tuple(arr.shape), dtype=str(arr.dtype),
                offset=offset, nbytes=arr.nbytes,
            )
            offset += arr.nbytes
        raw = bytearray(offset + len(blob))
        for key, arr in entries:
            m = metas[key]
            raw[m.offset:m.offset + m.nbytes] = arr.tobytes()
        raw[offset:] = blob
        meta = {
            "tensors": metas,
            "config": CheckpointConfig(
                step=step, path=ckpt_dir, rank=rank,
                world_size=world, global_shard_num=world,
            ),
            "scalar_offset": offset,
            "scalar_nbytes": len(blob),
        }
        return meta, bytes(raw)

    step_dir = os.path.join(ckpt_dir, step_dirname(step))
    os.makedirs(step_dir, exist_ok=True)
    keys = np.arange(n_keys, dtype=np.int64)
    table_sums: Dict[str, int] = {}
    union_rows = 0
    for rank in range(world):
        table = KvVariable(dim=dim, seed=rank + 21, name="emb")
        opt = GroupAdamOptimizer(table, learning_rate=5e-3)
        adapter = SparseStateAdapter(digest=True)
        adapter.register_optimizer(opt)
        mine = keys[owner_of_keys(keys, world) == rank]
        rng = np.random.default_rng(rank + 3)
        for _ in range(3):
            batch = rng.choice(mine, size=min(256, mine.size),
                               replace=False)
            opt.apply_gradients(
                batch, np.tanh(table.gather(batch)) * 0.1
            )
        kv_state = adapter.export_state(step=step, rank=rank)
        for name, tbl in adapter.tables.items():
            k, v, f = tbl.export()
            table_sums[name] = (
                table_sums.get(name, 0) + rows_digest(k, v, f)
            ) % (1 << 64)
            union_rows += len(k)
        sd = {
            "w": np.zeros(8, np.float32),
            KV_STATE_KEY: kv_state,
        }
        meta, raw = _serialize(sd, rank)
        with open(os.path.join(step_dir, shard_file(rank)), "wb") as f:
            f.write(raw)
        with open(os.path.join(step_dir, meta_file(rank)), "wb") as f:
            f.write(pickle.dumps(meta))
    with open(
        os.path.join(ckpt_dir, CheckpointConstant.TRACKER_FILE), "w"
    ) as f:
        f.write(str(step))
    seed = {
        "step": int(step),
        "world": int(world),
        "rows": int(union_rows),
        "tables": {n: f"{s:016x}" for n, s in table_sums.items()},
    }
    if out_json:
        with open(out_json, "w") as f:
            json.dump(seed, f, indent=2)
    return seed


@dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""

    def __bool__(self):
        return self.ok


class Invariant:
    """Base checker: decide pass/fail from the job's event list."""

    name = "invariant"
    # ceiling-class invariants assert a measured DURATION against a
    # wall-clock ceiling; on a shared/sandboxed CI box a single noisy
    # trip (gofer contention, scheduler stalls) is not a regression,
    # so run_scenario grants the scenario ONE bounded re-measure when
    # every failed invariant is ceiling-class
    ceiling_class = False

    def check(self, events: List[dict],
              run: "ChaosRunReport") -> InvariantResult:
        raise NotImplementedError


def _injections(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("type") == CHAOS_EVENT]


def _first_fault_ts(events: List[dict]) -> Optional[float]:
    inj = _injections(events)
    return inj[0]["ts"] if inj else None


class WorkerRestarted(Invariant):
    name = "worker_restarted"

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        restarts = [
            e for e in events
            if e.get("type") == "worker_restart"
            and e["ts"] >= fault_ts
        ]
        if not restarts:
            return InvariantResult(
                self.name, False, "no worker_restart after the fault"
            )
        return InvariantResult(
            self.name, True,
            f"{len(restarts)} restart(s) after fault",
        )


class RendezvousReconverged(Invariant):
    """An elastic-training rendezvous completed after the fault, and
    the gap stayed under ``within_s``."""

    name = "rendezvous_reconverged"

    def __init__(self, within_s: float = 120.0):
        self.within_s = within_s

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        rounds = [
            e for e in events
            if e.get("type") == "rendezvous_complete"
            and e.get("rdzv") == "elastic-training"
            and e["ts"] > fault_ts
        ]
        if not rounds:
            return InvariantResult(
                self.name, False,
                "no elastic-training rendezvous completed after the "
                "fault",
            )
        gap = rounds[0]["ts"] - fault_ts
        if gap > self.within_s:
            return InvariantResult(
                self.name, False,
                f"reconverged after {gap:.1f}s > bound {self.within_s}s",
            )
        return InvariantResult(
            self.name, True, f"reconverged in {gap:.1f}s"
        )


class BoundedStepLoss(Invariant):
    """Steps lost across the fault ≤ one checkpoint interval, computed
    from telemetry only: the highest ``train_step`` of the first
    incarnation vs the first ``train_step`` of a respawned one."""

    name = "bounded_step_loss"

    def __init__(self, ckpt_interval: int):
        self.ckpt_interval = ckpt_interval

    def check(self, events, run):
        first = [
            e["step"] for e in events
            if e.get("type") == "train_step"
            and e.get("restart_count", 0) == 0
        ]
        resumed = [
            e["step"] for e in events
            if e.get("type") == "train_step"
            and e.get("restart_count", 0) > 0
        ]
        if not first:
            return InvariantResult(
                self.name, False, "no train_step events at all"
            )
        if not resumed:
            return InvariantResult(
                self.name, False,
                "no post-restart train_step events (recovery never "
                "stepped)",
            )
        last_before = max(first)
        resume_at = min(resumed)
        lost = last_before - (resume_at - 1)
        if lost > self.ckpt_interval:
            return InvariantResult(
                self.name, False,
                f"lost {lost} step(s) (last pre-fault {last_before}, "
                f"resumed at {resume_at}) > interval "
                f"{self.ckpt_interval}",
            )
        if lost < 0:
            return InvariantResult(
                self.name, False,
                f"resumed AHEAD of progress (last pre-fault "
                f"{last_before}, resumed at {resume_at})",
            )
        return InvariantResult(
            self.name, True,
            f"lost {lost} step(s) ≤ interval {self.ckpt_interval} "
            f"(resumed at {resume_at} after {last_before})",
        )


class TrainingCompleted(Invariant):
    """The job stepped through its full budget and committed the final
    checkpoint."""

    name = "training_completed"

    def __init__(self, total_steps: int):
        self.total_steps = total_steps

    def check(self, events, run):
        steps = [
            e["step"] for e in events if e.get("type") == "train_step"
        ]
        commits = [
            e["step"] for e in events
            if e.get("type") == "checkpoint_commit"
        ]
        if not steps or max(steps) < self.total_steps:
            return InvariantResult(
                self.name, False,
                f"highest step {max(steps) if steps else None} < "
                f"budget {self.total_steps}",
            )
        if self.total_steps not in commits:
            return InvariantResult(
                self.name, False,
                f"final step {self.total_steps} never committed "
                f"(commits: {sorted(set(commits))})",
            )
        return InvariantResult(
            self.name, True,
            f"stepped to {max(steps)}, committed {self.total_steps}",
        )


class DiagnosisEmitted(Invariant):
    """The master's diagnosis chain reached the expected action."""

    name = "diagnosis_emitted"

    def __init__(self, action: str):
        self.action = action

    def check(self, events, run):
        verdicts = [
            e for e in events if e.get("type") == "diagnosis_verdict"
        ]
        hits = [v for v in verdicts if v.get("action") == self.action]
        if not hits:
            return InvariantResult(
                self.name, False,
                f"no diagnosis_verdict with action {self.action!r} "
                f"(saw {[v.get('action') for v in verdicts]})",
            )
        return InvariantResult(self.name, True, hits[0].get("reason", ""))


class HangDiagnosed(Invariant):
    """Deep-diagnosis invariant: within ``within_s`` of the injected
    stall, the master reached a *hung* verdict that carries captured
    stack evidence and a measured stall duration, fed by at least one
    agent ``hang_evidence`` capture (stacks present)."""

    name = "hang_diagnosed"

    def __init__(self, within_s: float = 30.0):
        self.within_s = within_s

    def check(self, events, run):
        stalls = [
            e for e in _injections(events)
            if e.get("action") == "stall"
        ]
        if not stalls:
            return InvariantResult(
                self.name, False, "no stall injection recorded"
            )
        t0 = stalls[0]["ts"]
        evidence = [
            e for e in events
            if e.get("type") == "hang_evidence" and e["ts"] >= t0
        ]
        if not evidence:
            return InvariantResult(
                self.name, False,
                "no hang_evidence capture after the stall (agent "
                "watchdog never fired)",
            )
        if not any(e.get("stacks") for e in evidence):
            return InvariantResult(
                self.name, False,
                "hang_evidence carries no stacks",
            )
        verdicts = [
            e for e in events
            if e.get("type") == "diagnosis_verdict"
            and e.get("hung") and e["ts"] >= t0
        ]
        if not verdicts:
            return InvariantResult(
                self.name, False,
                "no hung diagnosis_verdict after the stall",
            )
        v = verdicts[0]
        gap = v["ts"] - t0
        stall_s = v.get("stall_s")
        if not isinstance(stall_s, (int, float)) or stall_s <= 0:
            return InvariantResult(
                self.name, False,
                f"verdict carries no measured stall ({stall_s!r})",
            )
        if not v.get("evidence"):
            return InvariantResult(
                self.name, False,
                "verdict carries no evidence excerpt",
            )
        if gap > self.within_s:
            return InvariantResult(
                self.name, False,
                f"diagnosed after {gap:.1f}s > bound "
                f"{self.within_s}s",
            )
        return InvariantResult(
            self.name, True,
            f"hung verdict in {gap:.1f}s (stall {stall_s:.1f}s, "
            f"{len(evidence)} evidence capture(s))",
        )


class OnlyCulpritRestarted(Invariant):
    """A hang verdict must restart exactly the culprit node: at least
    one restart happened, every restart is on ``culprit_rank``, and
    the job was never aborted for the hang."""

    def __init__(self, culprit_rank: int = 0):
        self.culprit_rank = culprit_rank
        self.name = f"only_culprit_node{culprit_rank}_restarted"

    def check(self, events, run):
        restarts = [
            e for e in events if e.get("type") == "worker_restart"
        ]
        if not restarts:
            return InvariantResult(
                self.name, False,
                "no worker_restart (culprit never relaunched)",
            )
        strays = [
            e for e in restarts
            if e.get("node_rank") != self.culprit_rank
        ]
        if strays:
            return InvariantResult(
                self.name, False,
                f"{len(strays)} restart(s) on non-culprit nodes: "
                f"{sorted({e.get('node_rank') for e in strays})}",
            )
        aborted = [
            e for e in events
            if e.get("type") == "master_exit"
            and e.get("exit_reason") == "hang_error"
        ]
        if aborted:
            return InvariantResult(
                self.name, False,
                "job aborted for the hang instead of a targeted "
                "restart",
            )
        return InvariantResult(
            self.name, True,
            f"{len(restarts)} restart(s), all on culprit node "
            f"{self.culprit_rank}",
        )


class WorldSizeTrajectory(Invariant):
    """Elastic-resize invariant: the completed-world size actually
    changed through the expected sequence — e.g. ``[2, 1, 2]`` means
    the elastic-training rendezvous completed at 2 nodes, later at 1,
    later at 2 again (extra rounds between are allowed; the FINAL
    round must match the last expected size)."""

    name = "world_size_trajectory"

    def __init__(self, expected: Sequence[int]):
        self.expected = list(expected)

    def check(self, events, run):
        sizes = [
            len(e.get("nodes") or [])
            for e in events
            if e.get("type") == "rendezvous_complete"
            and e.get("rdzv") == "elastic-training"
        ]
        if not sizes:
            return InvariantResult(
                self.name, False, "no elastic rendezvous rounds"
            )
        want = list(self.expected)
        i = 0
        for size in sizes:
            if i < len(want) and size == want[i]:
                i += 1
        if i < len(want):
            return InvariantResult(
                self.name, False,
                f"round sizes {sizes} do not contain the expected "
                f"trajectory {want} (matched {i}/{len(want)})",
            )
        if sizes[-1] != want[-1]:
            return InvariantResult(
                self.name, False,
                f"final world is {sizes[-1]}, expected {want[-1]} "
                f"(sizes: {sizes})",
            )
        return InvariantResult(
            self.name, True, f"round sizes {sizes} ⊇ {want}"
        )


class LossTrajectoryMatches(Invariant):
    """Resharded-restore correctness, decided from the event log
    alone: every reported ``train_step`` loss must equal the
    uninterrupted-control trajectory at that step (the resize train
    loop derives its batch from the step index, so the control is a
    pure recomputation), AND at least one step must carry records
    from two distinct incarnations/nodes — the proof that replay /
    cross-node agreement was actually exercised, not vacuously
    skipped.  A restore that resharded the params wrong diverges at
    the first replayed step."""

    name = "loss_trajectory_matches_control"

    def __init__(self, expected: Sequence[float],
                 rtol: float = 1e-3, atol: float = 1e-5):
        self.expected = list(expected)
        self.rtol = rtol
        self.atol = atol

    def check(self, events, run):
        by_step = {}
        for e in events:
            if e.get("type") != "train_step":
                continue
            loss = e.get("loss")
            if not isinstance(loss, (int, float)):
                continue
            step = int(e.get("step", 0))
            by_step.setdefault(step, []).append(
                (e.get("node_rank"), e.get("restart_count"), loss)
            )
        if not by_step:
            return InvariantResult(
                self.name, False, "no train_step events carry a loss"
            )
        mismatches = []
        for step, recs in sorted(by_step.items()):
            if not (1 <= step <= len(self.expected)):
                mismatches.append(f"step {step} outside control")
                continue
            want = self.expected[step - 1]
            for rank, count, loss in recs:
                if abs(loss - want) > self.atol + self.rtol * abs(want):
                    mismatches.append(
                        f"step {step} node{rank} r{count}: "
                        f"{loss:.6g} != control {want:.6g}"
                    )
        if mismatches:
            return InvariantResult(
                self.name, False,
                f"{len(mismatches)} loss divergence(s): "
                f"{mismatches[:5]}",
            )
        multi = [
            step for step, recs in by_step.items()
            if len({(r, c) for r, c, _ in recs}) > 1
        ]
        if not multi:
            return InvariantResult(
                self.name, False,
                "no step was reported by more than one incarnation/"
                "node — the cross-check never ran",
            )
        return InvariantResult(
            self.name, True,
            f"{len(by_step)} step(s) match control "
            f"({len(multi)} with multi-incarnation agreement)",
        )


class BoundedStepLossPerRestart(Invariant):
    """Per-restart step loss: for every ``worker_restart`` on node N
    at incarnation C, the steps lost between incarnation C-1's last
    step and C's first step stay within one durable-checkpoint
    interval, and the new incarnation never resumes AHEAD of
    recorded progress.  (The global first-vs-resumed rule breaks
    down once a REPLACEMENT node legitimately starts a fresh
    incarnation-0 process late in the run.)

    Incarnation-aware escape hatch: ``interval`` bounds the loss only
    when the dead incarnation actually committed on cadence.  A kill
    can land while the loop has stepped past the last *committed*
    step by more than ``disk_every`` (the commit barrier is
    per-cadence, not per-step, and a cross-world restore skips the
    per-node shm tier entirely) — then the rightful resume point is
    the newest durable commit that existed when the new incarnation
    booted, however far back that is.  Such a restart passes iff it
    resumed exactly from that commit; anything staler still fails."""

    name = "bounded_step_loss_per_restart"

    def __init__(self, interval: int):
        self.interval = interval

    def check(self, events, run):
        steps = {}
        first_ts = {}
        for e in events:
            if e.get("type") != "train_step":
                continue
            key = (e.get("node_rank"), e.get("restart_count", 0))
            steps.setdefault(key, []).append(int(e.get("step", 0)))
            ts = e.get("ts")
            if ts is not None:
                prev = first_ts.get(key)
                if prev is None or ts < prev:
                    first_ts[key] = ts
        commits = sorted(
            (e["ts"], int(e.get("step", 0)))
            for e in events
            if e.get("type") == "checkpoint_commit"
            and e.get("ts") is not None
        )
        checked = 0
        problems = []
        for e in events:
            if e.get("type") != "worker_restart":
                continue
            rank = e.get("node_rank")
            count = e.get("restart_count")
            before = steps.get((rank, count - 1))
            after = steps.get((rank, count))
            if not before or not after:
                continue  # an incarnation never stepped: nothing lost
            lost = max(before) - (min(after) - 1)
            checked += 1
            if lost < 0:
                problems.append(
                    f"node{rank} r{count} resumed AHEAD "
                    f"({min(after)} after {max(before)})"
                )
            elif lost > self.interval:
                boot_ts = first_ts.get((rank, count))
                best = max(
                    (step for ts, step in commits
                     if boot_ts is None or ts <= boot_ts),
                    default=None,
                )
                if best is not None and min(after) - 1 == best:
                    continue  # resumed from the newest durable commit
                problems.append(
                    f"node{rank} r{count} lost {lost} step(s) > "
                    f"interval {self.interval} and did not resume "
                    f"from the newest commit "
                    f"({best if best is not None else 'none seen'})"
                )
        if problems:
            return InvariantResult(
                self.name, False, "; ".join(problems)
            )
        if not checked:
            return InvariantResult(
                self.name, False,
                "no restart had steps on both sides to compare",
            )
        return InvariantResult(
            self.name, True,
            f"{checked} restart(s) within interval {self.interval}",
        )


class ResizePhasesOnTimeline(Invariant):
    """The assembled flight-recorder timeline carries the
    ``dlrover_resize_seconds`` phase breakdown: per resize decision a
    ``decide``/``rendezvous``/``first_step`` trail (``drain`` and
    ``reshard_restore`` where the events exist), rendered as
    ``resize``-cause slices."""

    name = "resize_phases_on_timeline"

    def __init__(self, min_resizes: int = 1):
        self.min_resizes = min_resizes

    def check(self, events, run):
        tl = run.job_timeline
        if tl is None:
            tl = flight.assemble(events)
        slices = tl.slices_by_cat(flight.CAUSE_RESIZE)
        if not slices:
            return InvariantResult(
                self.name, False, "no resize slices on the timeline"
            )
        phases = {}
        for s in slices:
            phases.setdefault(s.meta.get("phase"), []).append(
                round(s.duration, 3)
            )
        completed = len(phases.get("rendezvous", []))
        if completed < self.min_resizes:
            return InvariantResult(
                self.name, False,
                f"only {completed} resize(s) reached a completed "
                f"rendezvous phase (need {self.min_resizes}); "
                f"phases: {phases}",
            )
        missing = {"decide", "rendezvous", "first_step"} - set(phases)
        if missing:
            return InvariantResult(
                self.name, False,
                f"phase(s) {sorted(missing)} absent from the "
                f"timeline (have {sorted(phases)})",
            )
        if "reshard_restore" not in phases:
            return InvariantResult(
                self.name, False,
                f"no reshard_restore phase on any resize — the "
                f"re-formed world never restored (phases: {phases})",
            )
        return InvariantResult(
            self.name, True,
            f"{completed} completed resize(s); phase durations "
            f"{ {k: v for k, v in sorted(phases.items())} }",
        )


class DeterministicTimeline(Invariant):
    """The run's fault timeline equals a reference timeline (usually a
    prior run of the same scenario+seed)."""

    name = "deterministic_timeline"

    def __init__(self, reference: Sequence[Tuple]):
        self.reference = [tuple(r) for r in reference]

    def check(self, events, run):
        timeline = timeline_from_events(events)
        if timeline != self.reference:
            return InvariantResult(
                self.name, False,
                f"timeline {timeline} != reference {self.reference}",
            )
        return InvariantResult(
            self.name, True, f"{len(timeline)} injection(s) identical"
        )


class RestoredFromTier(Invariant):
    """The first post-fault restore came from the expected tier —
    e.g. a torn/corrupted shm snapshot must be refused and recovery
    must fall back to the storage tier.  Decided entirely from the
    ``checkpoint_restore`` event's ``tier`` field (shm / storage /
    orbax), which the engine stamps on every successful restore."""

    name = "restored_from_tier"

    def __init__(self, tier: str):
        self.tier = tier

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        restores = [
            e for e in events
            if e.get("type") == "checkpoint_restore"
            and e["ts"] >= fault_ts
        ]
        if not restores:
            return InvariantResult(
                self.name, False,
                "no checkpoint_restore event after the fault",
            )
        tiers = [e.get("tier") for e in restores]
        if tiers[0] != self.tier:
            return InvariantResult(
                self.name, False,
                f"first post-fault restore came from tier "
                f"{tiers[0]!r}, expected {self.tier!r} "
                f"(all: {tiers})",
            )
        return InvariantResult(
            self.name, True,
            f"restored from {self.tier!r} tier (step "
            f"{restores[0].get('step')})",
        )


def _kv_events(events: List[dict], stage: str) -> List[dict]:
    return [
        e for e in events
        if e.get("type") == "kv_checkpoint" and e.get("stage") == stage
    ]


class KvStateRoundTrip(Invariant):
    """Sparse state is bit-identical through the kill/restore cycle,
    decided from telemetry alone: the first post-fault kv restore's
    per-table content digests (keys + values + frequency counters +
    optimizer slot tables) equal the digests the matching export
    stamped before the fault.  Requires ``DLROVER_KV_DIGEST`` armed
    in the run."""

    name = "kv_state_round_trip"

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        restores = [
            e for e in _kv_events(events, "restore")
            if e["ts"] >= fault_ts
        ]
        if not restores:
            return InvariantResult(
                self.name, False, "no kv restore after the fault"
            )
        restore = restores[0]
        digests = restore.get("digests")
        if not digests:
            return InvariantResult(
                self.name, False,
                "kv restore carries no digests "
                "(DLROVER_KV_DIGEST not armed?)",
            )
        step = restore.get("step")
        exports = [
            e for e in _kv_events(events, "export")
            if e.get("step") == step and e.get("digests")
            and e["ts"] <= restore["ts"]
        ]
        if not exports:
            return InvariantResult(
                self.name, False,
                f"no digested kv export at restored step {step}",
            )
        expected = exports[-1]["digests"]
        if expected != digests:
            diff = sorted(
                t for t in set(expected) | set(digests)
                if expected.get(t) != digests.get(t)
            )
            return InvariantResult(
                self.name, False,
                f"digest mismatch at step {step} for table(s) {diff}: "
                f"exported {expected} != restored {digests}",
            )
        rows = sum(int(d.get("rows", 0)) for d in digests.values())
        return InvariantResult(
            self.name, True,
            f"{len(digests)} table(s), {rows} row(s) bit-identical "
            f"through the cycle at step {step}",
        )


class SpillBreakerTripped(Invariant):
    """The injected spill-tier fault tripped the PRODUCTION
    write-failure breaker (not just the export skip): some post-fault
    kv export event carries ``spill_disabled`` — the stat the tables
    write through to telemetry when the cold tier is taken offline."""

    name = "spill_breaker_tripped"

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        hits = [
            e for e in _kv_events(events, "export")
            if e["ts"] >= fault_ts and e.get("spill_disabled")
        ]
        if not hits:
            return InvariantResult(
                self.name, False,
                "no post-fault kv export reports spill_disabled — "
                "the breaker never tripped",
            )
        lost = max(int(e.get("lost_rows", 0)) for e in hits)
        return InvariantResult(
            self.name, True,
            f"breaker tripped ({len(hits)} export(s) with the cold "
            f"tier offline, up to {lost} stranded row(s) skipped)",
        )


class KvReshardExactlyOnce(Invariant):
    """Cross-world sparse restores redistribute the hash table
    EXACTLY ONCE, decided from events alone.  For every resharded
    restore generation (grouped by restored step + new world size):

    - the per-rank imported row counts sum to the distinct union of
      the old world's rows (``total_rows``, which every participant
      must agree on) — no row lost, none imported twice;
    - per table, the restore digests (additive across disjoint
      shards) sum — mod 2**64 — to the sum of the old ranks' export
      digests at that step: the redistributed CONTENT is the old
      content, bit for bit.
    """

    name = "kv_reshard_exactly_once"

    def __init__(self, min_reshards: int = 2):
        self.min_reshards = min_reshards

    @staticmethod
    def _sum64(hexes: List[str]) -> int:
        total = 0
        for h in hexes:
            total = (total + int(h, 16)) % (1 << 64)
        return total

    def check(self, events, run):
        groups: Dict[tuple, Dict[int, dict]] = {}
        for e in _kv_events(events, "restore"):
            if not e.get("resharded"):
                continue
            key = (e.get("step"), e.get("world_size"))
            # one record per (group, rank): retries keep the last
            groups.setdefault(key, {})[e.get("rank")] = e
        if len(groups) < self.min_reshards:
            return InvariantResult(
                self.name, False,
                f"only {len(groups)} resharded restore generation(s) "
                f"(need {self.min_reshards}): {sorted(groups)}",
            )
        # last digested export per (step, rank)
        exports: Dict[tuple, dict] = {}
        for e in _kv_events(events, "export"):
            if e.get("digests") and e.get("step") is not None:
                exports[(e["step"], e.get("rank", 0))] = e
        problems = []
        detail = []
        for (step, world), by_rank in sorted(groups.items()):
            recs = list(by_rank.values())
            totals = {int(r.get("total_rows", -1)) for r in recs}
            if len(totals) != 1:
                problems.append(
                    f"step {step}->world {world}: ranks disagree on "
                    f"total_rows {sorted(totals)}"
                )
                continue
            total_rows = totals.pop()
            got_rows = sum(int(r.get("rows", 0)) for r in recs)
            if got_rows != total_rows:
                problems.append(
                    f"step {step}->world {world}: imported "
                    f"{got_rows} != union {total_rows} row(s)"
                )
                continue
            src = [
                exp for (s, _r), exp in exports.items() if s == step
            ]
            if not src:
                problems.append(
                    f"step {step}: no digested source exports"
                )
                continue
            tables = set()
            for r in recs:
                tables |= set(r.get("digests") or {})
            bad_tables = []
            for table in sorted(tables):
                want = self._sum64([
                    exp["digests"][table]["sum"]
                    for exp in src if table in exp["digests"]
                ])
                got = self._sum64([
                    r["digests"][table]["sum"]
                    for r in recs if table in (r.get("digests") or {})
                ])
                if want != got:
                    bad_tables.append(table)
            if bad_tables:
                problems.append(
                    f"step {step}->world {world}: digest sums "
                    f"diverge for table(s) {bad_tables}"
                )
                continue
            detail.append(
                f"step {step}->world {world}: {total_rows} row(s) "
                f"across {len(recs)} rank(s)"
            )
        if problems:
            return InvariantResult(
                self.name, False, "; ".join(problems)
            )
        return InvariantResult(
            self.name, True,
            f"{len(detail)} exactly-once reshard(s): "
            + "; ".join(detail),
        )


class KvStreamingReshardReplayed(Invariant):
    """A worker SIGKILLed mid-streaming-reshard is replaced by one
    that replays the reshard from the SAME committed storage with
    exactly-once rows, decided from events + the seeder's JSON:

    - the fault fired on a ``kv.reshard_chunk`` hook (the kill landed
      mid-stream, after at least one chunk imported);
    - a post-fault ``kv_checkpoint`` restore with ``streamed`` ran in
      MORE than one chunk and imported rows == total_rows == the
      seeder's distinct union (no row lost, no chunk double-imported
      — the in-band additive digest assert would have raised, and
      the counts re-check it here);
    - its per-table digests equal the seeder's per-shard export sums
      (additive across the disjoint world-2 shards)."""

    name = "kv_streaming_reshard_replayed"

    def __init__(self, seed_json_path: str):
        self.seed_json_path = seed_json_path

    def check(self, events, run):
        try:
            with open(self.seed_json_path) as f:
                seed = json.load(f)
        except (OSError, ValueError) as e:
            return InvariantResult(
                self.name, False, f"seed JSON unreadable: {e}"
            )
        inj = [
            e for e in _injections(events)
            if e.get("point") == "kv.reshard_chunk"
        ]
        if not inj:
            return InvariantResult(
                self.name, False,
                "no chaos_inject on kv.reshard_chunk — the kill "
                "never landed mid-reshard",
            )
        fault_ts = inj[0]["ts"]
        restores = [
            e for e in _kv_events(events, "restore")
            if e.get("resharded") and e.get("streamed")
            and e["ts"] >= fault_ts
        ]
        if not restores:
            return InvariantResult(
                self.name, False,
                "no streamed resharded kv restore after the fault",
            )
        r = restores[-1]
        if int(r.get("chunks", 0)) <= 1:
            return InvariantResult(
                self.name, False,
                f"reshard ran in {r.get('chunks')} chunk(s) — not "
                "actually streamed (window too large?)",
            )
        rows, total = int(r.get("rows", -1)), int(
            r.get("total_rows", -2)
        )
        if not (rows == total == int(seed["rows"])):
            return InvariantResult(
                self.name, False,
                f"imported {rows} row(s) vs union {total} vs seeded "
                f"{seed['rows']} — rows lost or double-imported",
            )
        digests = r.get("digests") or {}
        bad = []
        for table, want in seed.get("tables", {}).items():
            got = (digests.get(table) or {}).get("sum")
            if got != want:
                bad.append(f"{table}: {got} != seeded {want}")
        if not seed.get("tables"):
            return InvariantResult(
                self.name, False, "seed JSON names no tables"
            )
        if bad:
            return InvariantResult(
                self.name, False,
                "digest mismatch vs seeded shards: " + "; ".join(bad),
            )
        return InvariantResult(
            self.name, True,
            f"replayed reshard imported {rows}/{total} row(s) in "
            f"{r.get('chunks')} chunk(s), {len(digests)} table "
            f"digest(s) equal the seeded sums (kill at chunk "
            f"{inj[0].get('step')} of incarnation 0)",
        )


def _serving_events(events: List[dict], etype: str) -> List[dict]:
    return [e for e in events if e.get("type") == etype]


class ServedGenerationCommitted(Invariant):
    """The replica never served a torn or uncommitted generation,
    decided from events alone: every ``serving_ingest`` generation
    has EXACTLY ONE matching committed ``serving_publish``, and the
    per-table content digests the replica verified over what it
    ACTUALLY applied equal the ones the publisher stamped at commit.
    (The ingest event is emitted only after the full apply under the
    swap lock, so a half-applied generation — e.g. a replica killed
    mid-ingest — can never produce one.)"""

    name = "served_generation_committed"

    def check(self, events, run):
        publishes = {}
        for e in _serving_events(events, "serving_publish"):
            publishes.setdefault(e.get("generation"), []).append(e)
        ingests = _serving_events(events, "serving_ingest")
        if not ingests:
            return InvariantResult(
                self.name, False, "no serving_ingest events recorded"
            )
        problems = []
        for e in ingests:
            gen = e.get("generation")
            pubs = publishes.get(gen)
            if not pubs:
                problems.append(
                    f"gen {gen} ingested but never published"
                )
                continue
            want = pubs[-1].get("tables") or {}
            got = e.get("tables") or {}
            if want != got:
                problems.append(
                    f"gen {gen} digest mismatch: published {want} != "
                    f"ingested {got}"
                )
        if problems:
            return InvariantResult(
                self.name, False, "; ".join(problems[:4])
            )
        gens = sorted({e.get("generation") for e in ingests})
        return InvariantResult(
            self.name, True,
            f"{len(ingests)} ingest(s) over generation(s) "
            f"{gens[0]}..{gens[-1]}, every digest matches its commit",
        )


class PublishExactlyOnce(Invariant):
    """Every committed generation was published exactly once
    (``serving_publish`` is emitted after the tracker advance): no
    generation number repeats, and the sequence is monotonic — the
    trainer killed mid-publish left its half-written generation
    uncommitted and its replacement moved on to a fresh number."""

    name = "publish_exactly_once"

    def check(self, events, run):
        pubs = _serving_events(events, "serving_publish")
        if not pubs:
            return InvariantResult(
                self.name, False, "no serving_publish events recorded"
            )
        gens = [e.get("generation") for e in pubs]
        dupes = sorted({g for g in gens if gens.count(g) > 1})
        if dupes:
            return InvariantResult(
                self.name, False,
                f"generation(s) {dupes} published more than once",
            )
        if gens != sorted(gens):
            return InvariantResult(
                self.name, False,
                f"publish sequence not monotonic: {gens}",
            )
        bases = sum(1 for e in pubs if e.get("kind") == "base")
        return InvariantResult(
            self.name, True,
            f"{len(gens)} generation(s) ({bases} base), each "
            "committed exactly once",
        )


class ServingConverged(Invariant):
    """The replica caught up: the LAST committed generation (highest
    ``serving_publish``) was ingested — freshness converges to zero
    lag after the chaos settles."""

    name = "serving_converged"

    def check(self, events, run):
        pubs = _serving_events(events, "serving_publish")
        ingests = _serving_events(events, "serving_ingest")
        if not pubs or not ingests:
            return InvariantResult(
                self.name, False,
                f"{len(pubs)} publish / {len(ingests)} ingest "
                "event(s)",
            )
        last_pub = max(e.get("generation") for e in pubs)
        got = {e.get("generation") for e in ingests}
        if last_pub not in got:
            return InvariantResult(
                self.name, False,
                f"final committed generation {last_pub} never "
                f"ingested (replica reached {max(got)})",
            )
        fresh = [
            e.get("freshness_s") for e in ingests
            if e.get("generation") == last_pub
            and isinstance(e.get("freshness_s"), (int, float))
        ]
        tail = f" (freshness {fresh[-1]:.3f}s)" if fresh else ""
        return InvariantResult(
            self.name, True,
            f"replica converged on generation {last_pub}{tail}",
        )


class ReplicaReingested(Invariant):
    """After the fault, a RESPAWNED replica re-ingested from
    committed state: some post-fault ``serving_ingest`` carries
    ``respawned`` and the respawn's first ingest is a BASE (a fresh
    replica cannot apply a delta onto nothing — re-basing is the
    recovery path under test)."""

    name = "replica_reingested"

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        post = [
            e for e in _serving_events(events, "serving_ingest")
            if e.get("respawned") and e["ts"] >= fault_ts
        ]
        if not post:
            return InvariantResult(
                self.name, False,
                "no post-fault ingest from a respawned replica",
            )
        first = post[0]
        if first.get("kind") != "base":
            return InvariantResult(
                self.name, False,
                f"respawned replica's first ingest was a "
                f"{first.get('kind')!r} (gen {first.get('generation')}"
                "), not a re-base",
            )
        return InvariantResult(
            self.name, True,
            f"respawned replica re-based at generation "
            f"{first.get('generation')} and applied {len(post)} "
            "generation(s)",
        )


def _fleet_injections(events: List[dict], point: str) -> List[dict]:
    return [
        e for e in _injections(events) if e.get("point") == point
    ]


class RoutedTrafficClean(Invariant):
    """The fleet's headline verdict, decided from events alone: the
    router's ``serving_route`` windows counted real traffic with ZERO
    ``failed`` and ZERO ``stale`` outcomes, the freshness floor never
    regressed across windows, and the load harness's client-side
    aggregate (``serving_lookup_stats`` with ``replica="load"``)
    agrees that no failure ever reached a caller."""

    name = "routed_traffic_clean"

    def check(self, events, run):
        windows = [
            e for e in events if e.get("type") == "serving_route"
        ]
        if not windows:
            return InvariantResult(
                self.name, False, "no serving_route window recorded"
            )
        total = sum(int(e.get("count") or 0) for e in windows)
        failed = sum(int(e.get("failed") or 0) for e in windows)
        stale = sum(int(e.get("stale") or 0) for e in windows)
        if total == 0:
            return InvariantResult(
                self.name, False,
                f"{len(windows)} windows but zero routed lookups",
            )
        floors = [
            int(e.get("generation_floor", -1))
            for e in sorted(windows, key=lambda e: e.get("ts", 0))
        ]
        regress = [
            (a, b) for a, b in zip(floors, floors[1:]) if b < a
        ]
        if failed or stale or regress:
            return InvariantResult(
                self.name, False,
                f"routed {total}: failed={failed} stale={stale} "
                f"floor_regressions={regress[:3]}",
            )
        loads = [
            e for e in events
            if e.get("type") == "serving_lookup_stats"
            and e.get("replica") == "load"
        ]
        client_failed = sum(int(e.get("failed") or 0) for e in loads)
        if client_failed:
            return InvariantResult(
                self.name, False,
                f"{client_failed} client-visible lookup failure(s)",
            )
        return InvariantResult(
            self.name, True,
            f"{total} routed over {len(windows)} windows, 0 failed, "
            f"0 stale, floor {floors[0]}->{floors[-1]} monotonic, "
            f"client failures 0",
        )


class ReplicaShedAndReadmitted(Invariant):
    """The SIGKILLed pool member was shed (``replica_status`` state
    suspect/lost from the router) within ``window_s`` of the
    injection, and its RESPAWNED incarnation later re-joined and was
    re-admitted at a served generation — the pool healed without any
    caller noticing."""

    def __init__(self, killed_id: int, window_s: float):
        self.killed_id = killed_id
        self.window_s = window_s
        self.name = f"replica_shed_within[{window_s:g}s]"

    def check(self, events, run):
        kills = _fleet_injections(events, "serving.ingest")
        if not kills:
            return InvariantResult(
                self.name, False,
                "no serving.ingest injection (replica never killed)",
            )
        kill_ts = kills[0]["ts"]
        status = [
            e for e in events
            if e.get("type") == "replica_status"
            and int(e.get("replica_id", -1)) == self.killed_id
        ]
        sheds = [
            e for e in status
            if e.get("state") in ("suspect", "lost")
            and e["ts"] >= kill_ts
        ]
        if not sheds:
            return InvariantResult(
                self.name, False,
                f"replica {self.killed_id} was never shed after the "
                "kill",
            )
        shed_lag = sheds[0]["ts"] - kill_ts
        if shed_lag > self.window_s:
            return InvariantResult(
                self.name, False,
                f"shed {shed_lag:.2f}s after the kill > "
                f"{self.window_s:g}s window",
            )
        back = [
            e for e in status
            if e.get("state") in ("joined", "recovered", "admitted")
            and e.get("respawned") and e["ts"] > kill_ts
        ]
        if not back:
            return InvariantResult(
                self.name, False,
                f"respawned replica {self.killed_id} never re-joined "
                "the table",
            )
        return InvariantResult(
            self.name, True,
            f"shed {shed_lag:.2f}s after the kill; respawn "
            f"re-admitted at gen {back[-1].get('generation')}",
        )


class FleetHealthyReplicasNotRestarted(Invariant):
    """Blast radius: NO pool member other than the killed one ever
    reported a respawned incarnation — neither the replica kill nor
    the router kill/replay may restart healthy replicas."""

    def __init__(self, killed_id: int):
        self.killed_id = killed_id
        self.name = "fleet_healthy_not_restarted"

    def check(self, events, run):
        respawned = {
            int(e.get("replica_id", -1))
            for e in events
            if e.get("type") == "replica_status" and e.get("respawned")
        }
        strays = sorted(respawned - {self.killed_id})
        if strays:
            return InvariantResult(
                self.name, False,
                f"healthy replica(s) {strays} reported respawned "
                "incarnations",
            )
        return InvariantResult(
            self.name, True,
            f"only replica {self.killed_id} respawned",
        )


class RouterReplayMatchesLive(Invariant):
    """The router was killed mid-stream, resumed routing after its
    respawn, and a cold journal replay reconstructs EXACTLY the live
    routing table the runner snapshotted (per-member generation /
    draining / removed plus the freshness floor) — membership is a
    deterministic function of the journal, not of runtime luck."""

    def __init__(self, journal_dir: str, live_snapshot_json: str):
        self.journal_dir = journal_dir
        self.live_snapshot_json = live_snapshot_json
        self.name = "router_replay_matches_live"

    @staticmethod
    def _view(members: Dict) -> Dict[int, Tuple]:
        return {
            int(v["replica_id"]): (
                int(v.get("generation", -1)),
                bool(v.get("draining")),
                bool(v.get("removed")),
            )
            for v in members
        }

    def check(self, events, run):
        kills = _fleet_injections(events, "serving.route")
        if not kills:
            return InvariantResult(
                self.name, False,
                "no serving.route injection (router never killed)",
            )
        kill_ts = kills[0]["ts"]
        resumed = [
            e for e in events
            if e.get("type") == "serving_route"
            and e["ts"] > kill_ts and int(e.get("count") or 0) > 0
        ]
        if not resumed:
            return InvariantResult(
                self.name, False,
                "no routed traffic after the router kill (respawn "
                "never resumed routing)",
            )
        try:
            with open(self.live_snapshot_json) as f:
                live = json.load(f)
        except OSError as e:
            return InvariantResult(
                self.name, False, f"no live table snapshot: {e}"
            )
        from dlrover_tpu.serving.router import RoutingTable

        replayed = RoutingTable.replayed(self.journal_dir)
        snap = replayed.snapshot()
        got = self._view(snap["members"])
        want = self._view(live["members"])
        if got != want or (
            snap["generation_floor"] != live["generation_floor"]
        ):
            return InvariantResult(
                self.name, False,
                f"replayed table != live: replay={got} "
                f"floor={snap['generation_floor']} vs live={want} "
                f"floor={live['generation_floor']}",
            )
        return InvariantResult(
            self.name, True,
            f"replay == live across {len(want)} member(s), floor "
            f"{snap['generation_floor']}; routing resumed "
            f"({len(resumed)} post-kill windows)",
        )


class EventRecorded(Invariant):
    """At least ``min_count`` events of ``event_type`` exist (e.g. a
    ``warm_fork_fallback`` proving the cold-spawn path ran)."""

    def __init__(self, event_type: str, min_count: int = 1):
        self.event_type = event_type
        self.min_count = min_count
        self.name = f"event_recorded[{event_type}]"

    def check(self, events, run):
        hits = [e for e in events if e.get("type") == self.event_type]
        if len(hits) < self.min_count:
            return InvariantResult(
                self.name, False,
                f"{len(hits)} {self.event_type!r} event(s) < "
                f"required {self.min_count}",
            )
        return InvariantResult(
            self.name, True, f"{len(hits)} event(s)"
        )


class CompileCacheHitOnRecovery(Invariant):
    """The replacement incarnation's first post-restore step HIT the
    persistent compilation cache — decided from the ``compile_cache``
    event the trainer-side retrace monitor emits (entries
    before/after the bracketed first step)."""

    name = "compile_cache_hit"

    def check(self, events, run):
        witnesses = [
            e for e in events
            if e.get("type") == "compile_cache"
            and int(e.get("restart_count", 0) or 0) > 0
        ]
        if not witnesses:
            return InvariantResult(
                self.name, False,
                "no compile_cache event from a respawned incarnation "
                "(retrace monitor never ran)",
            )
        misses = [e for e in witnesses if not e.get("hit")]
        if misses:
            e = misses[0]
            return InvariantResult(
                self.name, False,
                f"cache MISS on restart "
                f"#{e.get('restart_count')}: entries "
                f"{e.get('entries_before')}->{e.get('entries_after')} "
                f"in {e.get('dir')}",
            )
        e = witnesses[0]
        return InvariantResult(
            self.name, True,
            f"cache HIT on restart #{e.get('restart_count')} "
            f"({e.get('entries_before')} warm entries, retrace "
            f"{e.get('retrace_s')}s)",
        )


class RetraceBelow(Invariant):
    """Measured ``retrace + aot`` of every respawned incarnation
    stays under the ceiling — re-establishing a runnable step
    executable (deserialize on an AOT hit, trace+compile otherwise)
    must translate into TIME, not just a filesystem witness."""

    ceiling_class = True

    def __init__(self, ceiling_s: float):
        self.ceiling_s = ceiling_s
        self.name = f"retrace_below[{ceiling_s:g}s]"

    def check(self, events, run):
        # keyed by (node_rank, restart_count) — in a multi-node run
        # one rank's fast recovery must not mask another's violation
        budgets = flight.recovery_budgets(events)
        totals = [
            (key, phases.get("retrace", 0.0) + phases.get("aot", 0.0))
            for key, phases in budgets.items()
            if key[1] > 0 and "retrace" in phases
        ]
        if not totals:
            return InvariantResult(
                self.name, False,
                "no retrace recovery_phase event from a respawned "
                "incarnation",
            )
        worst = max(totals, key=lambda x: x[1])
        if worst[1] > self.ceiling_s:
            return InvariantResult(
                self.name, False,
                f"retrace+aot {worst[1]:.3f}s on node{worst[0][0]} "
                f"restart #{worst[0][1]} > ceiling {self.ceiling_s}s",
            )
        return InvariantResult(
            self.name, True,
            f"worst retrace+aot {worst[1]:.3f}s ≤ {self.ceiling_s}s "
            f"across {len(totals)} recovery(ies)",
        )


class AotCacheHitOnRecovery(Invariant):
    """The replacement incarnation's step executable was
    DESERIALIZED from the AOT cache (the first incarnation's miss
    wrote the entry) — decided from the ``aot_cache`` events."""

    name = "aot_cache_hit"

    def check(self, events, run):
        witnesses = [
            e for e in events
            if e.get("type") == "aot_cache"
            and int(e.get("restart_count", 0) or 0) > 0
        ]
        if not witnesses:
            return InvariantResult(
                self.name, False,
                "no aot_cache event from a respawned incarnation "
                "(the resolve never ran)",
            )
        misses = [e for e in witnesses if not e.get("hit")]
        if misses:
            e = misses[0]
            return InvariantResult(
                self.name, False,
                f"AOT miss on restart #{e.get('restart_count')}: "
                f"resolution={e.get('resolution')} "
                f"reason={e.get('reason', '')!r}",
            )
        e = witnesses[0]
        return InvariantResult(
            self.name, True,
            f"AOT hit on restart #{e.get('restart_count')} "
            f"(deserialize {e.get('load_s')}s, critical-path wait "
            f"{e.get('wait_s', e.get('load_s'))}s)",
        )


class RecoveryCycleBelow(Invariant):
    """The whole measured death→first-step budget of every respawned
    incarnation stays under the ceiling — the sub-second-recovery
    acceptance, decided from the summed ``recovery_phase`` events
    (the same numbers the timeline's budget section prints)."""

    ceiling_class = True

    def __init__(self, ceiling_s: float):
        self.ceiling_s = ceiling_s
        self.name = f"recovery_cycle_below[{ceiling_s:g}s]"

    def check(self, events, run):
        budgets = flight.recovery_budgets(events)
        cycles = [
            (count, sum(
                v for k, v in phases.items()
                if k in flight.RECOVERY_PHASES
            ))
            for (_rank, count), phases in budgets.items()
            if count > 0 and "first_step" in phases
        ]
        if not cycles:
            return InvariantResult(
                self.name, False,
                "no complete recovery budget from a respawned "
                "incarnation",
            )
        worst = max(cycles, key=lambda x: x[1])
        if worst[1] > self.ceiling_s:
            return InvariantResult(
                self.name, False,
                f"death->first-step {worst[1]:.3f}s on restart "
                f"#{worst[0]} > ceiling {self.ceiling_s}s",
            )
        return InvariantResult(
            self.name, True,
            f"worst cycle {worst[1]:.3f}s ≤ {self.ceiling_s}s "
            f"across {len(cycles)} recovery(ies)",
        )


class RecoveryPhasesOnTimeline(Invariant):
    """The assembled flight-recorder timeline carries the recovery
    breakdown slices (spawn/import/restore/retrace/first_step) for a
    respawned incarnation — the budget is not just measured, it is
    visible where operators look."""

    name = "recovery_phases_on_timeline"

    REQUIRED = ("restore", "retrace", "first_step")

    def check(self, events, run):
        if run.job_timeline is None:
            return InvariantResult(
                self.name, False, "no assembled job timeline"
            )
        phases = {
            s.meta.get("phase")
            for s in run.job_timeline.slices
            if s.cat == flight.CAT_RECOVERY_PHASE
            and int(s.meta.get("restart_count", 0) or 0) > 0
        }
        missing = [p for p in self.REQUIRED if p not in phases]
        if missing:
            return InvariantResult(
                self.name, False,
                f"recovery slices missing phase(s) {missing} "
                f"(present: {sorted(p for p in phases if p)})",
            )
        return InvariantResult(
            self.name, True,
            f"phases on timeline: {sorted(p for p in phases if p)}",
        )


class MasterRecoveredFromMirror(Invariant):
    """The respawned master's recovery was seeded from the
    storage-tier journal mirror (``master_recovered.from_mirror``) —
    the witness that a FRESH local journal dir (a different host)
    still recovers the job."""

    name = "master_recovered_from_mirror"

    def check(self, events, run):
        recovered = [
            e for e in events if e.get("type") == "master_recovered"
        ]
        if not recovered:
            return InvariantResult(
                self.name, False, "no master_recovered event"
            )
        from_mirror = [e for e in recovered if e.get("from_mirror")]
        if not from_mirror:
            return InvariantResult(
                self.name, False,
                f"{len(recovered)} recovery(ies), none seeded from "
                "the mirror (the fresh-journal respawn found local "
                "state?)",
            )
        e = from_mirror[0]
        return InvariantResult(
            self.name, True,
            f"recovery #{e.get('recoveries')} seeded from the "
            f"mirror: {e.get('entries')} entries replayed",
        )


class MasterRecovered(Invariant):
    """A respawned master replayed the journal after the fault
    (``master_recovered``) AND at least one client replayed the
    session-resync handshake against it (``master_resync`` or
    ``agent_resync``)."""

    name = "master_recovered"

    def check(self, events, run):
        fault_ts = _first_fault_ts(events)
        if fault_ts is None:
            return InvariantResult(
                self.name, False, "no chaos_inject event recorded"
            )
        recovered = [
            e for e in events
            if e.get("type") == "master_recovered"
            and e["ts"] >= fault_ts
        ]
        if not recovered:
            return InvariantResult(
                self.name, False,
                "no master_recovered event after the fault (journal "
                "replay never ran)",
            )
        resyncs = [
            e for e in events
            if e.get("type") in ("master_resync", "agent_resync")
            and e["ts"] >= fault_ts
        ]
        if not resyncs:
            return InvariantResult(
                self.name, False,
                "master recovered but no client session-resync "
                "handshake followed",
            )
        rec = recovered[0]
        return InvariantResult(
            self.name, True,
            f"recovery #{rec.get('recoveries')} replayed "
            f"{rec.get('entries')} entries (re-queued "
            f"{rec.get('requeued')} lease(s)); "
            f"{len(resyncs)} client resync(s)",
        )


class HealthyWorkersNotRestarted(Invariant):
    """A master crash must NOT cascade into worker restarts: healthy
    trainers ride out the outage on the parked RPC path."""

    name = "healthy_workers_not_restarted"

    def check(self, events, run):
        restarts = [
            e for e in events if e.get("type") == "worker_restart"
        ]
        if restarts:
            return InvariantResult(
                self.name, False,
                f"{len(restarts)} worker restart(s): a master crash "
                "cascaded into the data plane",
            )
        return InvariantResult(self.name, True, "no worker restarts")


class NoDuplicateShards(Invariant):
    """Dataset-shard exactly-once accounting across the fault, from
    ``shard_ack`` events alone: every sample index acked exactly once
    (none lost, none completed twice)."""

    name = "no_duplicate_shards"

    def __init__(self, dataset_size: int, dataset: str = "chaos-ds"):
        self.dataset_size = dataset_size
        self.dataset = dataset

    def check(self, events, run):
        acks = [
            e for e in events
            if e.get("type") == "shard_ack"
            and e.get("dataset") == self.dataset
            and e.get("success")
        ]
        if not acks:
            return InvariantResult(
                self.name, False, "no successful shard_ack events"
            )
        ranges = [(e.get("start"), e.get("end")) for e in acks]
        dupes = {r for r in ranges if ranges.count(r) > 1}
        if dupes:
            return InvariantResult(
                self.name, False,
                f"shard range(s) acked more than once: "
                f"{sorted(dupes)}",
            )
        covered = set()
        for start, end in ranges:
            covered.update(range(int(start), int(end)))
        missing = set(range(self.dataset_size)) - covered
        if missing:
            return InvariantResult(
                self.name, False,
                f"{len(missing)} sample(s) never acked (lost "
                f"shards): {sorted(missing)[:10]}",
            )
        return InvariantResult(
            self.name, True,
            f"{len(acks)} shard(s) acked exactly once, full "
            f"coverage of {self.dataset_size} samples",
        )


class FinalStepCommitted(Invariant):
    """The job's last reached step ended up durably committed (the
    shard-driven loops derive their budget from the dataset, so the
    bound is 'whatever the trainer actually reached')."""

    name = "final_step_committed"

    def check(self, events, run):
        steps = [
            e["step"] for e in events if e.get("type") == "train_step"
        ]
        commits = [
            e.get("step") for e in events
            if e.get("type") == "checkpoint_commit"
        ]
        if not steps:
            return InvariantResult(
                self.name, False, "no train_step events"
            )
        final = max(steps)
        if final not in commits:
            return InvariantResult(
                self.name, False,
                f"final step {final} never committed "
                f"(commits: {sorted(set(commits))})",
            )
        return InvariantResult(
            self.name, True, f"final step {final} committed"
        )


class GoodputAtLeast(Invariant):
    """The master's own goodput accounting (SpeedMonitor ->
    ``dlrover_goodput_ratio``, stamped on the ``master_exit`` event)
    stayed at or above the bound through the scheduled churn."""

    name = "goodput_at_least"

    def __init__(self, threshold: float = 0.90):
        self.threshold = threshold

    def check(self, events, run):
        exits = [
            e for e in events if e.get("type") == "master_exit"
        ]
        if not exits:
            return InvariantResult(
                self.name, False,
                "no master_exit event (master was killed, not "
                "terminated?)",
            )
        goodput = exits[-1].get("goodput")
        if goodput is None:
            return InvariantResult(
                self.name, False, "master_exit carries no goodput"
            )
        if float(goodput) < self.threshold:
            return InvariantResult(
                self.name, False,
                f"goodput {float(goodput):.3f} < bound "
                f"{self.threshold}",
            )
        return InvariantResult(
            self.name, True,
            f"goodput {float(goodput):.3f} >= {self.threshold}",
        )


class GoodputLossAttributed(Invariant):
    """Flight-recorder invariant: the assembled timeline's
    goodput-loss diagnosis must attribute at least
    ``min_attributed_frac`` of the measured non-training wall-clock
    to NAMED causes (rendezvous / restore / master recovery /
    straggler) — an unattributed majority means the telemetry lost
    the causal trail.  Reads the ready-made ``run.attribution``
    instead of re-parsing raw events; runs with no measurable loss
    pass vacuously."""

    name = "goodput_loss_attributed"

    def __init__(self, min_attributed_frac: float = 0.5,
                 expect_cause: str = ""):
        self.min_attributed_frac = min_attributed_frac
        self.expect_cause = expect_cause

    def check(self, events, run):
        attr = run.attribution
        if attr is None:
            tl = flight.assemble(events)
            attr = flight.attribute_goodput_loss(tl)
        loss = attr["loss_s"]
        if loss <= 0:
            return InvariantResult(
                self.name, True, "no non-training time to attribute"
            )
        named = sum(
            v for k, v in attr["buckets"].items()
            if k != flight.CAUSE_UNATTRIBUTED
        )
        frac = named / loss
        if self.expect_cause and (
            attr["buckets"].get(self.expect_cause, 0.0) <= 0
        ):
            return InvariantResult(
                self.name, False,
                f"expected cause {self.expect_cause!r} got 0s "
                f"(buckets: {attr['buckets']})",
            )
        if frac < self.min_attributed_frac:
            return InvariantResult(
                self.name, False,
                f"only {frac:.0%} of {loss:.3f}s lost attributed "
                f"(buckets: {attr['buckets']})",
            )
        return InvariantResult(
            self.name, True,
            f"{frac:.0%} of {loss:.3f}s lost attributed "
            f"({ {k: round(v, 3) for k, v in attr['buckets'].items()} })",
        )


class GoodputConservation(Invariant):
    """Goodput-ledger invariant: the per-incarnation wall-clock
    partition must CLOSE — every incarnation's attributed categories
    sum to its measured wall clock within ``eps`` (default 2%).  An
    attribution the ledger cannot explain is a bug, not a rounding
    error.  With ``named_floor`` > 0 the scenario additionally proves
    causality: at least that fraction of total non-productive time
    must land in NAMED categories (not ``idle_unattributed``) — the
    worker-kill scenarios assert 90%, i.e. the death-witness ->
    rendezvous -> restore -> first-step chain was actually observed.
    Runs whose ledger has no incarnations (no step/restart telemetry
    at all) pass vacuously; the floor is only enforced once there is
    ``min_loss_s`` of non-productive time to explain."""

    name = "goodput_conservation"

    def __init__(self, eps: float = 0.02,
                 named_floor: float = 0.0,
                 min_loss_s: float = 1.0):
        self.eps = eps
        self.named_floor = named_floor
        self.min_loss_s = min_loss_s

    def check(self, events, run):
        from dlrover_tpu.telemetry import goodput as _goodput

        ledger = _goodput.build_ledger(events)
        if not ledger.incarnations:
            return InvariantResult(
                self.name, True, "no incarnations in ledger"
            )
        errors = ledger.conservation_errors(self.eps)
        if errors:
            return InvariantResult(
                self.name, False,
                "conservation violated: " + "; ".join(errors),
            )
        loss = ledger.loss_totals()
        nonprod = sum(loss.values())
        detail = (
            f"{len(ledger.incarnations)} incarnation(s) close "
            f"within {self.eps:.0%}"
        )
        if self.named_floor > 0 and nonprod >= self.min_loss_s:
            named = nonprod - loss.get(_goodput.IDLE, 0.0)
            frac = named / nonprod
            if frac < self.named_floor:
                return InvariantResult(
                    self.name, False,
                    f"only {frac:.0%} of {nonprod:.3f}s "
                    f"non-productive time named (< "
                    f"{self.named_floor:.0%}; totals: "
                    f"{ {k: round(v, 3) for k, v in loss.items() if v > 0} })",
                )
            detail += (
                f"; {frac:.0%} of {nonprod:.3f}s non-productive "
                f"time named"
            )
        return InvariantResult(self.name, True, detail)


class NodeCompletedSteps(Invariant):
    """Per-node progress in a multi-agent run: node ``rank`` stepped
    through at least ``total_steps`` (train_step events carry
    node_rank)."""

    def __init__(self, rank: int, total_steps: int):
        self.rank = rank
        self.total_steps = total_steps
        self.name = f"node{rank}_completed"

    def check(self, events, run):
        steps = [
            e["step"] for e in events
            if e.get("type") == "train_step"
            and e.get("node_rank") == self.rank
        ]
        top = max(steps) if steps else None
        if top is None or top < self.total_steps:
            return InvariantResult(
                self.name, False,
                f"node {self.rank} reached step {top} < budget "
                f"{self.total_steps}",
            )
        return InvariantResult(
            self.name, True,
            f"node {self.rank} reached step {top}",
        )


class NoRestartForNode(Invariant):
    """An un-partitioned node must never be restarted by someone
    else's fault."""

    def __init__(self, rank: int):
        self.rank = rank
        self.name = f"node{rank}_not_restarted"

    def check(self, events, run):
        restarts = [
            e for e in events
            if e.get("type") == "worker_restart"
            and e.get("node_rank") == self.rank
        ]
        if restarts:
            return InvariantResult(
                self.name, False,
                f"node {self.rank} restarted {len(restarts)} "
                "time(s) though it was never faulted",
            )
        return InvariantResult(
            self.name, True, f"node {self.rank} never restarted"
        )


class InjectionsOnlyOnNode(Invariant):
    """The fault stayed confined to its target: every injection's
    node_rank equals ``rank`` (subset-partition scenarios)."""

    def __init__(self, rank: int, action: str = ""):
        self.rank = rank
        self.action = action
        self.name = f"injections_only_on_node{rank}"

    def check(self, events, run):
        inj = _injections(events)
        if self.action:
            inj = [e for e in inj if e.get("action") == self.action]
        if not inj:
            return InvariantResult(
                self.name, False, "no matching injections recorded"
            )
        strays = [
            e for e in inj if e.get("node_rank") != self.rank
        ]
        if strays:
            return InvariantResult(
                self.name, False,
                f"{len(strays)} injection(s) fired on other nodes: "
                f"{[e.get('node_rank') for e in strays]}",
            )
        return InvariantResult(
            self.name, True,
            f"{len(inj)} injection(s), all on node {self.rank}",
        )


class NoOrphanProcesses(Invariant):
    """No process whose cmdline or environment references the job's
    workdir survives the run — catches leaked trainers, forkserver
    children whose template died, and the local master (matched via
    its inherited env)."""

    name = "no_orphan_processes"

    def __init__(self, marker: str, grace_s: float = 5.0):
        self.marker = marker
        self.grace_s = grace_s

    def check(self, events, run):
        deadline = time.time() + self.grace_s
        leftovers = scan_processes(self.marker)
        while leftovers and time.time() < deadline:
            time.sleep(0.2)  # freshly-killed procs may linger a beat
            leftovers = scan_processes(self.marker)
        if leftovers:
            return InvariantResult(
                self.name, False, f"orphans: {leftovers}"
            )
        return InvariantResult(self.name, True, "no survivors")


def _ancestors(pid: int) -> List[int]:
    """pid plus its ppid chain up to init (a shell wrapper invoking
    the harness carries the workdir in ITS cmdline and must never be
    reported as an orphan)."""
    chain = []
    while pid > 1 and len(chain) < 64:
        chain.append(pid)
        fields = proc_stat_fields(pid)
        if fields is None:
            break
        try:
            pid = int(fields[1])  # ppid
        except (IndexError, ValueError):
            break
    chain.append(pid)
    return chain


def scan_processes(marker: str) -> List[int]:
    """Live (non-zombie) pids whose cmdline OR environment contains
    ``marker``, excluding this process and its ancestors.  The environ
    check is what catches a leaked local master: its argv carries no
    workdir, but it inherits ``DLROVER_SHARED_DIR=<workdir>/sock``."""
    skip = set(_ancestors(os.getpid()))
    out: List[int] = []
    marker_b = marker.encode()
    # stdlib runtime infrastructure legitimately outlives a run and
    # inherits the run's env (the harness's own multiprocessing
    # resource tracker, spawned lazily mid-run) — never an orphan
    infra = (b"resource_tracker", b"semaphore_tracker",
             b"multiprocessing.forkserver")
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        pid = int(entry)
        if pid in skip:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read()
            if any(tag in cmdline for tag in infra):
                continue
            matched = marker_b in cmdline
            if not matched:
                try:
                    with open(f"/proc/{pid}/environ", "rb") as f:
                        matched = marker_b in f.read()
                except OSError:  # other-user process: environ hidden
                    pass
            if not matched:
                continue
            fields = proc_stat_fields(pid)
            if fields is not None and fields[0] != b"Z":
                out.append(pid)
        except OSError:
            continue
    return out


def timeline_from_events(events: List[dict]) -> List[Tuple]:
    """Cross-run-comparable fault timeline from the event log:
    ``(seq, point, rule, action, step)`` per injection, ordered by
    emitting source then per-process seq (with step as tiebreak).
    Caveat: two processes with the SAME source both injecting (e.g. a
    future multi-agent partition) collide on (source, seq) — such
    scenarios need a per-process discriminator in the key before
    their timelines compare stably across runs."""
    inj = _injections(events)
    inj.sort(
        key=lambda e: (
            e.get("source", ""), e.get("seq", 0), e.get("step") or 0,
        )
    )
    return [
        (
            e.get("seq"), e.get("point"), e.get("rule"),
            e.get("action"), e.get("step"),
        )
        for e in inj
    ]


@dataclass
class ChaosRunReport:
    scenario: str
    seed: int
    rc: int
    workdir: str
    event_log: str
    events: List[dict] = field(default_factory=list)
    timeline: List[Tuple] = field(default_factory=list)
    invariants: List[InvariantResult] = field(default_factory=list)
    # flight recorder: the assembled job timeline + goodput-loss
    # attribution, ready-made for invariants and post-mortems (no
    # re-parsing of raw events)
    job_timeline: Optional[flight.JobTimeline] = None
    attribution: Optional[dict] = None

    @property
    def ok(self) -> bool:
        return self.rc == 0 and all(r.ok for r in self.invariants)

    def summary(self) -> str:
        lines = [
            f"scenario {self.scenario!r} seed={self.seed} rc={self.rc}",
            f"events: {len(self.events)}  injections: "
            f"{len(self.timeline)}",
        ]
        for t in self.timeline:
            lines.append(f"  inject {t}")
        if self.attribution and self.attribution["loss_s"] > 0:
            lines.append(
                f"  goodput {self.attribution['goodput']:.4f}  "
                f"lost {self.attribution['loss_s']:.3f}s "
                f"{self.attribution['buckets']}"
            )
        for r in self.invariants:
            mark = "PASS" if r.ok else "FAIL"
            lines.append(f"  [{mark}] {r.name}: {r.detail}")
        lines.append("RESULT: " + ("PASS" if self.ok else "FAIL"))
        return "\n".join(lines)


class _patched_env:
    """Set env vars for the run, restore the previous values after —
    the harness runs inside long-lived test processes."""

    def __init__(self, values: Dict[str, str]):
        self._values = values
        self._saved: Dict[str, Optional[str]] = {}

    def __enter__(self):
        for k, v in self._values.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = v
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False


def _build_report(
    scenario, rc: int, workdir: str, event_log: str,
    extra_sources: Optional[List[str]] = None,
) -> ChaosRunReport:
    """Collect the run's event stream (master log + any agent-shipped
    logs), assemble the flight-recorder timeline and goodput-loss
    attribution, and wrap everything in a report — the single
    post-run ingestion path both harness flavours share."""
    sources = [event_log] + list(extra_sources or [])
    events = collect_events(sources)
    report = ChaosRunReport(
        scenario=scenario.name,
        seed=scenario.seed,
        rc=rc,
        workdir=workdir,
        event_log=event_log,
        events=events,
        timeline=timeline_from_events(events),
    )
    try:
        report.job_timeline = flight.assemble(events)
        report.attribution = flight.attribute_goodput_loss(
            report.job_timeline
        )
    except Exception:  # noqa: BLE001 - assembly bug must not hide
        # the raw events from the invariants
        logger.exception("flight-recorder assembly failed")
    return report


def default_invariants(
    total_steps: int, ckpt_every: int, workdir: str,
    goodput_named_floor: float = 0.0,
) -> List[Invariant]:
    """The full recovery set — appropriate for scenarios whose fault
    is expected to crash a worker.  Every recovery scenario also
    proves its goodput accounting CLOSES (conservation within 2% per
    incarnation); pass ``goodput_named_floor`` to additionally demand
    that fraction of non-productive time land in named categories."""
    return [
        WorkerRestarted(),
        RendezvousReconverged(),
        BoundedStepLoss(ckpt_interval=ckpt_every),
        TrainingCompleted(total_steps=total_steps),
        NoOrphanProcesses(marker=workdir),
        GoodputConservation(named_floor=goodput_named_floor),
    ]


# scenarios whose fault kills a worker and therefore must show the
# full restart/reconverge/step-loss trail; every other scenario's
# DESIRED outcome is "the job rides it out with no restart at all",
# so only completion + no-orphans apply
RECOVERY_SCENARIOS = frozenset({
    "kill-worker-midstep", "sigterm-worker-midstep",
})


def invariants_for_scenario(
    name: str, total_steps: int, ckpt_every: int, workdir: str,
    disk_every: Optional[int] = None,
) -> List[Invariant]:
    if name == "master-kill-restart-midround":
        # the control-plane recovery trail: journal replay, client
        # resyncs, exactly-once sharding, NO data-plane restarts —
        # and the flight recorder must attribute the outage to
        # master recovery
        return [
            MasterRecovered(),
            HealthyWorkersNotRestarted(),
            NoDuplicateShards(dataset_size=total_steps),
            FinalStepCommitted(),
            GoodputLossAttributed(
                min_attributed_frac=0.5,
                expect_cause=flight.CAUSE_MASTER_RECOVERY,
            ),
            # the ledger's per-incarnation accounting must still
            # close across the control-plane outage (the silent gap
            # lands in idle_unattributed, never breaks conservation)
            GoodputConservation(),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "warm-recovery-cache-hit":
        # the invisible-recovery trail: the full recovery set PLUS
        # the AOT deserialize witnessed from events (the first
        # incarnation's miss wrote the entry this one hits), the
        # compile-cache witness agreeing (status=aot-hit), the
        # measured retrace+aot under a ceiling that separates the
        # regimes, the WHOLE death->first-step cycle bounded, and
        # the budget's phase slices on the assembled timeline.
        # Ceiling calibration (measured on the 2-core gVisor CI
        # box): an AOT hit books retrace=0 and pays only the XLA
        # executable deserialize — 0.4-0.8 s here, ~0.1 s on
        # unsandboxed hardware — while ANY trace costs ≥1.1 s even
        # on an XLA-cache hit, so 1.0 s cleanly proves tracing left
        # the critical path.  The cycle ceiling bounds the whole
        # budget under CI wall-clock noise (typical 1.2-2.0 s,
        # spikes from gofer contention); tighten both via the env
        # knobs on quieter hardware.
        return default_invariants(
            total_steps, ckpt_every, workdir
        ) + [
            CompileCacheHitOnRecovery(),
            AotCacheHitOnRecovery(),
            RetraceBelow(ceiling_s=float(os.environ.get(
                "DLROVER_CHAOS_RETRACE_CEILING_S", "1.0"
            ))),
            RecoveryCycleBelow(ceiling_s=float(os.environ.get(
                "DLROVER_CHAOS_CYCLE_CEILING_S", "3.0"
            ))),
            RecoveryPhasesOnTimeline(),
        ]
    if name == "master-respawn-other-host":
        # the master-kill trail with the host-portability twist: the
        # respawn has a FRESH journal dir, so recovery must be seeded
        # from the storage-tier mirror — and exactly-once sharding
        # must still hold (resync ack-reconciliation covers the
        # mirror's group-commit lag)
        return [
            MasterRecovered(),
            MasterRecoveredFromMirror(),
            EventRecorded("journal_mirror_flush"),
            HealthyWorkersNotRestarted(),
            NoDuplicateShards(dataset_size=total_steps),
            FinalStepCommitted(),
            NoOrphanProcesses(marker=workdir),
        ]
    if name in ("warm-template-import-kill",
                "warm-template-midspawn-kill"):
        return [
            EventRecorded("warm_fork_fallback"),
            TrainingCompleted(total_steps=total_steps),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "goodput-under-scheduled-churn":
        return [
            TrainingCompleted(total_steps=total_steps),
            GoodputAtLeast(0.90),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "shm-corrupt-storage-fallback":
        # full recovery trail PLUS the tier assertion; step loss is
        # bounded by the DISK interval (the shm interval's snapshot
        # was deliberately torn).  ``disk_every`` is the interval the
        # run ACTUALLY used (run_scenario passes its resolved value);
        # standalone callers fall back to the scenario's RUN_OPTIONS
        if not disk_every:
            disk_every = RUN_OPTIONS.get(name, {}).get("disk_every", 0)
        return [
            WorkerRestarted(),
            RendezvousReconverged(),
            BoundedStepLoss(ckpt_interval=max(ckpt_every, disk_every)),
            RestoredFromTier("storage"),
            TrainingCompleted(total_steps=total_steps),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "trainer-hang-detected":
        # the deep-diagnosis trail: evidence captured, hung verdict
        # with stacks + measured stall, ONLY the culprit restarted,
        # bounded loss, completion — and the loss attribution books
        # the stall under the hang bucket with real durations
        return [
            HangDiagnosed(within_s=30.0),
            OnlyCulpritRestarted(culprit_rank=0),
            BoundedStepLoss(ckpt_interval=ckpt_every),
            TrainingCompleted(total_steps=total_steps),
            GoodputLossAttributed(
                min_attributed_frac=0.75,
                expect_cause=flight.CAUSE_HANG,
            ),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "sparse-streaming-reshard-kill":
        # the streaming-reshard trail: the worker died mid-reshard
        # (no train_step in incarnation 0, so no BoundedStepLoss),
        # the replacement replayed the reshard exactly-once against
        # the seeder's digests, and the job still finished + committed
        return [
            WorkerRestarted(),
            KvStreamingReshardReplayed(
                os.path.join(workdir, "seed_kv.json")
            ),
            TrainingCompleted(total_steps=total_steps),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "sparse-kill-restore":
        # the sparse acceptance trail: full recovery set + the loss
        # trajectory equal to the uninterrupted DeepFM control + the
        # kv digests proving rows/freq/slots bit-identical through
        # the cycle — the latter two are what make it SPARSE recovery
        return default_invariants(
            total_steps, ckpt_every, workdir
        ) + [
            LossTrajectoryMatches(
                sparse_reference_losses(total_steps)
            ),
            KvStateRoundTrip(),
        ]
    if name == "rl-rollout-worker-kill":
        # the elastic-RL acceptance trail: full recovery set + the
        # PPO loss trajectory equal to the uninterrupted control
        # (flash restore + deterministic train-step replay + the
        # requeued lease regenerated bit-identically), exactly-once
        # rollout-lease accounting from the master's journaled
        # dispatch/ack trail, and the recovery outage booked to a
        # real cause bucket (rendezvous/restore), not unattributed
        return default_invariants(
            total_steps, ckpt_every, workdir
        ) + [
            LossTrajectoryMatches(rl_reference_losses(total_steps)),
            NoDuplicateShards(
                dataset_size=total_steps, dataset="rl-rollouts"
            ),
            GoodputLossAttributed(min_attributed_frac=0.5),
        ]
    if name == "sparse-spill-io-error":
        # no loss-trajectory assertion: rows stranded on the dead
        # spill disk are LOST by design — the contract is graceful
        # degradation (breaker trips, DRAM rows commit, the restore
        # round-trips exactly what the post-fault export contains)
        return [
            WorkerRestarted(),
            RendezvousReconverged(),
            BoundedStepLoss(ckpt_interval=ckpt_every),
            SpillBreakerTripped(),
            KvStateRoundTrip(),
            TrainingCompleted(total_steps=total_steps),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "serving-replica-kill-midingest":
        # the trainer is undisturbed (completion only); the serving
        # assertions carry the scenario: every served generation was
        # committed with matching digests (no torn serve), committed
        # exactly once, the respawned replica re-based from committed
        # state, and the replica converged on the final generation
        return [
            TrainingCompleted(total_steps=total_steps),
            ServedGenerationCommitted(),
            PublishExactlyOnce(),
            ReplicaReingested(),
            ServingConverged(),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "serving-fleet-replica-kill":
        # the fleet trail, decided from the merged router/replica/
        # load event logs: clean routed traffic throughout BOTH kills
        # (zero failed, zero stale, floor monotonic, zero client-
        # visible failures), the killed member shed within the
        # heartbeat window and its respawn re-admitted, no healthy
        # member restarted, and the respawned router's journal replay
        # equal to the live routing table.  The shed window is the
        # 1 s heartbeat timeout + the 0.4 s sweep cadence + CI slack.
        return [
            RoutedTrafficClean(),
            ReplicaShedAndReadmitted(killed_id=0, window_s=3.0),
            FleetHealthyReplicasNotRestarted(killed_id=0),
            RouterReplayMatchesLive(
                os.path.join(workdir, "router_journal"),
                os.path.join(workdir, "router_table_live.json"),
            ),
            GoodputConservation(),
            NoOrphanProcesses(marker=workdir),
        ]
    if name == "serving-trainer-kill-midpublish":
        # the data-plane recovery trail (the kill lands mid-step) PLUS
        # publish exactly-once across the trainer replacement: the
        # half-published generation never committed, the replacement
        # re-based at a fresh number, the replica kept serving and
        # converged — and the restored trainer's loss trajectory still
        # equals the uninterrupted control (publishing is side-effect-
        # free for training)
        return [
            WorkerRestarted(),
            BoundedStepLoss(ckpt_interval=ckpt_every),
            TrainingCompleted(total_steps=total_steps),
            LossTrajectoryMatches(
                sparse_reference_losses(total_steps)
            ),
            ServedGenerationCommitted(),
            PublishExactlyOnce(),
            ServingConverged(),
            NoOrphanProcesses(marker=workdir),
        ]
    if name in RECOVERY_SCENARIOS:
        # the worker-kill trail must also NAME >=90% of its
        # non-productive time (death witness -> rendezvous ->
        # restore -> first step), not dump it in idle_unattributed
        return default_invariants(
            total_steps, ckpt_every, workdir,
            goodput_named_floor=0.9,
        )
    return [
        TrainingCompleted(total_steps=total_steps),
        NoOrphanProcesses(marker=workdir),
    ]


def run_scenario(
    scenario,
    workdir: str,
    total_steps: Optional[int] = None,
    ckpt_every: Optional[int] = None,
    max_restarts: int = 2,
    monitor_interval: float = 0.3,
    warm_restart: bool = False,
    invariants: Optional[List[Invariant]] = None,
    disk_every: Optional[int] = None,
    step_sleep: Optional[float] = None,
    extra_env: Optional[Dict[str, str]] = None,
    _ceiling_budget: Optional[int] = None,
) -> ChaosRunReport:
    """Run ``scenario`` against a fresh single-node mini-cluster under
    ``workdir`` and evaluate the invariants.  With ``invariants=None``
    the set is chosen by scenario name (recovery scenarios get the
    full restart trail, ride-it-out scenarios completion+no-orphans);
    pass ``invariants=[]`` to skip checking entirely.

    When the run otherwise succeeded (rc == 0) but SOME invariants
    failed and every failure is ceiling-class (a measured duration vs
    a wall-clock ceiling — ``RetraceBelow``/``RecoveryCycleBelow``),
    the scenario is re-measured ONCE in a fresh sub-workdir and the
    second report returned: a 1.016 s trip of a 1.0 s ceiling on a
    sandboxed CI box is measurement noise, not a regression, while a
    real regression trips both runs.  ``DLROVER_CHAOS_CEILING_REMEASURE``
    sets the retry budget (default 1; 0 disables).

    ``total_steps``/``ckpt_every``/``disk_every`` (durable mid-run
    saves), ``step_sleep`` (stretch the toy loop for wall-clock
    windows), ``warm_restart`` and ``extra_env`` default to the
    scenario's entry in :data:`scenarios.RUN_OPTIONS`, so named
    scenarios run correctly from the CLI and tests alike."""
    scenario = load_scenario(scenario)
    opts = RUN_OPTIONS.get(scenario.name, {})
    if total_steps is None:
        total_steps = int(opts.get("total_steps", 10))
    if ckpt_every is None:
        ckpt_every = int(opts.get("ckpt_every", 2))
    if disk_every is None:
        disk_every = int(opts.get("disk_every", 0))
    if step_sleep is None:
        step_sleep = float(opts.get("step_sleep", 0.0))
    warm_restart = warm_restart or bool(opts.get("warm_restart"))
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, "chaos_scenario.json")
    with open(spec_path, "w") as f:
        json.dump(scenario.to_dict(), f, indent=2)
    script = os.path.join(workdir, "chaos_train.py")
    with open(script, "w") as f:
        f.write(TRAIN_SCRIPTS[opts.get("train_script", "default")])
    event_log = os.path.join(workdir, "events.jsonl")
    ckpt_dir = os.path.join(workdir, "ckpt")
    if opts.get("seed_kv_world"):
        # pre-seed a committed old-world sparse checkpoint so the
        # job's FIRST restore is a cross-world streaming reshard;
        # the seeder's digest sums land in seed_kv.json for the
        # exactly-once invariant
        seed_sparse_world_checkpoint(
            ckpt_dir,
            world=int(opts["seed_kv_world"]),
            out_json=os.path.join(workdir, "seed_kv.json"),
        )

    env = {
        _chaos.CHAOS_ENV: spec_path,
        EVENT_LOG_ENV: event_log,
        TOTAL_STEPS_ENV: str(total_steps),
        CKPT_EVERY_ENV: str(ckpt_every),
        "DLROVER_SHARED_DIR": os.path.join(workdir, "sock"),
        "DLROVER_METRICS_FILE": os.path.join(workdir, "metrics.json"),
        # isolation: an ambient master address (a previous in-process
        # run, an outer job) must not hijack this mini-cluster — empty
        # means "spawn a fresh local master"
        "DLROVER_MASTER_ADDR": "",
    }
    if disk_every:
        env[DISK_EVERY_ENV] = str(disk_every)
    if step_sleep:
        env[STEP_SLEEP_ENV] = str(step_sleep)
    if opts.get("shard_dataset"):
        # shard-driven loop: one sample per shard, one shard per step
        env[SHARD_DATASET_ENV] = str(total_steps)
    if opts.get("compile_cache"):
        # workdir-scoped persistent compile cache: incarnation 0's
        # compile deterministically pre-populates the replacement's
        # retrace, with no cross-run pollution from a tmpdir default
        env["DLROVER_COMPILE_CACHE_DIR"] = os.path.join(
            workdir, "jax_cache"
        )
    if opts.get("journal_mirror"):
        # storage-tier journal mirror under the run's workdir; the
        # master (and its respawns) read this env at construction
        env["DLROVER_MASTER_JOURNAL_MIRROR_DIR"] = os.path.join(
            workdir, "journal_mirror"
        )
    env.update(opts.get("extra_env", {}))
    if extra_env:
        env.update(extra_env)
    argv = [
        "--nproc_per_node=1",
        f"--max_restarts={max_restarts}",
        f"--monitor_interval={monitor_interval}",
    ]
    if warm_restart:
        argv.append("--warm-restart")
    argv += [script, ckpt_dir]

    from dlrover_tpu import run as tpurun

    with _patched_env(env):
        # arm in-process too: the agent (and its saver/monitors) runs
        # in THIS process, and its hook points must see the scenario
        _chaos.install(scenario)
        try:
            rc = tpurun.main(argv)
        finally:
            _chaos.uninstall()

    report = _build_report(scenario, rc, workdir, event_log)
    checks = (
        invariants if invariants is not None
        else invariants_for_scenario(
            scenario.name, total_steps, ckpt_every, workdir,
            disk_every=disk_every,
        )
    )
    for inv in checks:
        try:
            report.invariants.append(
                inv.check(report.events, report)
            )
        except Exception as e:  # noqa: BLE001 - a checker bug is a FAIL
            logger.exception("invariant %s crashed", inv.name)
            report.invariants.append(
                InvariantResult(inv.name, False, f"checker crashed: {e}")
            )

    if _ceiling_budget is None:
        _ceiling_budget = int(os.environ.get(
            "DLROVER_CHAOS_CEILING_REMEASURE", "1"
        ))
    failed = [r for r in report.invariants if not r.ok]
    by_name = {inv.name: inv for inv in checks}
    if (
        failed and report.rc == 0 and _ceiling_budget > 0
        and all(
            getattr(by_name.get(r.name), "ceiling_class", False)
            for r in failed
        )
    ):
        logger.warning(
            "ceiling-class trip(s) only (%s); re-measuring once in a "
            "fresh workdir",
            ", ".join(f"{r.name}: {r.detail}" for r in failed),
        )
        return run_scenario(
            scenario,
            os.path.join(workdir, "ceiling_remeasure"),
            total_steps=total_steps,
            ckpt_every=ckpt_every,
            max_restarts=max_restarts,
            monitor_interval=monitor_interval,
            warm_restart=warm_restart,
            invariants=invariants,
            disk_every=disk_every,
            step_sleep=step_sleep,
            extra_env=extra_env,
            _ceiling_budget=_ceiling_budget - 1,
        )
    return report


def run_serving_scenario(
    scenario,
    workdir: str,
    total_steps: Optional[int] = None,
    max_replica_respawns: int = 1,
    replica_lookup_batch: int = 256,
    converge_timeout_s: float = 20.0,
    invariants: Optional[List[Invariant]] = None,
    **kwargs,
) -> ChaosRunReport:
    """Run a train-to-serve scenario: the single-node mini-cluster
    (trainer publishing serving generations) PLUS a supervised
    read-only replica subprocess (``python -m dlrover_tpu.serving``)
    ingesting them while driving lookup traffic.

    The replica gets its OWN event log (merged into the report like
    an agent-shipped log) and the scenario spec via ``DLROVER_CHAOS``
    — rules targeting it select on ``DLROVER_SERVING_ROLE=replica``.
    A replica that dies is respawned up to ``max_replica_respawns``
    times with ``DLROVER_SERVING_RESPAWNED=1`` (the schedule's
    env-equals guard against re-firing, and the ``respawned`` stamp
    on its events).  After training finishes the runner waits for the
    replica to converge on the final committed generation, then stops
    it via the stop file before the orphan scan runs."""
    scenario = load_scenario(scenario)
    opts = RUN_OPTIONS.get(scenario.name, {})
    os.makedirs(workdir, exist_ok=True)
    serving_dir = os.path.join(workdir, "serving")
    spec_path = os.path.join(workdir, "chaos_scenario.json")
    with open(spec_path, "w") as f:
        json.dump(scenario.to_dict(), f, indent=2)
    replica_log = os.path.join(workdir, "serving_events.jsonl")
    stop_file = os.path.join(workdir, "serving_stop")

    replica_env = dict(os.environ)
    replica_env.update(opts.get("extra_env", {}))
    replica_env.update({
        _chaos.CHAOS_ENV: spec_path,
        EVENT_LOG_ENV: replica_log,
        "DLROVER_SERVING_ROLE": "replica",
        "DLROVER_SERVING_RESPAWNED": "",
        # the replica needs no master and must not inherit one
        "DLROVER_MASTER_ADDR": "",
    })
    cmd = [
        sys.executable, "-m", "dlrover_tpu.serving",
        "--dir", serving_dir,
        "--poll", "0.1",
        "--batch", str(replica_lookup_batch),
        "--key-space", "4000",
        "--stats-every", "0.5",
        "--stop-file", stop_file,
    ]
    state = {"proc": None, "respawns": 0, "stopping": False}

    def _spawn(respawned: bool):
        env = dict(replica_env)
        if respawned:
            env["DLROVER_SERVING_RESPAWNED"] = "1"
        state["proc"] = subprocess.Popen(  # noqa: S603
            cmd, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def _supervise():
        while not state["stopping"]:
            proc = state["proc"]
            if proc is None:
                return
            rc = proc.wait()
            if state["stopping"] or rc == 0:
                return
            if state["respawns"] >= max_replica_respawns:
                logger.warning(
                    "serving replica died rc=%s with no respawn "
                    "budget left", rc,
                )
                return
            state["respawns"] += 1
            logger.warning(
                "serving replica died rc=%s; respawning (%d/%d)",
                rc, state["respawns"], max_replica_respawns,
            )
            _spawn(respawned=True)

    _spawn(respawned=False)
    supervisor = threading.Thread(
        target=_supervise, daemon=True, name="serving-replica-sup"
    )
    supervisor.start()

    try:
        base = run_scenario(
            scenario, workdir,
            total_steps=total_steps,
            invariants=[],
            extra_env={"DLROVER_SERVING_DIR": serving_dir},
            **kwargs,
        )
        # let the replica converge on the final committed generation
        # before stopping it (the freshness the invariants assert)
        from dlrover_tpu.serving.publisher import (
            committed_generation,
        )

        deadline = time.time() + converge_timeout_s
        target = committed_generation(serving_dir)
        while time.time() < deadline and target > 0:
            try:
                ingested = {
                    e.get("generation")
                    for e in collect_events([replica_log])
                    if e.get("type") == "serving_ingest"
                }
            except OSError:
                ingested = set()
            if target in ingested:
                break
            time.sleep(0.25)
    finally:
        state["stopping"] = True
        with open(stop_file, "w") as f:
            f.write("stop")
        proc = state["proc"]
        if proc is not None:
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        supervisor.join(timeout=5.0)

    report = _build_report(
        scenario, base.rc, workdir, base.event_log,
        extra_sources=[replica_log],
    )
    resolved_steps = total_steps if total_steps is not None else int(
        opts.get("total_steps", 10)
    )
    checks = (
        invariants if invariants is not None
        else invariants_for_scenario(
            scenario.name, resolved_steps,
            int(opts.get("ckpt_every", 2)), workdir,
        )
    )
    for inv in checks:
        try:
            report.invariants.append(
                inv.check(report.events, report)
            )
        except Exception as e:  # noqa: BLE001 - a checker bug is a FAIL
            logger.exception("invariant %s crashed", inv.name)
            report.invariants.append(
                InvariantResult(inv.name, False, f"checker crashed: {e}")
            )
    return report


def run_serving_fleet_scenario(
    scenario,
    workdir: str,
    pool_size: Optional[int] = None,
    generations: Optional[int] = None,
    publish_every_s: Optional[float] = None,
    load_streams: Optional[int] = None,
    lookup_floor_ms: Optional[float] = None,
    heartbeat_s: float = 0.25,
    heartbeat_timeout_s: float = 1.0,
    converge_timeout_s: float = 30.0,
    max_router_respawns: int = 1,
    invariants: Optional[List[Invariant]] = None,
) -> ChaosRunReport:
    """Run a serving-FLEET scenario: an in-process publisher shipping
    embedding generations (bases forced mid-run via ``compact_every``
    so drained re-bases land under load), a supervised
    :class:`~dlrover_tpu.serving.pool.ReplicaPool` of replica
    subprocesses, a ``python -m dlrover_tpu.serving.router``
    subprocess fronting them (journaled membership; respawned on
    death with ``DLROVER_SERVING_RESPAWNED=1`` onto the SAME port so
    clients reconnect), and a
    :class:`~dlrover_tpu.fleet.lookup_load.LookupLoadHarness` driving
    real routed lookups throughout.

    The RUNNER process never arms the scenario — only the replica and
    router subprocesses receive ``DLROVER_CHAOS``, so kill rules
    select their targets via ``DLROVER_SERVING_ROLE`` /
    ``DLROVER_SERVING_REPLICA_ID`` env guards.  All subprocess event
    logs are merged into the report; before teardown the runner
    snapshots the LIVE routing table (``router_table_live.json``) for
    the journal-replay-determinism invariant and emits the load
    harness's client-side aggregate as a ``serving_lookup_stats``
    event (``replica="load"``), so every verdict decides from events
    alone."""
    import numpy as np

    from dlrover_tpu.checkpoint.sparse import SparseStateAdapter
    from dlrover_tpu.common.comm import MessageClient
    from dlrover_tpu.fleet.lookup_load import LookupLoadHarness
    from dlrover_tpu.ops.kv_variable import KvVariable
    from dlrover_tpu.serving.messages import RoutingTableRequest
    from dlrover_tpu.serving.pool import ReplicaPool
    from dlrover_tpu.serving.publisher import (
        EmbeddingPublisher,
        committed_generation,
    )
    from dlrover_tpu.telemetry.events import emit_event

    scenario = load_scenario(scenario)
    opts = RUN_OPTIONS.get(scenario.name, {})
    if pool_size is None:
        pool_size = int(opts.get("pool_size", 2))
    if generations is None:
        generations = int(opts.get("generations", 10))
    if publish_every_s is None:
        publish_every_s = float(opts.get("publish_every_s", 0.35))
    if load_streams is None:
        load_streams = int(opts.get("load_streams", 4))
    if lookup_floor_ms is None:
        lookup_floor_ms = float(opts.get("lookup_floor_ms", 2.0))
    os.makedirs(workdir, exist_ok=True)
    serving_dir = os.path.join(workdir, "serving")
    spec_path = os.path.join(workdir, "chaos_scenario.json")
    with open(spec_path, "w") as f:
        json.dump(scenario.to_dict(), f, indent=2)
    event_log = os.path.join(workdir, "events.jsonl")
    router_log = os.path.join(workdir, "events_router.jsonl")
    journal_dir = os.path.join(workdir, "router_journal")
    router_port_file = os.path.join(workdir, "router.port")
    router_stop = os.path.join(workdir, "router.stop")
    live_json = os.path.join(workdir, "router_table_live.json")

    router_env = dict(os.environ)
    router_env.update(opts.get("extra_env", {}))
    router_env.update({
        _chaos.CHAOS_ENV: spec_path,
        EVENT_LOG_ENV: router_log,
        "DLROVER_SERVING_ROLE": "router",
        "DLROVER_SERVING_RESPAWNED": "",
        "DLROVER_MASTER_ADDR": "",
    })
    state = {"proc": None, "respawns": 0, "stopping": False,
             "port": 0}

    def _spawn_router(respawned: bool):
        env = dict(router_env)
        if respawned:
            env["DLROVER_SERVING_RESPAWNED"] = "1"
        try:
            os.remove(router_port_file)
        except OSError:
            pass
        state["proc"] = subprocess.Popen(  # noqa: S603
            [
                sys.executable, "-m", "dlrover_tpu.serving.router",
                "--journal-dir", journal_dir,
                # respawns rebind the SAME port so every client's
                # retry envelope reconnects instead of failing over
                "--port", str(state["port"]),
                "--port-file", router_port_file,
                "--stop-file", router_stop,
                "--heartbeat-timeout", str(heartbeat_timeout_s),
                "--min-available", "1",
                "--stats-every", "0.4",
            ],
            env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def _wait_router_port(timeout_s: float = 20.0) -> int:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            try:
                with open(router_port_file) as f:
                    return int(f.read().strip())
            except (OSError, ValueError):
                time.sleep(0.05)
        raise TimeoutError("router never wrote its port file")

    def _supervise_router():
        while not state["stopping"]:
            proc = state["proc"]
            if proc is None:
                return
            rc = proc.wait()
            if state["stopping"] or rc == 0:
                return
            if state["respawns"] >= max_router_respawns:
                logger.warning(
                    "router died rc=%s with no respawn budget", rc
                )
                return
            state["respawns"] += 1
            logger.warning(
                "router died rc=%s; respawning (%d/%d)",
                rc, state["respawns"], max_router_respawns,
            )
            _spawn_router(respawned=True)

    rc = 0
    pool = None
    ctl = None
    pool_logs: List[str] = []
    with _patched_env({
        EVENT_LOG_ENV: event_log,
        "DLROVER_MASTER_ADDR": "",
    }):
        try:
            # -- publisher state (in-process; never a kill target) --
            rows, dim = 4000, 16
            rng = np.random.default_rng(scenario.seed)
            table = KvVariable(
                dim, initial_capacity=rows * 2, name="emb"
            )
            table.enable_dirty_tracking()
            table.insert(
                np.arange(rows, dtype=np.int64),
                rng.normal(size=(rows, dim)).astype(np.float32),
            )
            adapter = SparseStateAdapter(digest=True).register_table(
                table
            )
            pub = EmbeddingPublisher(
                adapter, serving_dir,
                compact_every=int(opts.get("compact_every", 3)),
            )
            pub.publish(step=0)

            _spawn_router(respawned=False)
            state["port"] = _wait_router_port()
            supervisor = threading.Thread(
                target=_supervise_router, daemon=True,
                name="router-sup",
            )
            supervisor.start()
            router_addr = f"127.0.0.1:{state['port']}"

            pool = ReplicaPool(
                serving_dir, os.path.join(workdir, "pool"),
                router_addr=router_addr, size=pool_size,
                heartbeat_s=heartbeat_s,
                lookup_floor_ms=lookup_floor_ms,
                stats_every_s=0.5, max_respawns=1,
                extra_env={_chaos.CHAOS_ENV: spec_path},
            )
            pool_logs = pool.event_logs()
            pool.wait_ports(30.0)

            # patient control client: rides out the router respawn
            ctl = MessageClient(
                router_addr, node_id=-3, node_type="fleet-runner",
                timeout=15.0, retries=8, backoff_base=0.1,
                backoff_max=1.0, resync_timeout=0.0,
            )

            def _table_view():
                resp = ctl.get(RoutingTableRequest())
                live = [
                    m for m in resp.members.values()
                    if not m.get("removed")
                ]
                return resp, live

            deadline = time.time() + 20.0
            while time.time() < deadline:
                _, live = _table_view()
                if len(live) >= pool_size and all(
                    int(m.get("generation", -1)) >= 0 for m in live
                ):
                    break
                time.sleep(0.1)

            load = LookupLoadHarness(
                router_addr, streams=load_streams, batch=128,
                key_space=rows, timeout_s=30.0, retries=8,
                seed=scenario.seed,
            )
            load.start()
            try:
                for g in range(1, generations + 1):
                    touched = rng.choice(
                        rows, size=256, replace=False
                    ).astype(np.int64)
                    table.scatter_add(
                        touched,
                        (rng.normal(size=(len(touched), dim)) * 0.01)
                        .astype(np.float32),
                    )
                    pub.publish(step=g)
                    time.sleep(publish_every_s)

                # convergence: the whole pool (incl. the respawned
                # member) admitted at the final committed generation
                target = committed_generation(serving_dir)
                deadline = time.time() + converge_timeout_s
                while time.time() < deadline:
                    resp, live = _table_view()
                    if resp.generation_floor >= target and live and \
                            all(
                                int(m.get("generation", -1)) >= target
                                for m in live
                            ):
                        break
                    time.sleep(0.2)
                # one more beat of routed traffic at the converged
                # floor so post-respawn windows carry real counts
                time.sleep(0.6)
            finally:
                load.stop()

            summary = load.summary()
            emit_event(
                "serving_lookup_stats",
                count=int(summary["lookups"]),
                p50_ms=summary.get("p50_ms", 0.0),
                p99_ms=summary.get("p99_ms", 0.0),
                qps=summary.get("qps", 0.0),
                window_s=summary.get("wall_s", 0.0),
                generation=int(summary["max_generation"]),
                replica="load",
                failed=int(summary["failed"]),
                streams=int(summary["streams"]),
            )
            resp, _ = _table_view()
            with open(live_json, "w") as f:
                json.dump({
                    "members": list(resp.members.values()),
                    "generation_floor": int(resp.generation_floor),
                    "journal_seq": int(resp.journal_seq),
                }, f, indent=2)
        except Exception:  # noqa: BLE001 - report carries the verdict
            logger.exception("serving-fleet run failed")
            rc = 1
        finally:
            if ctl is not None:
                ctl.close()
            if pool is not None:
                pool.stop()
            state["stopping"] = True
            with open(router_stop, "w") as f:
                f.write("stop")
            proc = state["proc"]
            if proc is not None:
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.terminate()
                    try:
                        proc.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()

    report = _build_report(
        scenario, rc, workdir, event_log,
        extra_sources=[router_log] + pool_logs,
    )
    checks = (
        invariants if invariants is not None
        else invariants_for_scenario(
            scenario.name, generations, 2, workdir
        )
    )
    for inv in checks:
        try:
            report.invariants.append(
                inv.check(report.events, report)
            )
        except Exception as e:  # noqa: BLE001 - a checker bug is a FAIL
            logger.exception("invariant %s crashed", inv.name)
            report.invariants.append(
                InvariantResult(inv.name, False, f"checker crashed: {e}")
            )
    return report


def default_multinode_invariants(
    nnodes: int, total_steps: int, workdir: str,
    faulted_rank: Optional[int] = None,
) -> List[Invariant]:
    """Per-node completion for every rank; when one rank carries the
    fault, additionally pin the blast radius: injections confined to
    it and no restart of the healthy ranks."""
    checks: List[Invariant] = [
        NodeCompletedSteps(rank, total_steps)
        for rank in range(nnodes)
    ]
    if faulted_rank is not None:
        checks.append(InjectionsOnlyOnNode(faulted_rank))
        checks.extend(
            NoRestartForNode(rank)
            for rank in range(nnodes) if rank != faulted_rank
        )
    checks.append(NoOrphanProcesses(marker=workdir))
    return checks


def run_scenario_multinode(
    scenario,
    workdir: str,
    nnodes: int = 2,
    total_steps: Optional[int] = None,
    ckpt_every: Optional[int] = None,
    max_restarts: int = 2,
    monitor_interval: float = 0.3,
    warm_restart: bool = False,
    invariants: Optional[List[Invariant]] = None,
    faulted_rank: Optional[int] = None,
    timeout: float = 240.0,
) -> ChaosRunReport:
    """Drive ``nnodes`` REAL agent processes (each a full ``tpurun``
    supervision tree with its own trainer) against one shared,
    journal-backed master subprocess — the harness shape the
    node-subset partition and multi-node recovery scenarios need.
    Every process arms the same scenario via ``DLROVER_CHAOS``; rules
    target a subset with ``env_equals: {"DLROVER_NODE_RANK": ...}``.
    One event log collects the whole job, and the invariants decide
    from it alone."""
    from dlrover_tpu.common.comm import addr_connected, find_free_port

    scenario = load_scenario(scenario)
    opts = RUN_OPTIONS.get(scenario.name, {})
    if total_steps is None:
        total_steps = int(opts.get("total_steps", 10))
    if ckpt_every is None:
        ckpt_every = int(opts.get("ckpt_every", 2))
    step_sleep = float(opts.get("step_sleep", 0.0))
    warm_restart = warm_restart or bool(opts.get("warm_restart"))
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, "chaos_scenario.json")
    with open(spec_path, "w") as f:
        json.dump(scenario.to_dict(), f, indent=2)
    script = os.path.join(workdir, "chaos_train.py")
    with open(script, "w") as f:
        f.write(CHAOS_TRAIN_SCRIPT)
    # event shipping, the deployment shape: the master writes its own
    # log; every agent (and the trainers it spawns) writes a per-node
    # log, and the aggregate glob folds them into the master's
    # /timeline + the post-run assembly — the event analog of the
    # DLROVER_METRICS_AGGREGATE_GLOB textfile aggregation
    event_log = os.path.join(workdir, "events.jsonl")
    agent_event_glob = os.path.join(workdir, "events_node*.jsonl")

    base_env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        **{
            _chaos.CHAOS_ENV: spec_path,
            EVENT_LOG_ENV: event_log,
            EVENTS_AGGREGATE_ENV: agent_event_glob,
            TOTAL_STEPS_ENV: str(total_steps),
            CKPT_EVERY_ENV: str(ckpt_every),
        },
    )
    if step_sleep:
        base_env[STEP_SLEEP_ENV] = str(step_sleep)
    if opts.get("shard_dataset"):
        # same contract as the single-node path: one sample per
        # shard, one shard per step — without it a shard-driven
        # scenario would silently run the plain loop and inject
        # nothing
        base_env[SHARD_DATASET_ENV] = str(total_steps)
    base_env.update(opts.get("extra_env", {}))
    # the framework must be importable in the subprocesses even when
    # not pip-installed (the caller may run from anywhere)
    import dlrover_tpu

    pkg_root = os.path.dirname(os.path.dirname(dlrover_tpu.__file__))
    prev_pp = base_env.get("PYTHONPATH", "")
    if pkg_root not in prev_pp.split(os.pathsep):
        base_env["PYTHONPATH"] = (
            f"{pkg_root}{os.pathsep}{prev_pp}" if prev_pp else pkg_root
        )
    port = find_free_port()
    addr = f"127.0.0.1:{port}"
    master_env = dict(
        base_env,
        DLROVER_MASTER_JOURNAL_DIR=os.path.join(
            workdir, "master_journal"
        ),
        DLROVER_RESTART_COUNT="0",
    )
    master = subprocess.Popen(  # noqa: S603
        [
            sys.executable, "-m", "dlrover_tpu.master.main",
            "--port", str(port), "--node_num", str(nnodes),
        ],
        env=master_env,
    )
    agents: List[subprocess.Popen] = []
    logs: List = []
    rc = 0
    try:
        deadline = time.time() + 30
        while not addr_connected(addr):
            if master.poll() is not None or time.time() > deadline:
                raise RuntimeError("multinode master failed to start")
            time.sleep(0.2)
        for rank in range(nnodes):
            env = dict(
                base_env,
                DLROVER_MASTER_ADDR=addr,
                **{EVENT_LOG_ENV: os.path.join(
                    workdir, f"events_node{rank}.jsonl"
                )},
                DLROVER_NODE_RANK=str(rank),
                DLROVER_NODE_ID=str(rank),
                DLROVER_SHARED_DIR=os.path.join(
                    workdir, f"sock{rank}"
                ),
                DLROVER_METRICS_FILE=os.path.join(
                    workdir, f"metrics_{rank}.json"
                ),
            )
            out = open(
                os.path.join(workdir, f"agent{rank}.log"), "w"
            )
            logs.append(out)
            argv = [
                sys.executable, "-m", "dlrover_tpu.run",
                "--nnodes", str(nnodes),
                "--nproc_per_node", "1",
                f"--max_restarts={max_restarts}",
                f"--monitor_interval={monitor_interval}",
                "--node_rank", str(rank),
            ]
            if warm_restart:
                argv.append("--warm-restart")
            argv += [script, os.path.join(workdir, f"ckpt{rank}")]
            agents.append(subprocess.Popen(  # noqa: S603
                argv, env=env, stdout=out, stderr=subprocess.STDOUT,
            ))
        deadline = time.time() + timeout
        for p in agents:
            try:
                p.wait(timeout=max(1.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                rc = rc or 124
            rc = rc or (p.returncode or 0)
    finally:
        for p in agents:
            if p.poll() is None:
                p.kill()
                p.wait()
        if master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        for out in logs:
            try:
                out.close()
            except OSError:
                pass

    report = _build_report(
        scenario, rc, workdir, event_log,
        extra_sources=[agent_event_glob],
    )
    checks = (
        invariants if invariants is not None
        else default_multinode_invariants(
            nnodes, total_steps, workdir, faulted_rank=faulted_rank
        )
    )
    for inv in checks:
        try:
            report.invariants.append(
                inv.check(report.events, report)
            )
        except Exception as e:  # noqa: BLE001 - a checker bug is a FAIL
            logger.exception("invariant %s crashed", inv.name)
            report.invariants.append(
                InvariantResult(inv.name, False, f"checker crashed: {e}")
            )
    return report


def elastic_resize_invariants(
    nnodes: int, total_steps: int, disk_every: int, workdir: str,
    dim: int = 64,
) -> List[Invariant]:
    """The elastic-resize acceptance set: the completed world really
    changed N -> N-1 -> N, the cross-world restores came RESHARDED
    from the committed storage tier, every reported loss matches the
    uninterrupted control, per-restart step loss is bounded by the
    durable interval, dataset shards stay exactly-once, the final
    step commits, the resize phase breakdown is on the timeline, and
    the goodput loss is booked under the resize cause."""
    return [
        WorldSizeTrajectory([nnodes, nnodes - 1, nnodes]),
        EventRecorded("resize_decision", min_count=2),
        RestoredFromTier("storage"),
        LossTrajectoryMatches(
            resize_reference_losses(total_steps, dim=dim)
        ),
        BoundedStepLossPerRestart(interval=disk_every),
        NoDuplicateShards(dataset_size=total_steps),
        FinalStepCommitted(),
        ResizePhasesOnTimeline(min_resizes=2),
        GoodputLossAttributed(
            min_attributed_frac=0.5,
            expect_cause=flight.CAUSE_RESIZE,
        ),
        # overlapping incarnations (old world draining while the new
        # world rendezvouses) must still each close their books
        GoodputConservation(),
        NoOrphanProcesses(marker=workdir),
    ]


def sparse_resize_invariants(
    nnodes: int, total_steps: int, disk_every: int, workdir: str,
    dim: int = 64,
) -> List[Invariant]:
    """The sparse elastic-resize acceptance set: everything the dense
    resize proves about the world trajectory / storage-tier reshard /
    loss control, PLUS exactly-once redistribution of the hash-table
    rows across both world changes (kv digests additive across
    disjoint shards)."""
    return [
        WorldSizeTrajectory([nnodes, nnodes - 1, nnodes]),
        EventRecorded("resize_decision", min_count=2),
        RestoredFromTier("storage"),
        LossTrajectoryMatches(
            resize_reference_losses(total_steps, dim=dim)
        ),
        BoundedStepLossPerRestart(interval=disk_every),
        KvReshardExactlyOnce(min_reshards=2),
        FinalStepCommitted(),
        GoodputConservation(),
        NoOrphanProcesses(marker=workdir),
    ]


def run_elastic_resize_scenario(
    scenario,
    workdir: str,
    nnodes: int = 2,
    min_nodes: int = 1,
    kill_rank: Optional[int] = None,
    total_steps: Optional[int] = None,
    disk_every: Optional[int] = None,
    max_restarts: int = 3,
    monitor_interval: float = 0.3,
    invariants: Optional[List[Invariant]] = None,
    rejoin_after_steps: int = 2,
    timeout: float = 240.0,
) -> ChaosRunReport:
    """Drive the elastic world-resize churn: ``nnodes`` real tpurun
    agents against a ``min_nodes``-floored master, ALL sharing one
    checkpoint directory (the shared filesystem that makes cross-host
    shard redistribution possible).  The scenario's ``kill_node`` rule
    takes one agent's whole supervision tree down mid-run; the master
    shrinks the world and the survivor reshards-restores.  Once the
    shrunken world has made ``rejoin_after_steps`` steps, the harness
    plays the cluster scheduler and starts a REPLACEMENT agent for the
    lost rank (fresh shm namespace — a new host — and
    ``DLROVER_AGENT_RESPAWNED=1`` so seeded rules never re-fire),
    which grows the world back.  Invariants then decide everything
    from the telemetry event log."""
    from dlrover_tpu.common.comm import addr_connected, find_free_port

    scenario = load_scenario(scenario)
    opts = RUN_OPTIONS.get(scenario.name, {})
    if total_steps is None:
        total_steps = int(opts.get("total_steps", 24))
    if disk_every is None:
        disk_every = int(opts.get("disk_every", 3))
    step_sleep = float(opts.get("step_sleep", 0.0))
    if kill_rank is None:
        kill_rank = nnodes - 1
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, "chaos_scenario.json")
    with open(spec_path, "w") as f:
        json.dump(scenario.to_dict(), f, indent=2)
    script = os.path.join(workdir, "resize_train.py")
    with open(script, "w") as f:
        f.write(TRAIN_SCRIPTS[opts.get("train_script", "resize")])
    event_log = os.path.join(workdir, "events.jsonl")
    agent_event_glob = os.path.join(workdir, "events_node*.jsonl")
    ckpt_dir = os.path.join(workdir, "ckpt")  # SHARED across nodes

    base_env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        **{
            _chaos.CHAOS_ENV: spec_path,
            EVENT_LOG_ENV: event_log,
            EVENTS_AGGREGATE_ENV: agent_event_glob,
            TOTAL_STEPS_ENV: str(total_steps),
            DISK_EVERY_ENV: str(disk_every),
        },
    )
    if step_sleep:
        base_env[STEP_SLEEP_ENV] = str(step_sleep)
    # tail-stretch (see RESIZE_TRAIN_SCRIPT): below-full-strength
    # incarnations crawl so the survivor cannot finish the job before
    # the grow-back decision lands on a slow box
    shrunk_sleep = float(opts.get("shrunk_step_sleep", 0.0))
    if shrunk_sleep:
        base_env["DLROVER_CHAOS_NNODES"] = str(nnodes)
        base_env["DLROVER_CHAOS_SHRUNK_STEP_SLEEP"] = str(shrunk_sleep)
    if opts.get("shard_dataset"):
        base_env[SHARD_DATASET_ENV] = str(total_steps)
    base_env.update(opts.get("extra_env", {}))
    import dlrover_tpu

    pkg_root = os.path.dirname(os.path.dirname(dlrover_tpu.__file__))
    prev_pp = base_env.get("PYTHONPATH", "")
    if pkg_root not in prev_pp.split(os.pathsep):
        base_env["PYTHONPATH"] = (
            f"{pkg_root}{os.pathsep}{prev_pp}" if prev_pp else pkg_root
        )
    port = find_free_port()
    addr = f"127.0.0.1:{port}"
    master_env = dict(
        base_env,
        DLROVER_MASTER_JOURNAL_DIR=os.path.join(
            workdir, "master_journal"
        ),
        DLROVER_RESTART_COUNT="0",
    )
    master = subprocess.Popen(  # noqa: S603
        [
            sys.executable, "-m", "dlrover_tpu.master.main",
            "--port", str(port), "--node_num", str(nnodes),
            "--min_nodes", str(min_nodes),
        ],
        env=master_env,
    )

    def agent_env(rank: int, respawn: bool) -> Dict[str, str]:
        # a respawned rank is a REPLACEMENT host: a fresh IPC/shm
        # namespace (its predecessor's stale shm must not exist on a
        # new VM) and the respawn marker protecting it from seeded
        # rules
        suffix = "b" if respawn else ""
        env = dict(
            base_env,
            DLROVER_MASTER_ADDR=addr,
            **{EVENT_LOG_ENV: os.path.join(
                workdir, f"events_node{rank}.jsonl"
            )},
            DLROVER_NODE_RANK=str(rank),
            DLROVER_NODE_ID=str(rank),
            DLROVER_SHARED_DIR=os.path.join(
                workdir, f"sock{rank}{suffix}"
            ),
            DLROVER_METRICS_FILE=os.path.join(
                workdir, f"metrics_{rank}{suffix}.json"
            ),
        )
        if respawn:
            env["DLROVER_AGENT_RESPAWNED"] = "1"
        return env

    def spawn_agent(rank: int, respawn: bool, logs: List):
        out = open(
            os.path.join(
                workdir,
                f"agent{rank}{'_respawn' if respawn else ''}.log",
            ),
            "w",
        )
        logs.append(out)
        argv = [
            sys.executable, "-m", "dlrover_tpu.run",
            "--nnodes", f"{min_nodes}:{nnodes}",
            "--nproc_per_node", "1",
            f"--max_restarts={max_restarts}",
            f"--monitor_interval={monitor_interval}",
            "--node_rank", str(rank),
            script, ckpt_dir,
        ]
        return subprocess.Popen(  # noqa: S603
            argv, env=agent_env(rank, respawn),
            stdout=out, stderr=subprocess.STDOUT,
        )

    def shrunken_world_stepping() -> bool:
        """The respawn trigger, from the event log alone: the world
        reconverged at nnodes-1 AND made rejoin_after_steps steps
        since — replacement capacity arriving mid-recovery would
        race the shrink and prove nothing."""
        try:
            ev = collect_events([
                event_log,
                os.path.join(workdir, "events_node*.jsonl"),
            ])
        except Exception:  # noqa: BLE001 - torn mid-write reads retry
            return False
        round_ts = None
        for e in ev:
            if (
                e.get("type") == "rendezvous_complete"
                and e.get("rdzv") == "elastic-training"
                and len(e.get("nodes") or []) == nnodes - 1
            ):
                round_ts = e["ts"]
                break
        if round_ts is None:
            return False
        later_steps = [
            e for e in ev
            if e.get("type") == "train_step" and e["ts"] > round_ts
        ]
        return len(later_steps) >= rejoin_after_steps

    agents: Dict[int, subprocess.Popen] = {}
    logs: List = []
    rc = 0
    respawned = False
    try:
        deadline = time.time() + 30
        while not addr_connected(addr):
            if master.poll() is not None or time.time() > deadline:
                raise RuntimeError("resize master failed to start")
            time.sleep(0.2)
        for rank in range(nnodes):
            agents[rank] = spawn_agent(rank, respawn=False, logs=logs)
        deadline = time.time() + timeout
        while time.time() < deadline:
            states = {r: p.poll() for r, p in agents.items()}
            if not respawned and states.get(kill_rank) is not None:
                if shrunken_world_stepping():
                    logger.info(
                        "shrunken world is stepping; respawning "
                        "replacement agent for rank %s", kill_rank,
                    )
                    agents[kill_rank] = spawn_agent(
                        kill_rank, respawn=True, logs=logs
                    )
                    respawned = True
            elif all(s is not None for s in states.values()):
                if respawned or states.get(kill_rank) is None:
                    break
            time.sleep(0.3)
        else:
            rc = 124  # deadline: kill whatever is left
        for p in agents.values():
            if p.poll() is None and rc == 124:
                p.kill()
            try:
                p.wait(timeout=max(1.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
                rc = rc or 124
        if not respawned:
            rc = rc or 125  # the churn never completed its arc
        for rank, p in agents.items():
            # the killed rank's FIRST incarnation legitimately dies
            # non-zero; every final incarnation must succeed
            rc = rc or (p.returncode or 0)
    finally:
        for p in agents.values():
            if p.poll() is None:
                p.kill()
                p.wait()
        if master.poll() is None:
            master.terminate()
            try:
                master.wait(timeout=10)
            except subprocess.TimeoutExpired:
                master.kill()
        for out in logs:
            try:
                out.close()
            except OSError:
                pass

    report = _build_report(
        scenario, rc, workdir, event_log,
        extra_sources=[agent_event_glob],
    )
    default_set = (
        sparse_resize_invariants
        if opts.get("train_script") == "sparse_resize"
        else elastic_resize_invariants
    )
    checks = (
        invariants if invariants is not None
        else default_set(
            nnodes, total_steps, disk_every, workdir,
        )
    )
    for inv in checks:
        try:
            report.invariants.append(
                inv.check(report.events, report)
            )
        except Exception as e:  # noqa: BLE001 - a checker bug is a FAIL
            logger.exception("invariant %s crashed", inv.name)
            report.invariants.append(
                InvariantResult(inv.name, False, f"checker crashed: {e}")
            )
    return report
