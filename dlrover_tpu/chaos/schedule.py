"""Deterministic, seeded chaos scenario schedules.

A :class:`Scenario` is a named list of :class:`Rule`s.  Each rule
matches an injection point (exact name or ``fnmatch`` glob), carries
one trigger, and names a fault action executed by the injector when
the trigger fires.  Everything that involves randomness draws from a
``random.Random`` seeded with ``(scenario.seed, rule index)`` — so two
runs of the same scenario over the same sequence of ``fire()`` calls
produce byte-identical fault timelines, which is what the determinism
regression tests assert.

Trigger vocabulary (one per rule; all composable with ``max_count``,
``duration`` and ``only_first_incarnation``):

- ``at_step: N``          — fires when the hook context carries
  ``step == N`` (trainer-side points).
- ``after_step: N``       — fires once the hook context carries
  ``step >= N``.  The progress-based alternative to ``after_time``
  for SAMPLED step observations (the ``agent.monitor`` hook passes
  the step it last saw in the trainer's metrics record — equality
  can be skipped over, a threshold cannot), so "kill node 1 once it
  has trained past step N" stays deterministic however slow the
  job's startup is.
- ``step_window: [lo, hi]`` — a step is drawn deterministically from
  the inclusive window using the rule's seeded RNG ("kill one worker
  mid-step with a fixed seed").
- ``after_calls: N``      — fires from the Nth invocation of the
  matched point onward (per process).
- ``after_time: T``       — fires once wall time since injector
  install exceeds T seconds.
- ``prob: p``             — seeded Bernoulli draw per invocation.
- none of the above       — fires on every matched invocation.

``duration: S`` keeps the rule active for S seconds after its first
firing (RPC partitions, storage brownouts), ``max_count`` bounds the
number of executions (default: 1 for point rules, unbounded for
``duration`` windows — a partition drops EVERY frame in its window
unless the author bounds it explicitly; 0 always means unbounded),
and ``only_first_incarnation`` skips the rule in respawned workers
(``DLROVER_RESTART_COUNT > 0``) so a kill scheduled at step N does
not re-kill the recovered incarnation replaying step N.

Scenarios load from a dict, a JSON/YAML string, or a file path
(``.yaml``/``.yml``/``.json``); YAML needs pyyaml and degrades to a
clear error when it is missing.
"""

import fnmatch
import json
import os
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dlrover_tpu.common import env_utils

# actions the injector knows how to execute (see chaos/primitives.py)
KNOWN_ACTIONS = (
    "kill",          # signal own process (default SIGKILL)
    "kill_worker",   # signal a supervised worker from ctx["procs"]
    "kill_node",     # kill worker tree then self (node-loss parity)
    "drop",          # raise ConnectionError (RPC drop / partition)
    "delay",         # sleep args["seconds"] then continue (RPC delay)
    "io_error",      # raise OSError (storage fault)
    "stall",         # sleep args["seconds"] (storage write stall)
    "slow",          # sleep args["seconds"] (straggler slow step)
    "corrupt_shm",   # flip bytes in the shm snapshot via ctx["handler"]
    "preempt",       # return True (simulated preemption notice)
)


@dataclass
class Rule:
    """One fault rule of a scenario."""

    point: str
    action: str
    name: str = ""
    at_step: Optional[int] = None
    after_step: Optional[int] = None
    step_window: Optional[List[int]] = None
    after_calls: Optional[int] = None
    after_time: Optional[float] = None
    prob: Optional[float] = None
    duration: float = 0.0
    # None = default: 1 for point rules, 0 (unbounded) inside a
    # duration window; resolved to an int in __post_init__
    max_count: Optional[int] = None
    only_first_incarnation: bool = False
    # fire only in the worker incarnation whose restart_count equals
    # this (generalizes only_first_incarnation: scheduled-churn
    # scenarios kill incarnation 0 at step A, incarnation 1 at step
    # B, ... without re-killing a respawn replaying A)
    incarnation: Optional[int] = None
    # fire only in processes whose environment matches every pair —
    # how a rule targets a SUBSET of a multi-process job: one node of
    # a multi-agent partition ({"DLROVER_NODE_RANK": "1"}), one
    # forkserver template generation
    # ({"DLROVER_FORKSERVER_GENERATION": "1"})
    env_equals: Dict[str, str] = field(default_factory=dict)
    args: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        if self.max_count is None:
            self.max_count = 0 if self.duration > 0 else 1
        if self.action not in KNOWN_ACTIONS:
            raise ValueError(
                f"unknown chaos action {self.action!r}; "
                f"known: {KNOWN_ACTIONS}"
            )
        triggers = [
            t for t in (
                self.at_step, self.after_step, self.step_window,
                self.after_calls, self.after_time, self.prob,
            )
            if t is not None
        ]
        if len(triggers) > 1:
            raise ValueError(
                f"rule {self.name or self.point!r} has more than one "
                "trigger; pick one of at_step/after_step/step_window/"
                "after_calls/after_time/prob"
            )
        if self.step_window is not None:
            lo, hi = self.step_window
            if lo > hi:
                raise ValueError(
                    f"step_window lo {lo} > hi {hi}"
                )

    def matches(self, point: str) -> bool:
        if self.point == point:
            return True
        return fnmatch.fnmatchcase(point, self.point)


class RuleState:
    """Per-process runtime state of one rule: its seeded RNG, call
    and execution counters, the step drawn from a ``step_window``, and
    the ``duration`` window opening time."""

    def __init__(self, rule: Rule, index: int, seed: int):
        self.rule = rule
        # stable derivation: the rule's position and the scenario seed
        # fully determine every draw this rule will ever make
        self.rng = random.Random(f"{seed}:{index}:{rule.point}")
        self.calls = 0
        self.executions = 0
        self.window_opened_at: Optional[float] = None
        self.window_closed = False
        self.chosen_step: Optional[int] = None
        if rule.step_window is not None:
            lo, hi = rule.step_window
            self.chosen_step = self.rng.randint(lo, hi)

    def exhausted(self) -> bool:
        if self.rule.duration > 0:
            # a window rule ends when its window closes OR it hit an
            # explicit execution bound mid-window
            return self.window_closed
        return (
            self.rule.max_count > 0
            and self.executions >= self.rule.max_count
        )

    def should_fire(self, ctx: Dict[str, Any], now: float,
                    installed_at: float) -> bool:
        """Decide, deterministically, whether this invocation of the
        matched point executes the rule's action."""
        rule = self.rule
        self.calls += 1
        if rule.only_first_incarnation or rule.incarnation is not None:
            # hook sites that KNOW the incarnation pass it in ctx (the
            # agent supervises restarts but never carries the env var
            # itself — it only exports it to spawned workers); other
            # processes read their inherited env
            restart_count = ctx.get("restart_count")
            if restart_count is None:
                restart_count = env_utils.get_restart_count()
            if rule.only_first_incarnation and restart_count > 0:
                return False
            if (rule.incarnation is not None
                    and restart_count != rule.incarnation):
                return False
        if rule.env_equals:
            for key, want in rule.env_equals.items():
                if os.environ.get(key, "") != str(want):
                    return False
        # an open duration window fires until it closes — or until an
        # explicit max_count bounds the blast radius mid-window
        if self.window_opened_at is not None:
            if rule.max_count > 0 and self.executions >= rule.max_count:
                self.window_closed = True
                return False
            if now - self.window_opened_at <= rule.duration:
                return True
            self.window_closed = True
            return False
        if rule.duration <= 0 and rule.max_count > 0 \
                and self.executions >= rule.max_count:
            return False
        triggered = self._trigger(ctx, now, installed_at)
        if triggered and rule.duration > 0:
            self.window_opened_at = now
        return triggered

    def _trigger(self, ctx: Dict[str, Any], now: float,
                 installed_at: float) -> bool:
        rule = self.rule
        if rule.at_step is not None:
            return ctx.get("step") == rule.at_step
        if rule.after_step is not None:
            step = ctx.get("step")
            return step is not None and step >= rule.after_step
        if rule.step_window is not None:
            return ctx.get("step") == self.chosen_step
        if rule.after_calls is not None:
            return self.calls >= rule.after_calls
        if rule.after_time is not None:
            return now - installed_at >= rule.after_time
        if rule.prob is not None:
            return self.rng.random() < rule.prob
        return True


@dataclass
class Scenario:
    """A named, seeded fault schedule."""

    name: str = "unnamed"
    seed: int = 0
    rules: List[Rule] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "seed": self.seed, "rules": [],
        }
        for r in self.rules:
            rd: Dict[str, Any] = {"point": r.point, "action": r.action}
            for key in (
                "name", "at_step", "after_step", "step_window",
                "after_calls", "after_time", "prob", "incarnation",
            ):
                val = getattr(r, key)
                if val not in (None, ""):
                    rd[key] = val
            if r.duration:
                rd["duration"] = r.duration
            if r.max_count != (0 if r.duration > 0 else 1):
                rd["max_count"] = r.max_count
            if r.only_first_incarnation:
                rd["only_first_incarnation"] = True
            if r.env_equals:
                rd["env_equals"] = dict(r.env_equals)
            if r.args:
                rd["args"] = dict(r.args)
            out["rules"].append(rd)
        return out

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "Scenario":
        rules = []
        for i, rd in enumerate(spec.get("rules", [])):
            rd = dict(rd)
            rd.setdefault("name", f"rule{i}")
            rules.append(Rule(**rd))
        return cls(
            name=str(spec.get("name", "unnamed")),
            seed=int(spec.get("seed", 0)),
            rules=rules,
        )


def load_scenario(source) -> Scenario:
    """Scenario from a Scenario/dict/JSON-or-YAML string/file path."""
    if isinstance(source, Scenario):
        return source
    if isinstance(source, dict):
        return Scenario.from_dict(source)
    if not isinstance(source, str):
        raise TypeError(f"cannot load a scenario from {type(source)}")
    text = source.strip()
    if text.startswith("{"):  # inline JSON spec
        return Scenario.from_dict(json.loads(text))
    if os.path.exists(source):
        with open(source) as f:
            text = f.read().strip()
        if text.startswith("{"):
            return Scenario.from_dict(json.loads(text))
    elif "\n" not in source and (
        os.sep in source
        or source.endswith((".yaml", ".yml", ".json"))
    ):
        # it NAMES a file that is not there (typo, unmounted volume,
        # subprocess cwd mismatch): raising beats feeding the path
        # string to the YAML parser, which would 'succeed' as a
        # scalar and arm nothing — a silent no-chaos run reads as a
        # recovery-machinery failure instead of a config error
        raise FileNotFoundError(f"chaos scenario file {source!r}")
    try:
        import yaml
    except ImportError as e:  # pragma: no cover - container has pyyaml
        raise RuntimeError(
            "YAML scenario given but pyyaml is unavailable; use a "
            "JSON spec instead"
        ) from e
    return Scenario.from_dict(yaml.safe_load(text))
