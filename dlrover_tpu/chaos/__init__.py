"""Chaos subsystem: deterministic fault injection for elastic-recovery
testing.

Permanent hook sites across the codebase call :func:`fire` — RPC
client/server (``common/comm.py``), checkpoint storage
(``common/storage.py``), the shm snapshot writer
(``checkpoint/shm_handler.py``), the trainer step loop
(``trainer/elastic_trainer.py``), the agent's worker monitor
(``agent/training.py``) and the preemption probe
(``agent/preemption.py``).  When no injector is installed — the
production default — ``fire`` is one module-global load and a ``None``
check, so the hooks live in hot paths for free.

Activation:

- set ``DLROVER_CHAOS`` to a scenario file path (YAML/JSON) or inline
  JSON before the process starts; every ``dlrover_tpu`` process that
  imports this package (the master subprocess, the agent, each trainer
  incarnation) arms itself at import, which is how one env var makes a
  whole mini-cluster misbehave on schedule, or
- call :func:`install` in-process (tests, the scenario harness).

``python -m dlrover_tpu.chaos`` runs a named scenario through the
mini-cluster harness and prints the invariant report (see
``chaos/harness.py``).
"""

import os
from typing import Any, Optional

from dlrover_tpu.chaos.injector import ChaosInjector, Injection
from dlrover_tpu.chaos.primitives import (
    ChaosIOError,
    ChaosRpcError,
    kill_process,
)
from dlrover_tpu.chaos.schedule import Rule, Scenario, load_scenario
from dlrover_tpu.common.log import default_logger as logger

CHAOS_ENV = "DLROVER_CHAOS"

_injector: Optional[ChaosInjector] = None


def fire(point: str, **ctx) -> Any:
    """The permanent hook.  No-op (one global load + None check) until
    an injector is installed."""
    inj = _injector
    if inj is None:
        return None
    return inj.fire(point, **ctx)


def chaos_enabled() -> bool:
    return _injector is not None


def get_injector() -> Optional[ChaosInjector]:
    return _injector


def install(scenario, clock=None) -> ChaosInjector:
    """Arm a scenario in this process (replaces any armed one)."""
    global _injector
    kwargs = {"clock": clock} if clock is not None else {}
    _injector = ChaosInjector(scenario, **kwargs)
    logger.warning(
        "chaos armed: scenario %r seed %s (%d rules)",
        _injector.scenario.name,
        _injector.scenario.seed,
        len(_injector.scenario.rules),
    )
    return _injector


def uninstall():
    global _injector
    _injector = None


def install_from_env() -> Optional[ChaosInjector]:
    """Arm from ``DLROVER_CHAOS`` if set; never raises into the caller
    — a malformed scenario logs and leaves chaos disabled (chaos must
    not be able to take a production job down by typo)."""
    spec = os.environ.get(CHAOS_ENV, "").strip()
    if not spec:
        return None
    try:
        return install(spec)
    except Exception as e:  # noqa: BLE001 - bad spec must not kill the job
        logger.error("chaos: cannot load %s=%r: %s", CHAOS_ENV, spec, e)
        return None


# import-time activation: spawned processes (master subprocess, warm-
# or cold-started trainers) inherit DLROVER_CHAOS and arm themselves
# on first import of any hooked module
if os.environ.get(CHAOS_ENV):
    install_from_env()

__all__ = [
    "CHAOS_ENV",
    "ChaosInjector",
    "ChaosIOError",
    "ChaosRpcError",
    "Injection",
    "Rule",
    "Scenario",
    "chaos_enabled",
    "fire",
    "get_injector",
    "install",
    "install_from_env",
    "kill_process",
    "load_scenario",
    "uninstall",
]
