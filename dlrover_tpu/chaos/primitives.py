"""Fault primitives the chaos injector executes.

Each primitive is a plain function ``(rule_args, ctx) -> result``;
raising is a legitimate result (RPC drop raises ``ChaosRpcError``, a
storage fault raises ``ChaosIOError`` — both subclass the exception
type the wrapped subsystem already handles, so hook sites need no
chaos-specific error handling and production retry/recovery paths are
exercised exactly as a real fault would exercise them).

Process kills use raw signals (SIGKILL parity with a node loss,
SIGTERM parity with an eviction) — the same primitive drives both the
trainer-side self-kill and the agent-side worker kill, and the
forkserver regression tests reuse :func:`kill_process` directly.
"""

import os
import signal
import time
from typing import Any, Dict

from dlrover_tpu.common.log import default_logger as logger


class ChaosRpcError(ConnectionError):
    """Injected RPC drop/partition — a ConnectionError so the client's
    reconnect/backoff machinery treats it as a real broken link."""


class ChaosIOError(OSError):
    """Injected storage fault — an OSError so storage callers exercise
    their real error paths."""


_SIGNALS = {
    "KILL": signal.SIGKILL,
    "TERM": signal.SIGTERM,
    "INT": signal.SIGINT,
}


def _resolve_signal(args: Dict[str, Any]) -> int:
    name = str(args.get("signal", "KILL")).upper()
    if name.startswith("SIG"):
        name = name[3:]
    return _SIGNALS.get(name, signal.SIGKILL)


def kill_process(pid: int, sig: int = signal.SIGKILL) -> bool:
    """Signal ``pid``; False when it is already gone.  Shared by the
    chaos actions and the forkserver kill/respawn regression tests."""
    try:
        os.kill(pid, sig)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        logger.warning("chaos: not permitted to signal pid %s", pid)
        return False


def act_kill(args: Dict[str, Any], ctx: Dict[str, Any]):
    """Signal the CURRENT process (trainer-side node-loss parity).
    With SIGKILL this call does not return."""
    sig = _resolve_signal(args)
    logger.warning(
        "chaos: signalling own pid %s with %s", os.getpid(), sig
    )
    kill_process(os.getpid(), sig)
    return None


def act_kill_worker(args: Dict[str, Any], ctx: Dict[str, Any]):
    """Signal one supervised worker process from ``ctx['procs']``
    (agent-side kill: the agent observes the death through its normal
    monitor loop, exactly like a real worker crash)."""
    procs = ctx.get("procs") or []
    idx = int(args.get("rank", 0))
    if idx >= len(procs):
        return False
    proc = procs[idx]
    pid = getattr(proc, "pid", None)
    if pid is None:
        return False
    sig = _resolve_signal(args)
    logger.warning("chaos: killing worker rank %s (pid %s)", idx, pid)
    return kill_process(pid, sig)


def act_kill_node(args: Dict[str, Any], ctx: Dict[str, Any]):
    """Node-loss parity: kill the supervised worker processes from
    ``ctx['procs']`` FIRST, then the current (agent) process — a VM
    that disappears takes its whole supervision tree with it, unlike
    ``kill`` (worker keeps its agent) or ``kill_worker`` (agent keeps
    supervising).  The elastic-resize scenarios fire this at the
    ``agent.monitor`` hook so the master sees a node go silent with
    no failure report, exactly like real capacity loss."""
    sig = _resolve_signal(args)
    for proc in ctx.get("procs") or []:
        pid = getattr(proc, "pid", None)
        if pid is not None:
            kill_process(pid, sig)
    logger.warning(
        "chaos: node loss — killed worker tree, now signalling own "
        "pid %s with %s", os.getpid(), sig,
    )
    kill_process(os.getpid(), sig)
    return None


def act_drop(args: Dict[str, Any], ctx: Dict[str, Any]):
    raise ChaosRpcError(
        f"chaos: dropped {ctx.get('point', 'rpc')} frame"
    )


def act_delay(args: Dict[str, Any], ctx: Dict[str, Any]):
    time.sleep(float(args.get("seconds", 0.1)))
    return None


def act_io_error(args: Dict[str, Any], ctx: Dict[str, Any]):
    raise ChaosIOError(
        args.get("errno", 5),
        f"chaos: injected IO error at {ctx.get('path', '?')}",
    )


def act_stall(args: Dict[str, Any], ctx: Dict[str, Any]):
    time.sleep(float(args.get("seconds", 1.0)))
    return None


def act_slow(args: Dict[str, Any], ctx: Dict[str, Any]):
    """Straggler slow-step: stretch the current step by sleeping in
    the report path, so the per-node step-time distribution the
    master's straggler rule medians over genuinely degrades."""
    time.sleep(float(args.get("seconds", 0.5)))
    return None


def act_corrupt_shm(args: Dict[str, Any], ctx: Dict[str, Any]):
    """Flip bytes in the just-written shm checkpoint snapshot via the
    handler passed in the hook context.  ``mode: "torn"`` instead
    republishes the snapshot metadata with ``writing=True`` so readers
    treat it as mid-write (a torn snapshot) and refuse the restore."""
    handler = ctx.get("handler")
    if handler is None:
        return False
    mode = str(args.get("mode", "flip"))
    meta = handler.metadata()
    if not meta:
        return False
    if mode == "torn":
        config = meta["config"]
        config.writing = True
        handler._publish_meta(
            meta["tensors"], config,
            meta["scalar_offset"], meta["scalar_nbytes"],
        )
        logger.warning("chaos: marked shm snapshot torn (writing=True)")
        return True
    shm = handler._attach()
    if shm is None:
        return False
    nbytes = min(int(args.get("nbytes", 64)), shm.size)
    offset = min(int(args.get("offset", 0)), max(0, shm.size - nbytes))
    for i in range(offset, offset + nbytes):
        shm.buf[i] = shm.buf[i] ^ 0xFF
    logger.warning(
        "chaos: flipped %s bytes of shm snapshot at offset %s",
        nbytes, offset,
    )
    return True


def act_preempt(args: Dict[str, Any], ctx: Dict[str, Any]):
    """Simulated preemption notice: the preemption monitor's probe
    hook interprets a truthy return as 'metadata server says TRUE'."""
    logger.warning("chaos: injecting preemption notice")
    return True


ACTIONS = {
    "kill": act_kill,
    "kill_worker": act_kill_worker,
    "kill_node": act_kill_node,
    "drop": act_drop,
    "delay": act_delay,
    "io_error": act_io_error,
    "stall": act_stall,
    "slow": act_slow,
    "corrupt_shm": act_corrupt_shm,
    "preempt": act_preempt,
}
