"""Chaos injection engine.

One :class:`ChaosInjector` per process holds a loaded
:class:`~dlrover_tpu.chaos.schedule.Scenario` plus per-rule runtime
state and answers every ``fire(point, **ctx)`` from the permanent hook
sites.  On a triggered rule it

1. appends ``(seq, point, rule, action, step)`` to the in-memory
   **timeline** (what the determinism tests compare),
2. emits a ``chaos_inject`` training event — BEFORE executing the
   action, so even a SIGKILL of this very process leaves its injection
   in the event log for the invariant checkers,
3. bumps ``dlrover_chaos_injections_total`` in the metrics registry,
4. executes the fault primitive (which may raise or never return).

The engine is deliberately dumb about *where* it runs: the same
scenario file is handed to the master subprocess, the agent process
and every trainer incarnation through the ``DLROVER_CHAOS`` env var;
each process arms only the rules whose points it actually fires.
"""

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.chaos import primitives
from dlrover_tpu.chaos.schedule import RuleState, Scenario, load_scenario
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_INJECTIONS_TOTAL = _REG.counter(
    "dlrover_chaos_injections_total",
    "Chaos fault injections executed, by point and action",
)


@dataclass
class Injection:
    """One executed fault (the timeline entry)."""

    seq: int
    point: str
    rule: str
    action: str
    step: Optional[int] = None

    def key(self):
        """Identity tuple for cross-run determinism comparison."""
        return (self.seq, self.point, self.rule, self.action, self.step)


class ChaosInjector:
    def __init__(
        self,
        scenario,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.scenario: Scenario = load_scenario(scenario)
        self._clock = clock
        self._installed_at = clock()
        self._lock = threading.Lock()
        self._states = [
            RuleState(rule, i, self.scenario.seed)
            for i, rule in enumerate(self.scenario.rules)
        ]
        self._timeline: List[Injection] = []
        self._seq = 0

    @property
    def timeline(self) -> List[Injection]:
        with self._lock:
            return list(self._timeline)

    def timeline_keys(self) -> List[tuple]:
        return [inj.key() for inj in self.timeline]

    def fire(self, point: str, **ctx) -> Any:
        """Evaluate every matching rule; execute the first that
        triggers.  Returns the action's result (hook sites that care —
        the preemption probe — interpret it); most sites ignore it.
        Exceptions raised by fault primitives propagate to the hook
        site by design."""
        now = self._clock()
        fired: Optional[RuleState] = None
        with self._lock:
            for state in self._states:
                if state.exhausted() or not state.rule.matches(point):
                    continue
                ctx["point"] = point
                if state.should_fire(ctx, now, self._installed_at):
                    fired = state
                    state.executions += 1
                    inj = Injection(
                        seq=self._seq,
                        point=point,
                        rule=state.rule.name or state.rule.point,
                        action=state.rule.action,
                        step=ctx.get("step"),
                    )
                    self._seq += 1
                    self._timeline.append(inj)
                    break
        if fired is None:
            return None
        # telemetry first: a kill action never returns, and the event
        # log is the only witness the invariant checkers get
        emit_event(
            "chaos_inject",
            scenario=self.scenario.name,
            seed=self.scenario.seed,
            seq=inj.seq,
            point=inj.point,
            rule=inj.rule,
            action=inj.action,
            step=inj.step,
            # per-process discriminator: multi-agent scenarios (node-
            # subset partitions) need to tell WHICH node injected —
            # two processes with the same source would otherwise
            # collide on (source, seq) in the timeline
            node_rank=env_utils.get_node_rank(),
        )
        _INJECTIONS_TOTAL.inc(point=point, action=fired.rule.action)
        logger.warning(
            "chaos[%s#%s]: %s at %s (step=%s)",
            self.scenario.name, inj.seq, inj.action, point, inj.step,
        )
        handler = primitives.ACTIONS[fired.rule.action]
        return handler(dict(fired.rule.args), ctx)

    def describe(self) -> Dict[str, Any]:
        """Armed-rule summary (CLI + debugging)."""
        with self._lock:
            return {
                "scenario": self.scenario.name,
                "seed": self.scenario.seed,
                "rules": [
                    {
                        "name": s.rule.name or s.rule.point,
                        "point": s.rule.point,
                        "action": s.rule.action,
                        "calls": s.calls,
                        "executions": s.executions,
                        "chosen_step": s.chosen_step,
                        "exhausted": s.exhausted(),
                    }
                    for s in self._states
                ],
                "injections": len(self._timeline),
            }
