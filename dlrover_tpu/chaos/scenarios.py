"""Built-in chaos scenarios + the toy elastic train loop they drive.

Each scenario is a factory ``(seed) -> Scenario`` registered in
:data:`SCENARIOS`; the CLI (``python -m dlrover_tpu.chaos``) and the
e2e tests run them through :mod:`dlrover_tpu.chaos.harness`.  They are
deliberately small compositions of the schedule vocabulary — the point
of the subsystem is that new failure modes are a dict away, not a new
test file away.
"""

from typing import Callable, Dict, Optional

from dlrover_tpu.agent.forkserver import TRAINER_PRELOAD
from dlrover_tpu.chaos.schedule import Scenario

# knobs the harness exports to the training subprocess
TOTAL_STEPS_ENV = "DLROVER_CHAOS_TOTAL_STEPS"
CKPT_EVERY_ENV = "DLROVER_CHAOS_CKPT_EVERY"
# durable mid-run saves every N steps (0 = only the final step goes
# to disk) — the tier-fallback scenarios restore from these when the
# shm snapshot is refused
DISK_EVERY_ENV = "DLROVER_CHAOS_DISK_EVERY"
# per-step sleep stretching the toy loop's wall clock so wall-time
# triggered rules (preemption notices, brownout windows) land
# mid-run instead of after the job already finished
STEP_SLEEP_ENV = "DLROVER_CHAOS_STEP_SLEEP"
# drive the master's dynamic data sharding: the dataset size (one
# sample per shard, one step per shard; 0 = plain fixed step loop).
# The master-recovery scenarios need shard traffic so "no shard lost,
# none acked twice" is decidable from shard_dispatch/shard_ack events
SHARD_DATASET_ENV = "DLROVER_CHAOS_SHARD_DATASET"

# Toy GPT elastic train loop (mirrors bench.py's ELASTIC_TRAIN_SCRIPT
# shape, minus the self-inflicted crash — faults come exclusively from
# the chaos schedule).  Flash-checkpoints to shm every CKPT_EVERY
# steps; a killed incarnation restores from the snapshot the agent
# kept alive and finishes the fixed step budget; the final step is
# persisted to disk and committed.  argv: ckpt_dir
CHAOS_TRAIN_SCRIPT = r'''
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.models.gpt import GPT, GPTConfig, cross_entropy_loss
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticTrainer, TrainState, abstract_like, make_train_step,
    restore_train_state,
)
from dlrover_tpu.trainer.recovery import RecoveryProfiler

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "10"))
CKPT_EVERY = int(os.environ.get("DLROVER_CHAOS_CKPT_EVERY", "2"))
DISK_EVERY = int(os.environ.get("DLROVER_CHAOS_DISK_EVERY", "0"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
SHARD_DATASET = int(os.environ.get("DLROVER_CHAOS_SHARD_DATASET", "0"))

# measured death->first-step budget: books the spawn/import phases
# now, restore/retrace/first_step below — every incarnation emits
# recovery_phase events the invariants and timeline read
prof = RecoveryProfiler()

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

# restore overlap: the read/assemble stages run on a background
# thread WHILE the model/optimizer/step build below proceeds — only
# the result() join is serial with training
with prof.phase("ckpt_init"):
    ckpt = Checkpointer(ckpt_dir)
    load_handle = ckpt.load_checkpoint_async()

with prof.phase("model_build"):
    cfg = GPTConfig.tiny()
    model = GPT(cfg)
    optimizer = optax.adam(1e-3)

    def loss_fn(p, batch):
        logits = model.apply({"params": p}, batch["x"])
        return cross_entropy_loss(logits, batch["y"])

    step_fn = make_train_step(loss_fn, optimizer)

rng = np.random.default_rng(0)
data = rng.integers(0, cfg.vocab_size, (8, 17), dtype=np.int32)

def place_batch():
    # per-step host->device placement so the always-on profiler's
    # h2d phase measures a real transfer, not zero
    return {"x": jnp.asarray(data[:, :-1]),
            "y": jnp.asarray(data[:, 1:])}

# AOT resolve, OVERLAPPED with the async restore read (which runs
# on its own thread — the PR 10 composition): a warm incarnation
# resolves straight through the label index and DESERIALIZES the
# compiled step — no eval_shape, no Python trace, no XLA compile —
# while the restore reads; a cold one traces+compiles here and
# WRITES the entry + index the next incarnation hits.  Deliberately
# on the MAIN thread: a second XLA-heavy thread fighting the
# restore/state build measurably inflates the deserialize on small
# hosts (resolve_step_async exists for wide ones).
def _abstract_examples():
    abs_params = jax.eval_shape(
        model.init_params, jax.random.PRNGKey(0)
    )
    abs_state = jax.eval_shape(
        lambda p: TrainState.create(p, optimizer), abs_params
    )
    return abs_state, abstract_like(place_batch())

step = prof.resolve_step(
    step_fn, _abstract_examples,
    restore_busy=lambda: not load_handle.done(),
)

start_step, restored = load_handle.result()
prof.record_restore(ckpt.last_restore_phases)
with prof.phase("state_build"):
    if start_step is None:
        params = model.init_params(jax.random.PRNGKey(0))
        start_step = 0
        state = TrainState.create(params, optimizer)
    else:
        # shaved state_build: the checkpoint carries the WHOLE train
        # state (params + optax slots + step), so nothing re-inits
        # eagerly and all leaf conversions ride one batched
        # device_put instead of a per-leaf jnp.asarray chain
        state = restore_train_state(optimizer, restored["state"])

_first_step = [True]
def run_step(state, batch):
    # no trace on an AOT hit — the step dispatches straight into the
    # deserialized executable; the MISS path already measured its
    # retrace (or measures it here on the deferred fallback)
    state, metrics = step(state, batch)
    if _first_step[0]:
        _first_step[0] = False
        jax.block_until_ready(metrics)
        prof.record_first_step()
    return state, metrics

with prof.phase("loop_setup"):
    trainer = ElasticTrainer(global_batch_size=8, micro_batch_size=8,
                             dp_size=1)
    trainer.global_step = start_step

    batch = place_batch()

def after_step():
    # identical checkpoint cadence for both loop flavours; the FULL
    # train state rides the snapshot so a restore supplies the optax
    # slots and state_build defers the optimizer init
    sd = {"state": state, "trainer": trainer.state_dict()}
    if DISK_EVERY and trainer.global_step % DISK_EVERY == 0:
        # durable mid-run save; wait for the commit so a kill rule
        # scheduled a couple of steps later deterministically finds
        # a committed storage step to fall back to
        ckpt.save_checkpoint(
            trainer.global_step, sd, storage_type=StorageType.DISK,
        )
        ckpt.wait()
        deadline = time.time() + 30
        while (time.time() < deadline
               and committed_step() < trainer.global_step):
            time.sleep(0.1)
    elif trainer.global_step % CKPT_EVERY == 0:
        ckpt.save_checkpoint(
            trainer.global_step, sd, storage_type=StorageType.MEMORY,
        )

if SHARD_DATASET:
    # master-driven dynamic sharding: one step per shard task.  The
    # master journals every dispatch/ack, so a master crash mid-run
    # (the master-recovery scenarios SIGKILL it between dispatches)
    # must lose no shard and complete none twice — decided later
    # from the shard_ack events
    from dlrover_tpu.agent.sharding_client import ShardingClient

    sc = ShardingClient(
        dataset_name="chaos-ds", batch_size=1, num_epochs=1,
        dataset_size=SHARD_DATASET, shuffle=False,
        num_minibatches_per_shard=1, storage_type="table",
    )
    while True:
        with trainer.profile("data_wait"):
            task = sc.fetch_task()
        if task is None:
            break
        with trainer.profile("h2d"):
            batch = place_batch()
        with trainer.profile("compute") as p:
            state, metrics = run_step(state, batch)
            p.block(metrics)
        trainer.report_step(metrics)
        if STEP_SLEEP:
            time.sleep(STEP_SLEEP)
        sc.report_task_done(task.task_id)
        # books into the NEXT step's breakdown (the step is closed by
        # report_step), which is where a save's stall is felt anyway
        with trainer.profile("checkpoint"):
            after_step()
    FINAL_STEP = trainer.global_step
else:
    for i in range(start_step, TOTAL_STEPS):
        # the always-on profiler: h2d is a real per-step placement
        # and compute is bracketed by block_until_ready, so every
        # train_step ships a real step_phases breakdown
        with trainer.profile("h2d"):
            batch = place_batch()
        with trainer.profile("compute") as p:
            state, metrics = run_step(state, batch)
            p.block(metrics)
        # report_step emits the train_step event and fires the
        # trainer.step chaos hook — a kill rule ends the process HERE
        trainer.report_step(metrics)
        if STEP_SLEEP:
            time.sleep(STEP_SLEEP)
        with trainer.profile("checkpoint"):
            after_step()
    FINAL_STEP = TOTAL_STEPS

# final durable save, retried until the commit lands: a transient
# brownout may eat one persist round (reported through telemetry,
# never retried by the saver itself — the next SAVE event is the
# retry), and the job's contract is that the final step ends up
# committed anyway.  Only node rank 0 waits on the commit tracker —
# the saver writes it on rank 0 alone, so in multi-agent runs the
# other ranks persist their shard and exit
final_sd = {"state": state, "trainer": trainer.state_dict()}
NODE_RANK = int(os.environ.get("DLROVER_NODE_RANK", "0") or 0)
if NODE_RANK == 0:
    deadline = time.time() + 60
    while time.time() < deadline and committed_step() < FINAL_STEP:
        ckpt.save_checkpoint(
            FINAL_STEP, final_sd, storage_type=StorageType.DISK,
        )
        ckpt.wait()
        poll_end = time.time() + 10
        while time.time() < poll_end and committed_step() < FINAL_STEP:
            time.sleep(0.2)
    assert committed_step() >= FINAL_STEP, (
        "checkpoint commit did not land"
    )
else:
    ckpt.save_checkpoint(
        FINAL_STEP, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
ckpt.close()
'''


# Elastic world-resize train loop (ISSUE 8): a GLOBAL param sharded
# over ALL devices of the current world (2 hosts x 2 CPU devices at
# world=2, 1 host x 2 at world=1 — the harness exports
# xla_force_host_platform_device_count=2), trained in lockstep with a
# real cross-process collective per step via jax.distributed.  Every
# incarnation re-forms the mesh from the agent's env contract and
# restores the checkpoint RESHARDED onto it: the storage tier holds
# per-host shard files, so a 2-host -> 1-host restore genuinely
# redistributes node 1's shards onto node 0's devices.  The per-step
# batch is a pure function of the step index (counter-based PRNG), so
# the loss at step k is identical for ANY world size / restart
# history — :func:`resize_reference_losses` recomputes the
# uninterrupted-control trajectory in-process and the harness compares
# every reported loss against it.  argv: ckpt_dir (SHARED across all
# nodes — that is what makes cross-host redistribution possible).
RESIZE_TRAIN_SCRIPT = r'''
import os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticTrainer, init_jax_distributed,
)

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "24"))
DISK_EVERY = int(os.environ.get("DLROVER_CHAOS_DISK_EVERY", "3"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
SHARD_DATASET = int(os.environ.get("DLROVER_CHAOS_SHARD_DATASET", "0"))
DIM = int(os.environ.get("DLROVER_CHAOS_RESIZE_DIM", "64"))
# tail-stretch: while running below full strength (the shrunken
# world between the kill and the grow-back), slow the step cadence so
# the job cannot finish before the coordinator's grow-back decision
# lands — the decision race, not the training math, is what the
# churn scenario exercises
NNODES = int(os.environ.get("DLROVER_CHAOS_NNODES", "0") or 0)
SHRUNK_SLEEP = float(
    os.environ.get("DLROVER_CHAOS_SHRUNK_STEP_SLEEP", "0") or 0
)

WORLD = int(os.environ.get("DLROVER_WORLD_SIZE", "1") or 1)
RANK = int(os.environ.get("DLROVER_RANK", "0") or 0)

# multi-host runtime from the agent's rendezvous env contract
# (no-op at world 1); the mesh spans EVERY device of this world
init_jax_distributed()
devs = jax.devices()
mesh = Mesh(np.array(devs), ("fsdp",))
shard = NamedSharding(mesh, P("fsdp"))

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

def make_sharded(global_np):
    # per-device placement of this process's addressable shards —
    # works at any world size (device_put of a full host array onto
    # a cross-process sharding would not)
    arrs = [
        jax.device_put(np.ascontiguousarray(global_np[index]), d)
        for d, index in shard.addressable_devices_indices_map(
            global_np.shape
        ).items()
    ]
    return jax.make_array_from_single_device_arrays(
        global_np.shape, shard, arrs
    )

template = make_sharded(np.zeros((DIM, 8), np.float32))
ckpt = Checkpointer(ckpt_dir, replicated=False)
# cross-world restores skip the shm tier (per-node, possibly
# different steps) and RESHARD from the committed storage tier
step0, restored = ckpt.load_checkpoint(target_state={"w": template})
if step0 is None:
    start_step, w = 0, template
else:
    start_step, w = int(step0), restored["w"]

# MUST mirror scenarios.resize_reference_losses exactly: the batch is
# derived from the step index inside the jitted program (counter-based
# PRNG -> same bits at any world size), so the loss trajectory of any
# incarnation matches the uninterrupted single-device control
@jax.jit
def step_fn(w, k):
    x = jax.random.normal(
        jax.random.fold_in(jax.random.PRNGKey(1000), k),
        (8,), jnp.float32,
    )
    def loss_fn(w):
        # row-sharded w: the mean over DIM is a real cross-device
        # (and at world 2, cross-process) reduction
        return ((w @ x - 1.0) ** 2).mean()
    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - 0.1 * g, loss

trainer = ElasticTrainer(global_batch_size=8, micro_batch_size=8,
                         dp_size=1)
trainer.global_step = start_step

# dynamic data sharding rides along on the lead rank only: the
# lockstep collective loop cannot let members consume different task
# counts, so global rank 0 is the data feeder — exactly-once shard
# accounting across all three world incarnations is still decided
# from shard_ack events alone
sc = None
if SHARD_DATASET and RANK == 0:
    from dlrover_tpu.agent.sharding_client import ShardingClient

    sc = ShardingClient(
        dataset_name="chaos-ds", batch_size=1, num_epochs=1,
        dataset_size=SHARD_DATASET, shuffle=False,
        num_minibatches_per_shard=1, storage_type="table",
    )

for k in range(start_step, TOTAL_STEPS):
    task = None
    if sc is not None:
        with trainer.profile("data_wait"):
            task = sc.fetch_task()
    with trainer.profile("compute") as p:
        w, loss = step_fn(w, k + 1)
        p.block(loss)
    trainer.report_step({"loss": float(loss)})
    if task is not None:
        sc.report_task_done(task.task_id)
    if NNODES and WORLD < NNODES and SHRUNK_SLEEP:
        time.sleep(SHRUNK_SLEEP)
    elif STEP_SLEEP:
        time.sleep(STEP_SLEEP)
    with trainer.profile("checkpoint"):
        if DISK_EVERY and trainer.global_step % DISK_EVERY == 0:
            ckpt.save_checkpoint(
                trainer.global_step, {"w": w},
                storage_type=StorageType.DISK,
            )
            ckpt.wait()
            deadline = time.time() + 30
            while (time.time() < deadline
                   and committed_step() < trainer.global_step):
                time.sleep(0.1)
        else:
            ckpt.save_checkpoint(
                trainer.global_step, {"w": w},
                storage_type=StorageType.MEMORY,
            )

# final durable save: every rank persists its shard; the lead rank
# waits for the commit (needs every surviving rank's done file)
final_sd = {"w": w}
if RANK == 0:
    deadline = time.time() + 60
    while time.time() < deadline and committed_step() < TOTAL_STEPS:
        ckpt.save_checkpoint(
            TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
        )
        ckpt.wait()
        poll_end = time.time() + 10
        while time.time() < poll_end and committed_step() < TOTAL_STEPS:
            time.sleep(0.2)
    assert committed_step() >= TOTAL_STEPS, (
        "checkpoint commit did not land"
    )
else:
    ckpt.save_checkpoint(
        TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
ckpt.close()
'''


# Sparse elastic train loop (ISSUE 9): a DeepFM job whose embedding
# lives in a host KvVariable table (GroupAdam slot tables riding
# along, spill tier armed when DLROVER_CHAOS_KV_SPILL sets a DRAM
# budget).  The SparseStateAdapter registers the tables with the
# flash-checkpoint engine, so every save snapshots keys/values/freq +
# optimizer slots into the shm segment next to the dense state, and a
# restore imports them back bit-exact.  The batch at step k is a pure
# function of k, so :func:`sparse_reference_losses` recomputes the
# uninterrupted control in-process and the harness compares every
# reported loss against it — a restore that lost an embedding row,
# a frequency count or an Adam moment forks the trajectory at the
# first replayed step.  argv: ckpt_dir
SPARSE_TRAIN_SCRIPT = r'''
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.checkpoint.checkpointer import (
    Checkpointer, StorageType, restore_to_template,
)
from dlrover_tpu.checkpoint.sparse import SparseStateAdapter
from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.trainer.sparse_pipeline import make_deepfm_device_step
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "12"))
CKPT_EVERY = int(os.environ.get("DLROVER_CHAOS_CKPT_EVERY", "2"))
DISK_EVERY = int(os.environ.get("DLROVER_CHAOS_DISK_EVERY", "0"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
KV_SPILL = int(os.environ.get("DLROVER_CHAOS_KV_SPILL", "0"))

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

# MUST mirror scenarios.sparse_reference_losses exactly
cfg = DeepFMConfig(num_sparse_fields=6, num_dense_features=4,
                   embedding_dim=8, hidden_dims=(16,), seed=5)
model = DeepFM(cfg)
if KV_SPILL:
    # node-local spill files next to (not inside) the shared ckpt dir;
    # O_TRUNC on re-open wipes a dead predecessor's file
    spill_dir = os.path.join(os.path.dirname(ckpt_dir), "kvspill")
    os.makedirs(spill_dir, exist_ok=True)
    model.table.enable_spill(
        os.path.join(spill_dir, "emb.spill"), KV_SPILL
    )
    model.sparse_optimizer.enable_spill(spill_dir, KV_SPILL)

dense_opt = optax.adam(1e-2)
adapter = SparseStateAdapter()
adapter.register_optimizer(model.sparse_optimizer)
ckpt = Checkpointer(ckpt_dir)
ckpt.register_sparse(adapter)

params = model.init_dense_params()
opt_state = dense_opt.init(params)
start_step, restored = ckpt.load_checkpoint()
if start_step is None:
    start_step = 0
else:
    # dense params AND optax state restored typed; the kv tables were
    # already imported by the engine through the adapter
    params, opt_state = restore_to_template(
        (params, opt_state), restored["dense"]
    )
state = (params, opt_state)
device_step = make_deepfm_device_step(model, dense_opt)

trainer = ElasticTrainer(global_batch_size=16, micro_batch_size=16,
                         dp_size=1)
trainer.global_step = start_step

def batch_for(k):
    rng = np.random.default_rng(10_000 + k)
    sparse = rng.integers(
        0, 4000, (16, cfg.num_sparse_fields)
    ).astype(np.int64)
    dense = rng.normal(
        size=(16, cfg.num_dense_features)
    ).astype(np.float32)
    labels = (sparse[:, 0] % 2).astype(np.float32)
    return sparse, dense, labels

for k in range(start_step, TOTAL_STEPS):
    sparse_ids, dense_x, labels = batch_for(k)
    with trainer.profile("h2d"):
        emb = jnp.asarray(model.gather_embeddings(sparse_ids))
        dx, lb = jnp.asarray(dense_x), jnp.asarray(labels)
    with trainer.profile("compute") as p:
        state, egrads, aux = device_step(state, emb, dx, lb)
        p.block(aux["loss"])
    # strict split step: the sparse update retires before the step is
    # reported, so a checkpoint taken after the report is exactly
    # step-consistent across dense AND host-table state
    model.apply_sparse_gradients(sparse_ids, np.asarray(egrads))
    trainer.report_step({"loss": float(aux["loss"])})
    if STEP_SLEEP:
        time.sleep(STEP_SLEEP)
    with trainer.profile("checkpoint"):
        sd = {"dense": state, "trainer": trainer.state_dict()}
        if DISK_EVERY and trainer.global_step % DISK_EVERY == 0:
            ckpt.save_checkpoint(
                trainer.global_step, sd,
                storage_type=StorageType.DISK,
            )
            ckpt.wait()
            deadline = time.time() + 30
            while (time.time() < deadline
                   and committed_step() < trainer.global_step):
                time.sleep(0.1)
        elif trainer.global_step % CKPT_EVERY == 0:
            ckpt.save_checkpoint(
                trainer.global_step, sd,
                storage_type=StorageType.MEMORY,
            )

final_sd = {"dense": state, "trainer": trainer.state_dict()}
deadline = time.time() + 60
while time.time() < deadline and committed_step() < TOTAL_STEPS:
    ckpt.save_checkpoint(
        TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
    poll_end = time.time() + 10
    while time.time() < poll_end and committed_step() < TOTAL_STEPS:
        time.sleep(0.2)
assert committed_step() >= TOTAL_STEPS, (
    "checkpoint commit did not land"
)
ckpt.close()
'''


# Streaming-reshard kill loop (ISSUE 14): a WORLD-1 job whose
# checkpoint dir was PRE-SEEDED by the harness with a committed
# world-2 sparse checkpoint.  The very first restore is therefore a
# cross-world STREAMING reshard — `kv.reshard_chunk` fires once per
# window, and the scenario SIGKILLs the worker mid-stream.  Committed
# storage is untouched by the partial reshard (it only mutates
# in-process tables), so the replacement replays the identical
# reshard from the same shards and trains to completion; the
# exactly-once digests are checked against the seeder's JSON.
# argv: ckpt_dir
SPARSE_RESHARD_TRAIN_SCRIPT = r'''
import os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.checkpoint.sparse import SparseStateAdapter
from dlrover_tpu.ops.kv_variable import GroupAdamOptimizer, KvVariable
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "10"))
CKPT_EVERY = int(os.environ.get("DLROVER_CHAOS_CKPT_EVERY", "2"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
DIM = int(os.environ.get("DLROVER_CHAOS_RESHARD_KV_DIM", "16"))

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

table = KvVariable(dim=DIM, seed=17, name="emb")
kv_opt = GroupAdamOptimizer(table, learning_rate=5e-3)
adapter = SparseStateAdapter()
adapter.register_optimizer(kv_opt)
ckpt = Checkpointer(ckpt_dir)
ckpt.register_sparse(adapter)

# the seeded checkpoint is stamped world 2, this job is world 1: the
# load below IS the streaming reshard (kv.reshard_chunk per window —
# the kill rule lands here in incarnation 0, before any train step)
step0, restored = ckpt.load_checkpoint()
assert step0 is not None, "pre-seeded world-2 checkpoint missing"
start_step = int(step0)
w = jnp.asarray(np.asarray(restored["w"], dtype=np.float32))

trainer = ElasticTrainer(global_batch_size=8, micro_batch_size=8,
                         dp_size=1)
trainer.global_step = start_step

for k in range(start_step, TOTAL_STEPS):
    krng = np.random.default_rng(5_000 + k)
    keys = krng.integers(0, 1_200, 64).astype(np.int64)
    with trainer.profile("h2d"):
        emb = table.gather(keys)
    with trainer.profile("compute") as p:
        kv_opt.apply_gradients(keys, np.tanh(emb) * 0.1)
        w = w * 0.9
        p.block(w)
    trainer.report_step({"loss": float(jnp.sum(w))})
    if STEP_SLEEP:
        time.sleep(STEP_SLEEP)
    with trainer.profile("checkpoint"):
        if trainer.global_step % CKPT_EVERY == 0:
            ckpt.save_checkpoint(
                trainer.global_step, {"w": np.asarray(w)},
                storage_type=StorageType.MEMORY,
            )

final_sd = {"w": np.asarray(w)}
deadline = time.time() + 60
while time.time() < deadline and committed_step() < TOTAL_STEPS:
    ckpt.save_checkpoint(
        TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
    poll_end = time.time() + 10
    while time.time() < poll_end and committed_step() < TOTAL_STEPS:
        time.sleep(0.2)
assert committed_step() >= TOTAL_STEPS, (
    "checkpoint commit did not land"
)
ckpt.close()
'''


def sparse_reference_losses(total_steps: int):
    """Uninterrupted-control loss trajectory of
    :data:`SPARSE_TRAIN_SCRIPT`, computed in-process: same DeepFM
    config/seeds, same step-indexed batches, same strict split-step
    order.  ``result[k-1]`` is the loss step ``k`` must report
    regardless of kills and flash restores — a restore that dropped
    an embedding row, a frequency count, an optimizer slot or the
    Adam step counter forks the trajectory at the first replayed
    step."""
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
    from dlrover_tpu.trainer.sparse_pipeline import (
        make_deepfm_device_step,
    )

    cfg = DeepFMConfig(num_sparse_fields=6, num_dense_features=4,
                       embedding_dim=8, hidden_dims=(16,), seed=5)
    model = DeepFM(cfg)
    dense_opt = optax.adam(1e-2)
    params = model.init_dense_params()
    state = (params, dense_opt.init(params))
    device_step = make_deepfm_device_step(model, dense_opt)
    out = []
    for k in range(total_steps):
        rng = np.random.default_rng(10_000 + k)
        sparse = rng.integers(
            0, 4000, (16, cfg.num_sparse_fields)
        ).astype(np.int64)
        dense = rng.normal(
            size=(16, cfg.num_dense_features)
        ).astype(np.float32)
        labels = (sparse[:, 0] % 2).astype(np.float32)
        emb = jnp.asarray(model.gather_embeddings(sparse))
        state, egrads, aux = device_step(
            state, emb, jnp.asarray(dense), jnp.asarray(labels)
        )
        model.apply_sparse_gradients(sparse, np.asarray(egrads))
        out.append(float(aux["loss"]))
    return out


# Elastic PPO loop (ISSUE 16): the four-role RL engine driven by
# master-dispatched ROLLOUT LEASES.  Each shard task is one rollout:
# prompts and the generation RNG derive purely from the lease id, so
# a lease requeued off a SIGKILLed worker regenerates bit-identically
# on the replacement — exactly-once rollout accounting from
# shard_dispatch/shard_ack events.  The full four-role state (actor +
# critic train states, RNG key, iteration cursor, the PARTIAL rollout
# buffer) rides every flash snapshot through PPOStateAdapter; the
# snapshot is taken after every completed lease and NEVER after a
# train phase, so a mid-iteration kill restores to the last completed
# lease and REPLAYS that iteration's train steps — the replayed
# train_step losses are the loss-trajectory invariant's
# multi-incarnation cross-check.  One PPO train step per lease
# (LEASES_PER_ITER leases buffered, then that many in-order PPO
# updates), so total train steps == total leases == TOTAL_STEPS.
# argv: ckpt_dir
RL_TRAIN_SCRIPT = r'''
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.accel import Strategy
from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.models.gpt import GPT, GPTConfig
from dlrover_tpu.rl.elastic import (
    PPOCursor, PPOStateAdapter, lease_prompts, lease_rng,
    resolve_role_steps,
)
from dlrover_tpu.rl.model_engine import ModelRole, RLModelEngine, RoleSpec
from dlrover_tpu.rl.rollout import (
    make_actor_loss, make_critic_loss, make_experience,
    sample_rollout_batch, train_on_batch,
)
from dlrover_tpu.rl.trainer import ReplayBuffer
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "8"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
LEASES_PER_ITER = int(
    os.environ.get("DLROVER_CHAOS_RL_LEASES_PER_ITER", "2")
)
RESTART_COUNT = int(os.environ.get("DLROVER_RESTART_COUNT", "0") or 0)
NODE_RANK = int(os.environ.get("DLROVER_NODE_RANK", "0") or 0)

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

# MUST mirror scenarios.rl_reference_losses exactly.  B=8 divides
# the data-axis of any test mesh (1 or 8 host devices)
B, PROMPT_LEN, MAX_NEW, VOCAB, SEED = 8, 4, 8, 32, 2
actor_cfg = GPTConfig.tiny(max_seq_len=16, vocab_size=VOCAB)
actor_model = GPT(actor_cfg)
critic_model = GPT(
    GPTConfig.tiny(max_seq_len=16, vocab_size=VOCAB, head="value")
)
ref_model = GPT(actor_cfg)
ref_params = actor_model.init_params(jax.random.PRNGKey(1))
sample = sample_rollout_batch(
    jnp.zeros((B, PROMPT_LEN), jnp.int32), MAX_NEW
)
dp = Strategy(opts=[("parallel_mode", {})])
engine = RLModelEngine(sample, {
    ModelRole.ACTOR: RoleSpec(
        model=actor_model,
        loss_fn=make_actor_loss(actor_model, PROMPT_LEN),
        optim_factory=lambda: optax.adam(5e-3),
        strategy=dp,
    ),
    ModelRole.CRITIC: RoleSpec(
        model=critic_model,
        loss_fn=make_critic_loss(critic_model, PROMPT_LEN),
        optim_factory=lambda: optax.adam(1e-3),
        strategy=dp,
    ),
    ModelRole.REF: RoleSpec(model=ref_model, params=ref_params),
}).build()

def reward_fn(sequences):
    resp = sequences[:, PROMPT_LEN:]
    return (resp < 16).mean(axis=1).astype(jnp.float32)

# register the PPO adapter BEFORE the load: the import needs the
# engine's fresh states as restore templates
buffer = ReplayBuffer()
cursor = PPOCursor(rng_key=np.asarray(jax.random.PRNGKey(SEED)))
adapter = PPOStateAdapter(engine, buffer, cursor)
ckpt = Checkpointer(ckpt_dir)
ckpt.register_sparse(adapter)
start_step, restored = ckpt.load_checkpoint()
# roles/buffer/cursor were rebuilt by the adapter during the load;
# the dense subtree only carried the trainer bookkeeping

trainer = ElasticTrainer(global_batch_size=B, micro_batch_size=B,
                         dp_size=1)
trainer.global_step = cursor.ppo_updates

# AOT-cached actor/critic steps: a respawn deserializes the compiled
# steps the first incarnation wrote — retrace-free RL recovery
steps = {
    role: res.fn
    for role, res in resolve_role_steps(engine, sample).items()
}

sc = ShardingClient(
    dataset_name="rl-rollouts", batch_size=1, num_epochs=1,
    dataset_size=TOTAL_STEPS, shuffle=False,
    num_minibatches_per_shard=1, storage_type="table",
)

phase_s = {"rollout": 0.0, "score": 0.0, "gae": 0.0}

def train_phase():
    # in INSERTION order, never shuffled: a restored incarnation
    # replays byte-identical PPO steps off the restored buffer
    t0 = time.perf_counter()
    batches = buffer.batches()
    actor_loss = critic_loss = 0.0
    for bt in batches:
        with trainer.profile("compute"):
            losses = train_on_batch(engine, bt, steps=steps)
        actor_loss = losses["actor_loss"]
        critic_loss = losses["critic_loss"]
        trainer.report_step(
            {"loss": losses["actor_loss"] + losses["critic_loss"]}
        )
    cursor.ppo_updates = trainer.global_step
    emit_event(
        "rl_iteration",
        iteration=trainer.global_step // max(1, LEASES_PER_ITER),
        restart_count=RESTART_COUNT, node_rank=NODE_RANK,
        leases=len(batches),
        rollout_s=round(phase_s["rollout"], 4),
        score_s=round(phase_s["score"], 4),
        gae_s=round(phase_s["gae"], 4),
        train_s=round(time.perf_counter() - t0, 4),
        actor_loss=actor_loss, critic_loss=critic_loss,
    )
    phase_s.update(rollout=0.0, score=0.0, gae=0.0)
    buffer.reset()

while True:
    if len(buffer.batches()) >= LEASES_PER_ITER:
        train_phase()
    with trainer.profile("data_wait"):
        task = sc.fetch_task()
    if task is None:
        break
    lease_id = int(task.start)
    if lease_id < cursor.leases_done:
        # the checkpointed predecessor already buffered (or trained
        # on) this lease before dying un-acked: ack WITHOUT
        # regenerating, or the batch would enter the buffer twice
        sc.report_task_done(task.task_id)
        continue
    with trainer.profile("rollout"):
        batch, metrics = make_experience(
            engine, jnp.asarray(
                lease_prompts(lease_id, B, PROMPT_LEN, VOCAB)
            ),
            lease_rng(SEED, lease_id), max_new_tokens=MAX_NEW,
            kl_coef=0.01, reward_fn=reward_fn,
        )
    for k in ("rollout", "score", "gae"):
        phase_s[k] += metrics[k + "_s"]
    # the kill rule lands HERE: batch generated but neither buffered,
    # checkpointed nor acked — the master requeues the lease and the
    # replacement regenerates it bit-identically
    _chaos.fire("rl.rollout", step=lease_id)
    buffer.add(batch)
    cursor.leases_done = lease_id + 1
    # flash snapshot after EVERY completed lease and never after a
    # train phase: a mid-iteration kill restores to the last lease
    # and REPLAYS the iteration's train steps (the loss-trajectory
    # invariant's multi-incarnation cross-check needs those replays)
    with trainer.profile("checkpoint"):
        ckpt.save_checkpoint(
            trainer.global_step, {"trainer": trainer.state_dict()},
            storage_type=StorageType.MEMORY,
        )
    sc.report_task_done(task.task_id)
    if STEP_SLEEP:
        time.sleep(STEP_SLEEP)

if buffer.batches():
    train_phase()

FINAL_STEP = trainer.global_step
final_sd = {"trainer": trainer.state_dict()}
deadline = time.time() + 60
while time.time() < deadline and committed_step() < FINAL_STEP:
    ckpt.save_checkpoint(
        FINAL_STEP, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
    poll_end = time.time() + 10
    while time.time() < poll_end and committed_step() < FINAL_STEP:
        time.sleep(0.2)
assert committed_step() >= FINAL_STEP, (
    "checkpoint commit did not land"
)
ckpt.close()
'''


def rl_reference_losses(total_steps: int):
    """Uninterrupted-control loss trajectory of
    :data:`RL_TRAIN_SCRIPT`, computed in-process: same four-role
    engine recipe, same lease-derived prompts/RNG, same
    buffer-then-train iteration structure.  ``result[k-1]`` is the
    combined actor+critic loss PPO train step ``k`` must report
    regardless of kills and flash restores — a restore that dropped
    an optimizer slot, a buffered rollout batch or the cursor forks
    the trajectory at the first replayed step."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dlrover_tpu.accel import Strategy
    from dlrover_tpu.models.gpt import GPT, GPTConfig
    from dlrover_tpu.rl.elastic import lease_prompts, lease_rng
    from dlrover_tpu.rl.model_engine import (
        ModelRole,
        RLModelEngine,
        RoleSpec,
    )
    from dlrover_tpu.rl.rollout import (
        make_actor_loss,
        make_critic_loss,
        make_experience,
        sample_rollout_batch,
        train_on_batch,
    )
    from dlrover_tpu.rl.trainer import ReplayBuffer

    b, prompt_len, max_new, vocab, seed = 8, 4, 8, 32, 2
    leases_per_iter = 2
    actor_cfg = GPTConfig.tiny(max_seq_len=16, vocab_size=vocab)
    actor_model = GPT(actor_cfg)
    critic_model = GPT(
        GPTConfig.tiny(max_seq_len=16, vocab_size=vocab,
                       head="value")
    )
    ref_model = GPT(actor_cfg)
    ref_params = actor_model.init_params(jax.random.PRNGKey(1))
    sample = sample_rollout_batch(
        jnp.zeros((b, prompt_len), jnp.int32), max_new
    )
    dp = Strategy(opts=[("parallel_mode", {})])
    engine = RLModelEngine(sample, {
        ModelRole.ACTOR: RoleSpec(
            model=actor_model,
            loss_fn=make_actor_loss(actor_model, prompt_len),
            optim_factory=lambda: optax.adam(5e-3),
            strategy=dp,
        ),
        ModelRole.CRITIC: RoleSpec(
            model=critic_model,
            loss_fn=make_critic_loss(critic_model, prompt_len),
            optim_factory=lambda: optax.adam(1e-3),
            strategy=dp,
        ),
        ModelRole.REF: RoleSpec(model=ref_model, params=ref_params),
    }).build()

    def reward_fn(sequences):
        resp = sequences[:, prompt_len:]
        return (resp < 16).mean(axis=1).astype(jnp.float32)

    buffer = ReplayBuffer()
    out = []
    for lease_id in range(total_steps):
        batch, _metrics = make_experience(
            engine, jnp.asarray(
                lease_prompts(lease_id, b, prompt_len, vocab)
            ),
            lease_rng(seed, lease_id), max_new_tokens=max_new,
            kl_coef=0.01, reward_fn=reward_fn,
        )
        buffer.add(batch)
        if len(buffer.batches()) >= leases_per_iter:
            for bt in buffer.batches():
                losses = train_on_batch(engine, bt)
                out.append(
                    losses["actor_loss"] + losses["critic_loss"]
                )
            buffer.reset()
    for bt in buffer.batches():
        losses = train_on_batch(engine, bt)
        out.append(losses["actor_loss"] + losses["critic_loss"])
    return out


# Train-to-serve loop: the sparse DeepFM loop PLUS an
# EmbeddingPublisher shipping the embedding table to a serving
# replica as committed base/delta generations every
# DLROVER_CHAOS_PUB_EVERY steps.  A fresh incarnation's publisher
# always opens with a base at a NEW generation (it cannot know what a
# dead predecessor half-published), which is what makes the
# trainer-kill-mid-publish scenario's recovery exactly-once by
# construction.  argv: ckpt_dir; serving dir from
# DLROVER_SERVING_DIR (harness) or <workdir>/serving.
SPARSE_SERVING_TRAIN_SCRIPT = r'''
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.checkpoint.checkpointer import (
    Checkpointer, StorageType, restore_to_template,
)
from dlrover_tpu.checkpoint.sparse import SparseStateAdapter
from dlrover_tpu.models.deepfm import DeepFM, DeepFMConfig
from dlrover_tpu.serving import EmbeddingPublisher
from dlrover_tpu.trainer.sparse_pipeline import make_deepfm_device_step
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "12"))
CKPT_EVERY = int(os.environ.get("DLROVER_CHAOS_CKPT_EVERY", "2"))
PUB_EVERY = int(os.environ.get("DLROVER_CHAOS_PUB_EVERY", "2"))
COMPACT_EVERY = int(os.environ.get("DLROVER_CHAOS_COMPACT_EVERY", "4"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
serving_dir = os.environ.get("DLROVER_SERVING_DIR") or os.path.join(
    os.path.dirname(ckpt_dir), "serving"
)

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

# MUST mirror scenarios.sparse_reference_losses exactly
cfg = DeepFMConfig(num_sparse_fields=6, num_dense_features=4,
                   embedding_dim=8, hidden_dims=(16,), seed=5)
model = DeepFM(cfg)

dense_opt = optax.adam(1e-2)
adapter = SparseStateAdapter()
adapter.register_optimizer(model.sparse_optimizer)
ckpt = Checkpointer(ckpt_dir)
ckpt.register_sparse(adapter)

# serving publishes ONLY the embedding table (replicas have no use
# for optimizer moments); its own adapter shares the table object, so
# dirty tracking is one truth for both planes
serving_adapter = SparseStateAdapter().register_table(model.table)
publisher = EmbeddingPublisher(
    serving_adapter, serving_dir, compact_every=COMPACT_EVERY,
)

params = model.init_dense_params()
opt_state = dense_opt.init(params)
start_step, restored = ckpt.load_checkpoint()
if start_step is None:
    start_step = 0
else:
    params, opt_state = restore_to_template(
        (params, opt_state), restored["dense"]
    )
state = (params, opt_state)
device_step = make_deepfm_device_step(model, dense_opt)

trainer = ElasticTrainer(global_batch_size=16, micro_batch_size=16,
                         dp_size=1)
trainer.global_step = start_step

def batch_for(k):
    rng = np.random.default_rng(10_000 + k)
    sparse = rng.integers(
        0, 4000, (16, cfg.num_sparse_fields)
    ).astype(np.int64)
    dense = rng.normal(
        size=(16, cfg.num_dense_features)
    ).astype(np.float32)
    labels = (sparse[:, 0] % 2).astype(np.float32)
    return sparse, dense, labels

for k in range(start_step, TOTAL_STEPS):
    sparse_ids, dense_x, labels = batch_for(k)
    with trainer.profile("h2d"):
        emb = jnp.asarray(model.gather_embeddings(sparse_ids))
        dx, lb = jnp.asarray(dense_x), jnp.asarray(labels)
    with trainer.profile("compute") as p:
        state, egrads, aux = device_step(state, emb, dx, lb)
        p.block(aux["loss"])
    model.apply_sparse_gradients(sparse_ids, np.asarray(egrads))
    trainer.report_step({"loss": float(aux["loss"])})
    if STEP_SLEEP:
        time.sleep(STEP_SLEEP)
    with trainer.profile("checkpoint"):
        if trainer.global_step % CKPT_EVERY == 0:
            ckpt.save_checkpoint(
                trainer.global_step,
                {"dense": state, "trainer": trainer.state_dict()},
                storage_type=StorageType.MEMORY,
            )
    if trainer.global_step % PUB_EVERY == 0:
        publisher.publish(step=trainer.global_step)

# final publish so the replica can converge on the last trained state
if publisher.generation == 0 or TOTAL_STEPS % PUB_EVERY != 0:
    publisher.publish(step=TOTAL_STEPS)

final_sd = {"dense": state, "trainer": trainer.state_dict()}
deadline = time.time() + 60
while time.time() < deadline and committed_step() < TOTAL_STEPS:
    ckpt.save_checkpoint(
        TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
    poll_end = time.time() + 10
    while time.time() < poll_end and committed_step() < TOTAL_STEPS:
        time.sleep(0.2)
assert committed_step() >= TOTAL_STEPS, (
    "checkpoint commit did not land"
)
ckpt.close()
'''


# Sparse elastic world-resize loop: RESIZE_TRAIN_SCRIPT's GSPMD dense
# leg (lockstep collectives, loss == the uninterrupted control at any
# world size) PLUS a KvVariable embedding partitioned across the
# world by the SAME key hash the cross-world reshard uses
# (checkpoint.sparse.owner_of_keys) — so a 2->1->2 churn genuinely
# redistributes hash-table rows from committed storage, exactly once,
# provable from the kv_checkpoint digests.  argv: ckpt_dir (SHARED).
SPARSE_RESIZE_TRAIN_SCRIPT = r'''
import os, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.checkpoint.sparse import (
    SparseStateAdapter, owner_of_keys,
)
from dlrover_tpu.ops.kv_variable import GroupAdamOptimizer, KvVariable
from dlrover_tpu.trainer.elastic_trainer import (
    ElasticTrainer, init_jax_distributed,
)

ckpt_dir = sys.argv[1]
TOTAL_STEPS = int(os.environ.get("DLROVER_CHAOS_TOTAL_STEPS", "24"))
DISK_EVERY = int(os.environ.get("DLROVER_CHAOS_DISK_EVERY", "3"))
STEP_SLEEP = float(os.environ.get("DLROVER_CHAOS_STEP_SLEEP", "0"))
DIM = int(os.environ.get("DLROVER_CHAOS_RESIZE_DIM", "64"))

WORLD = int(os.environ.get("DLROVER_WORLD_SIZE", "1") or 1)
RANK = int(os.environ.get("DLROVER_RANK", "0") or 0)

init_jax_distributed()
devs = jax.devices()
mesh = Mesh(np.array(devs), ("fsdp",))
shard = NamedSharding(mesh, P("fsdp"))

tracker = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")

def committed_step():
    try:
        with open(tracker) as f:
            return int(f.read().strip() or -1)
    except (OSError, ValueError):
        return -1

def make_sharded(global_np):
    arrs = [
        jax.device_put(np.ascontiguousarray(global_np[index]), d)
        for d, index in shard.addressable_devices_indices_map(
            global_np.shape
        ).items()
    ]
    return jax.make_array_from_single_device_arrays(
        global_np.shape, shard, arrs
    )

# host-table sparse state, hash-partitioned across the world: this
# rank's table holds ONLY the keys owner_of_keys assigns it, so each
# rank's checkpoint shard is a distinct slice of the logical table
# and a world change must genuinely redistribute rows
table = KvVariable(dim=8, seed=17, name="emb")
kv_opt = GroupAdamOptimizer(table, learning_rate=5e-3)
adapter = SparseStateAdapter()
adapter.register_optimizer(kv_opt)

template = make_sharded(np.zeros((DIM, 8), np.float32))
ckpt = Checkpointer(ckpt_dir, replicated=False)
ckpt.register_sparse(adapter)
# cross-world restores refuse the shm tier and reshard BOTH the dense
# GSPMD shards and the kv rows from committed storage
step0, restored = ckpt.load_checkpoint(target_state={"w": template})
if step0 is None:
    start_step, w = 0, template
else:
    start_step, w = int(step0), restored["w"]

# dense leg MUST mirror scenarios.resize_reference_losses exactly
@jax.jit
def step_fn(w, k):
    x = jax.random.normal(
        jax.random.fold_in(jax.random.PRNGKey(1000), k),
        (8,), jnp.float32,
    )
    def loss_fn(w):
        return ((w @ x - 1.0) ** 2).mean()
    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - 0.1 * g, loss

trainer = ElasticTrainer(global_batch_size=8, micro_batch_size=8,
                         dp_size=1)
trainer.global_step = start_step

for k in range(start_step, TOTAL_STEPS):
    # sparse leg: a step-indexed global key stream, routed to this
    # rank by the same owner hash the reshard partitions with; the
    # per-row update depends only on the row's own state, so row
    # trajectories are world-size-independent
    krng = np.random.default_rng(5_000 + k)
    gkeys = krng.integers(0, 3_000, 48).astype(np.int64)
    mine = gkeys[owner_of_keys(gkeys, WORLD) == RANK]
    if mine.size:
        emb = table.gather(mine)
        kv_opt.apply_gradients(mine, np.tanh(emb) * 0.1)
    with trainer.profile("compute") as p:
        w, loss = step_fn(w, k + 1)
        p.block(loss)
    trainer.report_step({"loss": float(loss)})
    if STEP_SLEEP:
        time.sleep(STEP_SLEEP)
    with trainer.profile("checkpoint"):
        if DISK_EVERY and trainer.global_step % DISK_EVERY == 0:
            ckpt.save_checkpoint(
                trainer.global_step, {"w": w},
                storage_type=StorageType.DISK,
            )
            ckpt.wait()
            deadline = time.time() + 30
            while (time.time() < deadline
                   and committed_step() < trainer.global_step):
                time.sleep(0.1)
        else:
            ckpt.save_checkpoint(
                trainer.global_step, {"w": w},
                storage_type=StorageType.MEMORY,
            )

final_sd = {"w": w}
if RANK == 0:
    deadline = time.time() + 60
    while time.time() < deadline and committed_step() < TOTAL_STEPS:
        ckpt.save_checkpoint(
            TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
        )
        ckpt.wait()
        poll_end = time.time() + 10
        while time.time() < poll_end and committed_step() < TOTAL_STEPS:
            time.sleep(0.2)
    assert committed_step() >= TOTAL_STEPS, (
        "checkpoint commit did not land"
    )
else:
    ckpt.save_checkpoint(
        TOTAL_STEPS, final_sd, storage_type=StorageType.DISK,
    )
    ckpt.wait()
ckpt.close()
'''


def resize_reference_losses(total_steps: int, dim: int = 64):
    """Uninterrupted-control loss trajectory of
    :data:`RESIZE_TRAIN_SCRIPT`'s update rule, computed single-device
    in-process.  ``result[k-1]`` is the loss the job must report at
    step ``k`` regardless of world size, restarts, or resharded
    restores — the batch derivation and update MUST stay in lockstep
    with the script's ``step_fn``."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step_fn(w, k):
        x = jax.random.normal(
            jax.random.fold_in(jax.random.PRNGKey(1000), k),
            (8,), jnp.float32,
        )

        def loss_fn(w):
            return ((w @ x - 1.0) ** 2).mean()

        loss, g = jax.value_and_grad(loss_fn)(w)
        return w - 0.1 * g, loss

    w = jnp.zeros((dim, 8), jnp.float32)
    out = []
    for k in range(1, total_steps + 1):
        w, loss = step_fn(w, k)
        out.append(float(loss))
    return out


def kill_worker_midstep(seed: int = 42) -> Scenario:
    """THE acceptance scenario: SIGKILL the worker at a seed-chosen
    step mid-run.  The agent's monitor loop observes the death,
    persists the shm snapshot, re-rendezvouses and respawns; the
    recovered incarnation must lose at most one checkpoint interval."""
    return Scenario.from_dict({
        "name": "kill-worker-midstep",
        "seed": seed,
        "rules": [{
            "name": "kill-midstep",
            "point": "trainer.step",
            "action": "kill",
            "step_window": [4, 7],
            "only_first_incarnation": True,
        }],
    })


def sigterm_worker_midstep(seed: int = 42) -> Scenario:
    """Graceful-eviction flavour of the kill scenario (SIGTERM)."""
    return Scenario.from_dict({
        "name": "sigterm-worker-midstep",
        "seed": seed,
        "rules": [{
            "name": "term-midstep",
            "point": "trainer.step",
            "action": "kill",
            "step_window": [4, 7],
            "only_first_incarnation": True,
            "args": {"signal": "TERM"},
        }],
    })


def rpc_partition(seed: int = 7) -> Scenario:
    """Drop every master RPC for a 2 s window early in the run: the
    client's jittered-backoff reconnect path must ride it out with no
    job impact beyond latency."""
    return Scenario.from_dict({
        "name": "rpc-partition",
        "seed": seed,
        "rules": [{
            "name": "partition",
            "point": "rpc.client.roundtrip",
            "action": "drop",
            "after_time": 1.0,
            "duration": 2.0,
        }],
    })


def storage_brownout(seed: int = 11) -> Scenario:
    """Every storage write fails for the first few persist attempts,
    then the backend 'recovers': persistence must degrade to a
    reported failure (telemetry event, error counter) and the next
    interval's save must still commit."""
    return Scenario.from_dict({
        "name": "storage-brownout",
        "seed": seed,
        "rules": [{
            "name": "flaky-writes",
            "point": "storage.write",
            "action": "io_error",
            "max_count": 3,
        }],
    })


def storage_stall(seed: int = 13) -> Scenario:
    """One slow (hung-NFS-style) storage write mid-run."""
    return Scenario.from_dict({
        "name": "storage-stall",
        "seed": seed,
        "rules": [{
            "name": "stalled-write",
            "point": "storage.write",
            "action": "stall",
            "after_calls": 2,
            "max_count": 1,
            "args": {"seconds": 1.0},
        }],
    })


def straggler(seed: int = 5) -> Scenario:
    """Seeded-probabilistic slow steps: the per-node step-time
    distribution degrades and the diagnosis chain's straggler rule has
    something real to catch in multi-node runs."""
    return Scenario.from_dict({
        "name": "straggler",
        "seed": seed,
        "rules": [{
            "name": "slow-steps",
            "point": "trainer.step",
            "action": "slow",
            "prob": 0.5,
            "max_count": 5,
            "args": {"seconds": 0.3},
        }],
    })


def preemption_notice(seed: int = 3) -> Scenario:
    """Simulated ~30s-warning spot preemption: the monitor's probe
    reads TRUE, the agent reports to the master and breakpoint-saves
    the shm snapshot while the 'VM' is still alive."""
    return Scenario.from_dict({
        "name": "preemption-notice",
        "seed": seed,
        "rules": [{
            "name": "notice",
            "point": "preemption.probe",
            "action": "preempt",
            "after_time": 2.0,
        }],
    })


def shm_corrupt_storage_fallback(seed: int = 23) -> Scenario:
    """Tier-fallback acceptance: tear the shm snapshot at a MEMORY
    save, then kill the worker one step later.  The respawned trainer
    must refuse the torn shm tier and restore from the last committed
    storage step (the harness runs this with ``disk_every=4`` so one
    exists) — asserted by the ``RestoredFromTier`` invariant reading
    the ``checkpoint_restore`` event's ``tier`` field."""
    return Scenario.from_dict({
        "name": "shm-corrupt-storage-fallback",
        "seed": seed,
        "rules": [
            {
                "name": "torn-snapshot",
                "point": "ckpt.shm_save",
                "action": "corrupt_shm",
                "at_step": 6,
                "only_first_incarnation": True,
                "args": {"mode": "torn"},
            },
            {
                "name": "kill-after-tear",
                "point": "trainer.step",
                "action": "kill",
                "at_step": 7,
                "only_first_incarnation": True,
            },
        ],
    })


def ckpt_brownout_during_preemption(seed: int = 19) -> Scenario:
    """ROADMAP scenario: a storage brownout lands exactly while a
    preemption notice's grace-period breakpoint save is trying to
    persist — the two grace paths compete for the persist executor.
    The job must ride it out: the failed persist is REPORTED
    (``checkpoint_persist`` ok=false event + error counter), later
    saves commit, training completes, nothing deadlocks.  Wall-clock
    triggered (the notice is a timer by nature), so the timeline is
    bounded, not byte-stable; the harness stretches the toy loop with
    ``step_sleep`` so the window lands mid-run."""
    return Scenario.from_dict({
        "name": "ckpt-brownout-during-preemption",
        "seed": seed,
        "rules": [
            {
                "name": "notice",
                "point": "preemption.probe",
                "action": "preempt",
                "after_time": 5.0,
            },
            {
                # exactly one injected failure, on the FIRST storage
                # write of the job — MEMORY saves never touch storage,
                # so that write is a grace-path persist (the notice's
                # breakpoint save when the snapshot beat the notice,
                # else the final commit's first round, which the toy
                # loop re-issues) — then the fault is spent so the
                # retried commit goes through
                "name": "brownout",
                "point": "storage.write",
                "action": "io_error",
                "max_count": 1,
            },
        ],
    })


def master_kill_restart_midround(seed: int = 31) -> Scenario:
    """Master crash recovery acceptance (ISSUE 4): SIGKILL the MASTER
    on its 3rd shard dispatch — mid-rendezvous-round, with one shard
    journaled-but-undelivered and acks in flight.  tpurun's watchdog
    respawns it on the same port; the new incarnation replays the
    state journal (re-entering rendezvous round 1, re-queueing only
    the un-acked shard), parked agents/trainers session-resync, and
    training completes with no shard lost, none acked twice, and NO
    healthy-worker restart — all decided from telemetry events."""
    return Scenario.from_dict({
        "name": "master-kill-restart-midround",
        "seed": seed,
        "rules": [{
            "name": "kill-master-middispatch",
            "point": "master.task_dispatch",
            "action": "kill",
            "after_calls": 3,
            # the respawned master (DLROVER_RESTART_COUNT=1) must
            # survive replaying the very dispatch that killed its
            # predecessor
            "only_first_incarnation": True,
        }],
    })


def multinode_rpc_partition(seed: int = 29) -> Scenario:
    """Partition a SUBSET of the job: drop every master RPC of node
    rank 1 (its agent AND its trainer) for a 3 s window while rank 0
    is untouched.  The un-partitioned node must keep training and the
    partitioned one must ride out the window on the reconnect path
    and rejoin WITHOUT a full-job restart (run via the multi-agent
    harness, ``run_scenario_multinode``)."""
    return Scenario.from_dict({
        "name": "multinode-rpc-partition",
        "seed": seed,
        "rules": [{
            "name": "partition-rank1",
            "point": "rpc.client.roundtrip",
            "action": "drop",
            "after_time": 2.0,
            "duration": 3.0,
            "env_equals": {"DLROVER_NODE_RANK": "1"},
        }],
    })


def warm_template_import_kill(seed: int = 37) -> Scenario:
    """Warm-restart chaos: SIGKILL the forkserver template DURING its
    heavy preload imports — generation 1 and its rebuild both die, so
    the agent's spawn must detect the dead template immediately and
    fall back to cold spawns with no orphan processes."""
    return Scenario.from_dict({
        "name": "warm-template-import-kill",
        "seed": seed,
        "rules": [
            {
                "name": "kill-template-import-gen1",
                "point": "forkserver.template_import",
                "action": "kill",
                "after_calls": 2,
                "env_equals": {"DLROVER_FORKSERVER_GENERATION": "1"},
            },
            {
                # the rebuilt template dies the same way: the agent
                # must give up on warm forks for the round instead of
                # rebuilding forever
                "name": "kill-template-import-gen2",
                "point": "forkserver.template_import",
                "action": "kill",
                "after_calls": 2,
                "env_equals": {"DLROVER_FORKSERVER_GENERATION": "2"},
            },
        ],
    })


def warm_template_midspawn_kill(seed: int = 41) -> Scenario:
    """Warm-restart chaos: SIGKILL the template mid-spawn — the spawn
    request is consumed but no child is forked and no reply is coming,
    the hardest template loss to detect.  The agent must fall back to
    a cold spawn in milliseconds (dead-template check in the wait
    loop), leaving no orphans."""
    return Scenario.from_dict({
        "name": "warm-template-midspawn-kill",
        "seed": seed,
        "rules": [{
            "name": "kill-template-midspawn",
            "point": "forkserver.spawn",
            "action": "kill",
            "env_equals": {"DLROVER_FORKSERVER_GENERATION": "1"},
        }],
    })


def goodput_under_scheduled_churn(seed: int = 43) -> Scenario:
    """bench.py's churn section as a seeded scenario: the worker is
    SIGKILLed at fixed absolute steps, one kill per incarnation (the
    ``incarnation`` trigger keeps a respawn replaying step N from
    being re-killed at N).  The invariant is on the master's own
    goodput accounting: ``dlrover_goodput_ratio`` ≥ 0.90, read from
    the ``master_exit`` event."""
    return Scenario.from_dict({
        "name": "goodput-under-scheduled-churn",
        "seed": seed,
        "rules": [
            {
                "name": "churn-kill-1",
                "point": "trainer.step",
                "action": "kill",
                "at_step": 7,
                "incarnation": 0,
            },
            {
                "name": "churn-kill-2",
                "point": "trainer.step",
                "action": "kill",
                "at_step": 14,
                "incarnation": 1,
            },
        ],
    })


def trainer_hang_detected(seed: int = 47) -> Scenario:
    """Deep-diagnosis acceptance (ISSUE 7): freeze one trainer
    mid-step with the stall primitive (a sleep in the report path —
    the process is alive, heartbeats flow, steps stop: exactly the
    silent-hang class that is indistinguishable from slowness without
    flight data).  The agent watchdog must capture stacks + /proc
    state and ship ``hang_evidence``; the master's inference chain
    must reach a *hung* verdict carrying that evidence and a measured
    stall, and restart ONLY the culprit node through the
    heartbeat-action relaunch path; the restored incarnation finishes
    the budget.  Thresholds are shrunk via RUN_OPTIONS env so the
    whole diagnosis plays out in seconds (tier-1)."""
    return Scenario.from_dict({
        "name": "trainer-hang-detected",
        "seed": seed,
        "rules": [{
            "name": "freeze-midstep",
            "point": "trainer.step",
            "action": "stall",
            "at_step": 5,
            "max_count": 1,
            "only_first_incarnation": True,
            # far beyond every diagnosis threshold: the sleep is
            # ended by the culprit restart's SIGTERM, never by the
            # timer — a diagnosis that fails leaves the job hung
            # until the harness timeout, not a silent pass
            "args": {"seconds": 90.0},
        }],
    })


def elastic_resize_churn(seed: int = 53) -> Scenario:
    """Elastic world-resize acceptance (ISSUE 8): a NODE LOSS — one of
    two agents dies with its whole worker tree (``kill_node``, no
    failure report, exactly like a vanished VM) — and the job survives
    by training SMALLER: the master's resize coordinator detects the
    silence, decides world 2 -> 1, drains the survivor over the
    heartbeat-action channel, and the re-formed world restores the
    checkpoint RESHARDED (node 1's storage shards redistributed onto
    node 0's devices).  The harness then respawns the lost agent (a
    replacement host: fresh shm namespace, ``DLROVER_AGENT_RESPAWNED``
    marks it so the kill rule never re-fires) and the job grows back
    to world 2 the same way.  Wall-clock triggered (the loss IS a
    timer event), so the timeline is bounded, not byte-stable."""
    return Scenario.from_dict({
        "name": "elastic-resize-churn",
        "seed": seed,
        "rules": [{
            "name": "node1-loss",
            "point": "agent.monitor",
            "action": "kill_node",
            "after_time": 8.0,
            "env_equals": {
                "DLROVER_NODE_RANK": "1",
                "DLROVER_AGENT_RESPAWNED": "",
            },
        }],
    })


def multinode_hang_culprit(seed: int = 59) -> Scenario:
    """Multinode hang diagnosis (ROADMAP carried-forward): freeze ONE
    node's trainer of a two-agent job mid-step while the other keeps
    stepping — the silence rule alone cannot convict (global progress
    continues), so the verdict must come from the culprit-selection
    evidence scoring over the agents' shipped flight data, and ONLY
    node 1 may be restarted."""
    return Scenario.from_dict({
        "name": "multinode-hang-culprit",
        "seed": seed,
        "rules": [{
            "name": "freeze-node1-midstep",
            "point": "trainer.step",
            "action": "stall",
            # early: node 1's whole recovery must finish while node 0
            # is STILL TRAINING — a peer that succeeds mid-recovery
            # leaves the liveness set and the in-place rejoin
            # (correctly) refuses a world with a departed member
            "at_step": 3,
            "max_count": 1,
            "only_first_incarnation": True,
            "env_equals": {"DLROVER_NODE_RANK": "1"},
            # ended by the culprit restart's SIGTERM, never the timer
            "args": {"seconds": 90.0},
        }],
    })


def sparse_kill_restore(seed: int = 61) -> Scenario:
    """Sparse elastic recovery acceptance (ISSUE 9): SIGKILL a DeepFM
    job mid-run — embedding table, frequency counters and GroupAdam
    slot tables (spill tier ACTIVE: the harness arms a DRAM budget so
    real rows live on the cold tier) must ride the flash checkpoint
    and come back bit-identical: the restored incarnation's loss
    trajectory equals the uninterrupted control, and the
    ``kv_checkpoint`` digests prove every row/freq/slot survived —
    all decided from telemetry events alone."""
    return Scenario.from_dict({
        "name": "sparse-kill-restore",
        "seed": seed,
        "rules": [{
            "name": "kill-sparse-midstep",
            "point": "trainer.step",
            "action": "kill",
            "step_window": [5, 7],
            "only_first_incarnation": True,
        }],
    })


def rl_rollout_worker_kill(seed: int = 97) -> Scenario:
    """Elastic RL acceptance (ISSUE 16): SIGKILL the rollout worker
    mid-PPO-iteration — on the ``rl.rollout`` hook of lease 2, after
    the batch is generated but BEFORE it is buffered, checkpointed or
    acked.  The master requeues the lease (journaled dispatch/ack);
    the replacement restores the four-role state + partial buffer +
    cursor from the flash checkpoint, REPLAYS the interrupted
    iteration's train steps, regenerates the lost lease
    bit-identically and finishes the budget.  Exactly-once rollout
    accounting, the loss trajectory equal to the uninterrupted
    control, and recovery-loss attribution are all decided from the
    event log alone."""
    return Scenario.from_dict({
        "name": "rl-rollout-worker-kill",
        "seed": seed,
        "rules": [{
            "name": "kill-rollout-midlease",
            "point": "rl.rollout",
            "action": "kill",
            # lease 2 = the first lease AFTER a train phase: the
            # restore must land on the post-lease-1 snapshot and
            # replay PPO steps 1-2 (multi-incarnation loss agreement)
            "at_step": 2,
            "only_first_incarnation": True,
        }],
    })


def sparse_spill_io_error(seed: int = 67) -> Scenario:
    """Graceful degradation (ISSUE 9): the spill tier's disk dies
    DURING a checkpoint export (io_error on the ``kv.spill`` hook).
    Stranded cold rows drop out of that export; training continues
    and the production write-failure breaker trips on the next spill
    pass (``spill_disabled`` on the following export event); the
    checkpoint of the DRAM-resident rows still commits, and after a
    kill two steps later the restore is valid — round-trip digests
    still match the (post-fault) export."""
    return Scenario.from_dict({
        "name": "sparse-spill-io-error",
        "seed": seed,
        "rules": [
            {
                "name": "spill-disk-dies",
                "point": "kv.spill",
                "action": "io_error",
                "at_step": 4,
                "max_count": 1,
                "only_first_incarnation": True,
            },
            {
                "name": "kill-after-breaker",
                "point": "trainer.step",
                "action": "kill",
                "at_step": 7,
                "only_first_incarnation": True,
            },
        ],
    })


def sparse_resize_churn(seed: int = 71) -> Scenario:
    """Sparse elastic world-resize (ISSUE 9 — the genuinely novel
    combination with PR 8's ResizeCoordinator): a node loss shrinks a
    two-node sparse job to one, and the hash-table embedding (plus
    its optimizer slot tables) is RESHARDED from committed storage —
    all old ranks' kv shards read, rows re-partitioned by key hash,
    the owned subset imported — then the world grows back and
    reshards again.  Exactly-once row accounting and the shm-tier
    refusal across world sizes are decided from the ``kv_checkpoint``
    events alone."""
    return Scenario.from_dict({
        "name": "sparse-resize-churn",
        "seed": seed,
        "rules": [{
            "name": "node1-loss",
            "point": "agent.monitor",
            "action": "kill_node",
            # progress-based, not wall-clock: the node dies only once
            # its trainer has REPORTED past step 6 (two world-2 disk
            # commits exist) — a slow jax/distributed startup cannot
            # turn the scenario into train-from-scratch at world 1
            "after_step": 6,
            "env_equals": {
                "DLROVER_NODE_RANK": "1",
                "DLROVER_AGENT_RESPAWNED": "",
            },
        }],
    })


def sparse_streaming_reshard_kill(seed: int = 79) -> Scenario:
    """Streaming-reshard crash consistency (ISSUE 14): the harness
    pre-seeds a committed world-2 sparse checkpoint, the world-1
    job's first restore streams the cross-world reshard in bounded
    windows, and the worker is SIGKILLed on the 3rd
    ``kv.reshard_chunk`` — mid-stream, tables half-imported.
    Committed storage is untouched (the reshard mutates only
    in-process tables), so the replacement replays the identical
    reshard from the same shards; the additive per-table digests on
    its resharded restore event must equal the seeder's per-shard
    export sums with imported rows == the distinct union — no row
    lost, no chunk double-imported."""
    return Scenario.from_dict({
        "name": "sparse-streaming-reshard-kill",
        "seed": seed,
        "rules": [{
            "name": "kill-mid-reshard",
            "point": "kv.reshard_chunk",
            "action": "kill",
            "after_calls": 3,
            "max_count": 1,
            "only_first_incarnation": True,
        }],
    })


def serving_replica_kill_midingest(seed: int = 83) -> Scenario:
    """Serving-plane replica recovery (ISSUE 13): SIGKILL the serving
    replica INSIDE a generation apply (the ``serving.ingest`` hook
    fires under the swap lock, tables half-applied).  The harness
    respawns it; the fresh replica re-ingests from the newest
    committed base and converges on the trainer's final generation.
    The digest chain on ``serving_ingest`` vs ``serving_publish``
    events proves no torn generation was ever served — the
    half-applied state died with the process and no event claimed
    it."""
    return Scenario.from_dict({
        "name": "serving-replica-kill-midingest",
        "seed": seed,
        "rules": [{
            "name": "kill-replica-midingest",
            "point": "serving.ingest",
            "action": "kill",
            "after_calls": 3,
            "max_count": 1,
            "env_equals": {
                "DLROVER_SERVING_ROLE": "replica",
                "DLROVER_SERVING_RESPAWNED": "",
            },
        }],
    })


def serving_fleet_replica_kill(seed: int = 97) -> Scenario:
    """Serving-fleet routing under fire (ISSUE 17): against a live
    replica POOL fronted by the lookup router, SIGKILL (a) replica 0
    INSIDE a generation apply (``serving.ingest``, env-pinned to
    ``DLROVER_SERVING_REPLICA_ID=0`` — role alone would kill every
    member) and (b) the ROUTER itself mid-stream (``serving.route``
    fires once per routed lookup).  The router must shed the dead
    replica within the heartbeat window and keep answering from the
    survivors — zero failed and zero stale lookups counted on the
    ``serving_route`` windows — and the respawned router must replay
    its journaled membership to the identical routing table and
    resume routing without restarting any healthy replica.  The
    ``DLROVER_SERVING_RESPAWNED`` guards keep both kills
    single-shot."""
    return Scenario.from_dict({
        "name": "serving-fleet-replica-kill",
        "seed": seed,
        "rules": [{
            "name": "kill-pool-replica-midingest",
            "point": "serving.ingest",
            "action": "kill",
            "after_calls": 3,
            "max_count": 1,
            "env_equals": {
                "DLROVER_SERVING_ROLE": "replica",
                "DLROVER_SERVING_REPLICA_ID": "0",
                "DLROVER_SERVING_RESPAWNED": "",
            },
        }, {
            # time-based, NOT call-count: the router kill must land
            # AFTER the killed replica has been shed and its respawn
            # re-admitted (simultaneous kills would leave no router
            # alive to witness the shed), and the route hook fires
            # continuously under load so the window is hit exactly
            "name": "kill-router-midroute",
            "point": "serving.route",
            "action": "kill",
            "after_time": 5.0,
            "max_count": 1,
            "env_equals": {
                "DLROVER_SERVING_ROLE": "router",
                "DLROVER_SERVING_RESPAWNED": "",
            },
        }],
    })


def serving_trainer_kill_midpublish(seed: int = 89) -> Scenario:
    """Serving-plane publisher exactly-once (ISSUE 13): SIGKILL the
    trainer between writing a generation's blobs/manifest and its
    ``DONE`` marker (the ``serving.publish`` hook sits exactly
    there).  The half-published generation is never committed — the
    replica keeps serving the previous one — and the respawned
    trainer's publisher opens with a fresh BASE at the next
    generation number: every committed generation is published
    exactly once, provable by counting ``serving_publish`` events."""
    return Scenario.from_dict({
        "name": "serving-trainer-kill-midpublish",
        "seed": seed,
        "rules": [{
            "name": "kill-trainer-midpublish",
            "point": "serving.publish",
            "action": "kill",
            "after_calls": 3,
            "max_count": 1,
            "only_first_incarnation": True,
        }],
    })


def warm_recovery_cache_hit(seed: int = 73) -> Scenario:
    """Invisible-recovery acceptance (ISSUE 10): SIGKILL the worker
    mid-run under warm restarts + the job-keyed persistent compile
    cache.  The replacement incarnation must prove — from the event
    log alone — that its re-trace HIT the cache the first incarnation
    populated (``compile_cache`` event, no new entries over a warm
    dir), that the measured ``retrace_s`` stayed under the ceiling,
    and that the whole death->first-step budget landed as
    ``recovery_phase`` slices on the assembled timeline."""
    return Scenario.from_dict({
        "name": "warm-recovery-cache-hit",
        "seed": seed,
        "rules": [{
            "name": "kill-midstep",
            "point": "trainer.step",
            "action": "kill",
            "step_window": [5, 6],
            "only_first_incarnation": True,
        }],
    })


def master_respawn_other_host(seed: int = 79) -> Scenario:
    """Host-portable control plane (ISSUE 10): SIGKILL the master
    mid-dispatch like ``master_kill_restart_midround`` — but the
    respawn gets a FRESH, EMPTY journal dir (what a replacement host
    has), so recovery must come entirely from the async-group-commit
    journal mirror on the checkpoint storage tier.  Exactly-once
    sharding and the final commit are still asserted from events;
    ``master_recovered.from_mirror`` is the witness that the mirror,
    not the local disk, carried the state."""
    return Scenario.from_dict({
        "name": "master-respawn-other-host",
        "seed": seed,
        "rules": [{
            "name": "kill-master-middispatch",
            "point": "master.task_dispatch",
            "action": "kill",
            "after_calls": 3,
            "only_first_incarnation": True,
        }],
    })


def shm_corruption(seed: int = 17) -> Scenario:
    """Tear one shm snapshot right after it is written (writing=True
    republish): the persist and restore paths must refuse the torn
    snapshot instead of committing garbage."""
    return Scenario.from_dict({
        "name": "shm-corruption",
        "seed": seed,
        "rules": [{
            "name": "torn-snapshot",
            "point": "ckpt.shm_save",
            "action": "corrupt_shm",
            "at_step": 4,
            "args": {"mode": "torn"},
        }],
    })


SCENARIOS: Dict[str, Callable[[int], Scenario]] = {
    "kill_worker_midstep": kill_worker_midstep,
    "sigterm_worker_midstep": sigterm_worker_midstep,
    "rpc_partition": rpc_partition,
    "storage_brownout": storage_brownout,
    "storage_stall": storage_stall,
    "straggler": straggler,
    "preemption_notice": preemption_notice,
    "shm_corruption": shm_corruption,
    "shm_corrupt_storage_fallback": shm_corrupt_storage_fallback,
    "ckpt_brownout_during_preemption": ckpt_brownout_during_preemption,
    "master_kill_restart_midround": master_kill_restart_midround,
    "multinode_rpc_partition": multinode_rpc_partition,
    "warm_template_import_kill": warm_template_import_kill,
    "warm_template_midspawn_kill": warm_template_midspawn_kill,
    "goodput_under_scheduled_churn": goodput_under_scheduled_churn,
    "trainer_hang_detected": trainer_hang_detected,
    "elastic_resize_churn": elastic_resize_churn,
    "multinode_hang_culprit": multinode_hang_culprit,
    "sparse_kill_restore": sparse_kill_restore,
    "sparse_spill_io_error": sparse_spill_io_error,
    "sparse_resize_churn": sparse_resize_churn,
    "sparse_streaming_reshard_kill": sparse_streaming_reshard_kill,
    "serving_replica_kill_midingest": serving_replica_kill_midingest,
    "serving_fleet_replica_kill": serving_fleet_replica_kill,
    "serving_trainer_kill_midpublish": (
        serving_trainer_kill_midpublish
    ),
    "warm_recovery_cache_hit": warm_recovery_cache_hit,
    "master_respawn_other_host": master_respawn_other_host,
    "rl_rollout_worker_kill": rl_rollout_worker_kill,
}


# per-scenario harness knobs, keyed by the SCENARIO's name field, so
# the CLI and the tests drive each scenario the way it needs without
# repeating the recipe: the tier-fallback scenario needs a committed
# disk step to fall back to; the preemption scenarios need the
# monitor armed (a fast-failing metadata URL keeps the pre-notice
# probes cheap) and a stretched loop so the wall-clock window lands
# mid-run
RUN_OPTIONS: Dict[str, Dict] = {
    "shm-corrupt-storage-fallback": {"disk_every": 4},
    "ckpt-brownout-during-preemption": {
        "step_sleep": 1.0,
        "extra_env": {
            "DLROVER_PREEMPTION_MONITOR": "1",
            "DLROVER_METADATA_SERVER": "http://127.0.0.1:9/preempted",
        },
    },
    "preemption-notice": {
        "extra_env": {
            "DLROVER_PREEMPTION_MONITOR": "1",
            "DLROVER_METADATA_SERVER": "http://127.0.0.1:9/preempted",
        },
    },
    # the master-recovery acceptance drives the sharding path (one
    # shard per step) so shard-loss/duplication is decidable from
    # telemetry; shard_dataset=True sizes the dataset to total_steps
    "master-kill-restart-midround": {"shard_dataset": True},
    # churn goodput: warm restarts keep recovery ~1 s (cold jax
    # imports would eat the goodput the scenario measures), a
    # stretched step makes productive time dominate, and a fast
    # monitor-report cadence gives the master's SpeedMonitor a real
    # gap distribution to book recovery losses against
    "goodput-under-scheduled-churn": {
        "warm_restart": True,
        "total_steps": 20,
        # per-step flash snapshot (the reference's headline feature):
        # a respawn resumes at the killed step with zero replay —
        # at ~10 ms per shm save it costs nothing and is exactly the
        # churn posture a production job would run
        "ckpt_every": 1,
        # ~1 s steps: the toy loop's step:recovery ratio should
        # resemble real training (seconds-long steps vs ~1-2 s warm
        # recovery), not a microbenchmark where restart cost dwarfs
        # the step time it protects
        "step_sleep": 1.0,
        "extra_env": {
            "DLROVER_MONITOR_REPORT_INTERVAL": "0.5",
            # preload the framework modules the train script needs —
            # a respawn then pays fork+restore+retrace only, which is
            # exactly the warm-restart goodput story under test
            "DLROVER_PRELOAD": TRAINER_PRELOAD,
        },
    },
    "warm-template-import-kill": {"warm_restart": True},
    "warm-template-midspawn-kill": {"warm_restart": True},
    # run_scenario_multinode applies these to every agent process
    "multinode-rpc-partition": {"step_sleep": 0.5},
    # elastic resize in seconds: a 2.5 s heartbeat-silence window
    # detects the SIGKILLed node (no failure report exists), a 1 s
    # decision grace debounces it, and sub-second master polls /
    # monitor reports keep every control-plane reaction prompt; the
    # loop is stretched so the kill lands mid-run and disk commits
    # every 3 steps bound the cross-world restore's step loss
    "elastic-resize-churn": {
        "total_steps": 24,
        "disk_every": 3,
        "step_sleep": 0.3,
        # while the world is shrunken the loop crawls: on a loaded
        # box the replacement can take several seconds to boot, and
        # at 0.3 s/step the survivor would otherwise finish all 24
        # steps before the grow-back decision fires (flaky "never
        # grew back" verdicts) — stretching only the shrunken tail
        # bounds that race without slowing the healthy phases
        "shrunk_step_sleep": 1.0,
        "shard_dataset": True,
        "extra_env": {
            "DLROVER_MONITOR_REPORT_INTERVAL": "0.5",
            "DLROVER_HANG_DETECTION_S": "2.5",
            "DLROVER_RESIZE_GRACE_S": "1.0",
            "DLROVER_RESIZE_REDELIVER_S": "15.0",
            "DLROVER_RESIZE_STOP_TIMEOUT_S": "1.5",
            "DLROVER_SECONDS_TO_CHECK_HANG": "0.5",
            "DLROVER_BREAKPOINT_COMMIT_TIMEOUT_S": "3",
            # the coordinator owns BOTH resize directions: the
            # agent-side membership fallback would race it on the
            # grow-back and leave the decision un-journaled
            "DLROVER_MEMBERSHIP_SELF_RESTART": "0",
            # the world-2 mesh is 2 hosts x 2 devices; world-1 is
            # 1 x 2 — the restore genuinely redistributes shards
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    },
    # multinode hang: same shrunk diagnosis thresholds as the
    # single-node scenario, but the conviction must come from the
    # per-node evidence scoring — node 0 keeps stepping throughout,
    # and the budget/step pacing keeps it training PAST node 1's
    # whole recovery (a peer succeeding mid-recovery would leave the
    # world node 1 needs to rejoin)
    "multinode-hang-culprit": {
        "total_steps": 16,
        "step_sleep": 0.8,
        "extra_env": {
            "DLROVER_MONITOR_REPORT_INTERVAL": "0.5",
            "DLROVER_HANG_THRESHOLD_S": "2",
            "DLROVER_HANG_TIMEOUT": "3",
            "DLROVER_SECONDS_TO_CHECK_HANG": "0.5",
            "DLROVER_HANG_RESTART_GRACE_S": "20",
        },
    },
    # sparse recovery: the toy DeepFM loop (train_script selects it in
    # the harness), per-table content digests armed so the round-trip
    # invariant can decide bit-identity from events alone, and a DRAM
    # budget small enough that real rows live on the spill tier (the
    # control runs DRAM-only — residence is transparent, values equal)
    "sparse-kill-restore": {
        "total_steps": 12,
        "ckpt_every": 2,
        "train_script": "sparse",
        "extra_env": {
            "DLROVER_KV_DIGEST": "1",
            "DLROVER_CHAOS_KV_SPILL": "48",
        },
    },
    # serving plane: the sparse loop + publisher shipping the
    # embedding table every 2 steps (digests armed — manifests and
    # the torn-serve invariants need them); the serving runner reads
    # train_script="sparse_serving" and supervises the replica
    # subprocess itself
    "serving-replica-kill-midingest": {
        "total_steps": 12,
        "ckpt_every": 2,
        "train_script": "sparse_serving",
        "extra_env": {
            "DLROVER_KV_DIGEST": "1",
            "DLROVER_CHAOS_PUB_EVERY": "2",
            # slow the loop slightly so several generations commit
            # while the replica is alive on a loaded CI box
            "DLROVER_CHAOS_STEP_SLEEP": "0.2",
        },
    },
    # serving fleet: no trainer subprocess at all — the fleet runner
    # (run_serving_fleet_scenario) publishes in-process and drives
    # real routed load; these knobs shape the run.  compact_every=3
    # forces base generations (= drained re-bases) to land mid-load;
    # the 2 ms lookup floor models the TPU device-gather a CPU-only
    # CI box cannot reproduce, so in-flight requests genuinely
    # overlap across the pool
    "serving-fleet-replica-kill": {
        "pool_size": 3,
        "generations": 10,
        "publish_every_s": 0.35,
        "compact_every": 3,
        "load_streams": 4,
        "lookup_floor_ms": 2.0,
    },
    # ckpt_every=4 vs publish-every-2: the kill (3rd publish = step
    # 6) restores the step-4 snapshot and REPLAYS steps 5-6, so the
    # loss-trajectory invariant's multi-incarnation cross-check has
    # real replayed steps to agree on
    "serving-trainer-kill-midpublish": {
        "total_steps": 12,
        "ckpt_every": 4,
        "train_script": "sparse_serving",
        "extra_env": {
            "DLROVER_KV_DIGEST": "1",
            "DLROVER_CHAOS_PUB_EVERY": "2",
            "DLROVER_CHAOS_STEP_SLEEP": "0.2",
        },
    },
    # streaming reshard: the harness pre-seeds a committed world-2
    # sparse checkpoint at step 4 (seed_kv_world), the window is
    # pinned to 200 rows so the ~600-row-per-rank tables stream in
    # several chunks (the kill rule needs a 3rd chunk to land on),
    # and digests are armed for the exactly-once verdict
    "sparse-streaming-reshard-kill": {
        "total_steps": 10,
        "ckpt_every": 2,
        "train_script": "sparse_reshard",
        "seed_kv_world": 2,
        "extra_env": {
            "DLROVER_KV_DIGEST": "1",
            "DLROVER_KV_RESHARD_WINDOW_ROWS": "200",
        },
    },
    # elastic RL: 8 rollout leases = 8 PPO train steps (2 leases per
    # iteration), so total_steps doubles as the lease-dataset size and
    # the trainer's step budget; ckpt_every=2 is nominal — the RL loop
    # flash-saves after EVERY lease, and the kill on lease 2 restores
    # the post-lease-1 snapshot and replays PPO steps 1-2 before
    # regenerating the lost lease.  compile_cache gives the respawn
    # the AOT executable path for its actor/critic steps.
    "rl-rollout-worker-kill": {
        "total_steps": 8,
        "ckpt_every": 2,
        "train_script": "rl",
        "compile_cache": True,
    },
    # spill-disk death mid-export: same loop + budget; the kill lands
    # at step 7 so the step-6 export (post-breaker, spill_disabled
    # stamped) is the one the restore round-trips
    "sparse-spill-io-error": {
        "total_steps": 12,
        "ckpt_every": 2,
        "train_script": "sparse",
        "extra_env": {
            "DLROVER_KV_DIGEST": "1",
            "DLROVER_CHAOS_KV_SPILL": "48",
        },
    },
    # sparse resize: the elastic-resize recipe (same control-plane
    # knobs as elastic-resize-churn) with the kv-partitioned loop and
    # digests armed; disk commits every 3 steps bound the cross-world
    # restore's step loss AND guarantee a world-1 commit exists
    # before the harness respawns the replacement agent
    "sparse-resize-churn": {
        "total_steps": 24,
        "disk_every": 3,
        "step_sleep": 0.3,
        "train_script": "sparse_resize",
        "extra_env": {
            "DLROVER_KV_DIGEST": "1",
            "DLROVER_MONITOR_REPORT_INTERVAL": "0.5",
            "DLROVER_HANG_DETECTION_S": "2.5",
            "DLROVER_RESIZE_GRACE_S": "1.0",
            "DLROVER_RESIZE_REDELIVER_S": "15.0",
            "DLROVER_RESIZE_STOP_TIMEOUT_S": "1.5",
            "DLROVER_SECONDS_TO_CHECK_HANG": "0.5",
            "DLROVER_BREAKPOINT_COMMIT_TIMEOUT_S": "3",
            "DLROVER_MEMBERSHIP_SELF_RESTART": "0",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    },
    # invisible recovery: warm restarts + the framework preload so a
    # respawn pays fork+restore+aot only, a workdir-scoped
    # compile-cache dir (the harness materializes it; the AOT cache
    # rides under it) so the FIRST incarnation deterministically
    # pre-populates the replacement — it WRITES the serialized step
    # executable its replacement DESERIALIZES — and the forkserver
    # template pre-loads the entry bytes before each fork so the
    # replacement inherits them in memory.  The hit/miss, the
    # retrace+aot ceiling and the sub-second cycle are all decided
    # from the event log alone.
    "warm-recovery-cache-hit": {
        "warm_restart": True,
        "total_steps": 12,
        "ckpt_every": 2,
        "compile_cache": True,
        "extra_env": {
            "DLROVER_MONITOR_REPORT_INTERVAL": "0.5",
            "DLROVER_PRELOAD": TRAINER_PRELOAD,
            "DLROVER_AOT_PRETRACE": "1",
        },
    },
    # host-portable master: the respawn is forced onto a FRESH
    # journal dir (a replacement host's view) and must seed from the
    # storage-tier mirror (the harness materializes the mirror dir
    # via the journal_mirror knob); shard traffic armed so
    # exactly-once sharding is decidable from events
    "master-respawn-other-host": {
        "shard_dataset": True,
        "journal_mirror": True,
        "extra_env": {
            "DLROVER_MASTER_RESPAWN_FRESH_JOURNAL": "1",
            # tight group-commit window: the kill must not outrun the
            # mirror by more than one shard dispatch
            "DLROVER_JOURNAL_MIRROR_INTERVAL_S": "0.05",
        },
    },
    # hang diagnosis in seconds instead of half an hour: fast step
    # reporting, a 2 s agent watchdog window, a 3 s master hang
    # timeout and a sub-second master poll — the 90 s stall is
    # diagnosed, evidenced and culprit-restarted long before the
    # sleep could expire
    "trainer-hang-detected": {
        "extra_env": {
            "DLROVER_MONITOR_REPORT_INTERVAL": "0.5",
            "DLROVER_HANG_THRESHOLD_S": "2",
            "DLROVER_HANG_TIMEOUT": "3",
            "DLROVER_SECONDS_TO_CHECK_HANG": "0.5",
            # the 3 s hang timeout is smaller than a cold restart;
            # the post-restart grace keeps the recovery window from
            # re-convicting the fresh incarnation
            "DLROVER_HANG_RESTART_GRACE_S": "20",
        },
    },
}


def build(name: str, seed: Optional[int] = None) -> Scenario:
    if name not in SCENARIOS:
        raise KeyError(
            f"unknown scenario {name!r}; known: {sorted(SCENARIOS)}"
        )
    factory = SCENARIOS[name]
    return factory(seed) if seed is not None else factory()
