"""``python -m dlrover_tpu.chaos`` — scenario runner CLI.

Runs a built-in or file-provided scenario through the mini-cluster
harness and prints the fault timeline + invariant report; exit code 0
iff the job finished AND every invariant held.

Examples::

    python -m dlrover_tpu.chaos --list
    python -m dlrover_tpu.chaos --scenario kill_worker_midstep --seed 7
    python -m dlrover_tpu.chaos --spec my_scenario.yaml --steps 20
"""

import argparse
import json
import sys
import tempfile
from typing import List, Optional

from dlrover_tpu.chaos import harness, scenarios
from dlrover_tpu.chaos.schedule import load_scenario


def parse_args(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.chaos",
        description="deterministic fault-injection scenario runner",
    )
    src = parser.add_mutually_exclusive_group()
    src.add_argument(
        "--scenario", type=str, default="",
        help="built-in scenario name (see --list)",
    )
    src.add_argument(
        "--spec", type=str, default="",
        help="scenario YAML/JSON file (or inline JSON)",
    )
    parser.add_argument(
        "--seed", type=int, default=None,
        help="override the scenario seed",
    )
    parser.add_argument(
        "--workdir", type=str, default="",
        help="run directory (default: fresh temp dir)",
    )
    parser.add_argument(
        "--steps", type=int, default=None,
        help="step budget (default: the scenario's RUN_OPTIONS "
        "entry, else 10)",
    )
    parser.add_argument("--ckpt-every", type=int, default=None)
    parser.add_argument("--max-restarts", type=int, default=2)
    parser.add_argument(
        "--nnodes", type=int, default=1,
        help=">1 runs the multi-agent harness (one journal-backed "
        "master + N real tpurun agent processes) — what the "
        "node-subset partition scenarios need",
    )
    parser.add_argument(
        "--warm-restart", action="store_true",
        help="fork restarted workers from the warm template",
    )
    parser.add_argument(
        "--list", action="store_true", dest="list_scenarios",
        help="list built-in scenarios and exit",
    )
    parser.add_argument(
        "--show", action="store_true",
        help="print the resolved scenario spec and exit",
    )
    return parser.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.list_scenarios:
        for name in sorted(scenarios.SCENARIOS):
            doc = (scenarios.SCENARIOS[name].__doc__ or "").strip()
            print(f"{name}: {doc.splitlines()[0] if doc else ''}")
        return 0
    if args.spec:
        scenario = load_scenario(args.spec)
        if args.seed is not None:
            scenario.seed = args.seed
    else:
        name = args.scenario or "kill_worker_midstep"
        scenario = scenarios.build(name, seed=args.seed)
    if args.show:
        print(json.dumps(scenario.to_dict(), indent=2))
        return 0
    workdir = args.workdir or tempfile.mkdtemp(prefix="dlrover_chaos_")
    print(
        f"running scenario {scenario.name!r} (seed {scenario.seed}) "
        f"in {workdir}"
    )
    nnodes = args.nnodes
    if nnodes <= 1 and scenario.name in (
        "multinode-rpc-partition", "multinode-hang-culprit",
        "elastic-resize-churn", "sparse-resize-churn",
    ):
        # the subset-fault scenarios are meaningless single-node
        nnodes = 2
    if scenario.name in (
        "elastic-resize-churn", "sparse-resize-churn",
    ):
        # needs the elastic runner: a min_nodes<nnodes master, a
        # shared checkpoint dir, and the replacement-agent respawn
        report = harness.run_elastic_resize_scenario(
            scenario,
            workdir=workdir,
            nnodes=nnodes,
            total_steps=args.steps,
            max_restarts=args.max_restarts,
        )
    elif scenario.name == "serving-fleet-replica-kill":
        # needs the fleet runner: an in-process publisher, a
        # supervised router subprocess and a replica pool under
        # synthetic routed load
        report = harness.run_serving_fleet_scenario(
            scenario, workdir=workdir,
        )
    elif scenario.name in (
        "serving-replica-kill-midingest",
        "serving-trainer-kill-midpublish",
    ):
        # needs the serving runner: the mini-cluster plus a
        # supervised read-only replica subprocess ingesting the
        # published generations under lookup traffic
        report = harness.run_serving_scenario(
            scenario,
            workdir=workdir,
            total_steps=args.steps,
            max_restarts=args.max_restarts,
        )
    elif nnodes > 1:
        report = harness.run_scenario_multinode(
            scenario,
            workdir=workdir,
            nnodes=nnodes,
            total_steps=args.steps,
            ckpt_every=args.ckpt_every,
            max_restarts=args.max_restarts,
            warm_restart=args.warm_restart,
            faulted_rank=(
                1 if scenario.name == "multinode-rpc-partition"
                else None
            ),
        )
    else:
        report = harness.run_scenario(
            scenario,
            workdir=workdir,
            total_steps=args.steps,
            ckpt_every=args.ckpt_every,
            max_restarts=args.max_restarts,
            warm_restart=args.warm_restart,
        )
    print(report.summary())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
