"""Hybrid train/rollout layouts for RLHF (reference:
``atorch/rl/ds_hybrid_engine/`` + ``atorch/rl/model_engine/
model_engine.py:35``).

The reference's hybrid engine keeps the actor in a TRAINING layout
(ZeRO/FSDP-sharded) and swaps it into an INFERENCE layout (tensor
slicing, no optimizer state) for generation, because the two phases
want opposite shardings: training wants parameters scattered to fit
optimizer state, autoregressive decode wants them tensor-sliced so
each matmul of the (batch-1) token step is wide on every chip.

The TPU translation is a single primitive: ``jax.device_put`` with
the target layout's ``NamedSharding`` tree.  XLA emits exactly the
all-gather / all-to-all needed to re-tile each leaf — there is no
hand-written gather/scatter like the DS hybrid engine's — and the
swap is timed so the rollout-amortization tradeoff is visible.
"""

import time
from typing import Any, Dict, List, Optional

import jax

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.sharding import (
    PartitionRules,
    gpt_tp_rules,
    sharding_tree,
)
from dlrover_tpu.rl.model_engine import ModelRole, RLModelEngine


class HybridRolloutEngine:
    """Reshard the actor between its train layout and a rollout
    layout.

    Parameters
    ----------
    engine:
        the built :class:`RLModelEngine` (owns the actor's train-state
        in its training sharding).
    rollout_mesh:
        the mesh generation runs on — may have a different axis
        factorization from the training mesh (e.g. train dp4xfsdp2,
        rollout tp8), as long as it covers the same devices.
    rollout_rules:
        parameter partition rules for the decode layout; defaults to
        the GPT tensor-parallel rules (column/row sliced matmuls).
    """

    def __init__(
        self,
        engine: RLModelEngine,
        rollout_mesh,
        rollout_rules: Optional[PartitionRules] = None,
    ):
        self._engine = engine
        self.rollout_mesh = rollout_mesh
        self.rollout_rules = rollout_rules or gpt_tp_rules()
        self.reshard_times: List[float] = []
        self._target_shardings = None

    def reshard_actor_for_rollout(self):
        """Actor train-layout params -> rollout-layout params.

        One timed ``device_put`` against the cached target sharding
        tree; the result is a COPY in the rollout layout, so the train
        state (whose buffers the train step donates) stays untouched.
        """
        params = self._engine.state(ModelRole.ACTOR).params
        if self._target_shardings is None:
            self._target_shardings = sharding_tree(
                params, self.rollout_mesh, self.rollout_rules
            )
        t0 = time.perf_counter()
        out = jax.device_put(params, self._target_shardings)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self.reshard_times.append(dt)
        # the engine's per-role accounting sees every layout
        # transition, including this external one
        self._engine.record_reshard(ModelRole.ACTOR, dt)
        logger.debug("actor train->rollout reshard: %.4fs", dt)
        return out

    def place_rollout_batch(self, batch):
        """Prompts/rng onto the rollout mesh: batch dim over 'data'
        where the mesh has it and the size divides, replicated
        otherwise (shard_pytree applies the same fallback rules as
        the param resharding)."""
        from dlrover_tpu.parallel.sharding import shard_pytree

        return shard_pytree(
            batch, self.rollout_mesh,
            PartitionRules(default=("data",)),
        )

    def stats(self) -> Dict[str, Any]:
        ts = self.reshard_times
        return {
            "reshards": len(ts),
            "last_reshard_s": round(ts[-1], 4) if ts else None,
            "mean_reshard_s": (
                round(sum(ts) / len(ts), 4) if ts else None
            ),
        }
