"""Autoregressive rollout generation with a KV cache.

Reference capability: the RLHF engine's actor generation
(``atorch/rl/model_engine/model_engine.py:35`` drives HF
``generate``-style sampling for rollouts).  The TPU version runs the
model in decode mode (``GPTConfig.decode=True`` — attention keeps a
"cache" collection): one prefill pass over the prompt, then a
``lax.scan`` of single-token steps, all inside one jit.  Returns the
sampled sequences and their per-token logprobs (the "old" policy
logprobs PPO needs).
"""

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def decode_variant(model):
    """The same architecture/params with the KV-cache decode path."""
    cfg = dataclasses.replace(model.config, decode=True)
    return type(model)(cfg)


@functools.partial(
    jax.jit, static_argnames=("model", "max_new_tokens", "temperature")
)
def generate(
    model,
    params,
    prompts: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 16,
    temperature: float = 1.0,
) -> Tuple[jax.Array, jax.Array]:
    """Sample continuations of ``prompts`` [b, prompt_len].

    Returns (sequences [b, prompt_len + max_new_tokens],
    logprobs [b, max_new_tokens] of the sampled tokens).
    ``model`` must be the decode variant (``decode_variant``).
    """
    b, prompt_len = prompts.shape
    max_len = model.config.max_seq_len
    if prompt_len + max_new_tokens > max_len:
        raise ValueError(
            f"prompt {prompt_len} + {max_new_tokens} new tokens "
            f"exceeds max_seq_len {max_len}: the KV cache would "
            "silently clamp and corrupt decoding"
        )

    # prefill: one chunked pass writes the prompt into the cache
    logits, vars_ = model.apply(
        {"params": params}, prompts, mutable=["cache"]
    )
    cache = vars_["cache"]

    def sample(logits_last, rng):
        if temperature <= 0.0:
            tok = jnp.argmax(logits_last, axis=-1)
        else:
            tok = jax.random.categorical(
                rng, logits_last / temperature, axis=-1
            )
        logp = jax.nn.log_softmax(logits_last, axis=-1)
        tok_logp = jnp.take_along_axis(
            logp, tok[:, None], axis=-1
        )[:, 0]
        return tok.astype(prompts.dtype), tok_logp

    rng, sub = jax.random.split(rng)
    tok, tok_logp = sample(logits[:, -1], sub)

    def step(carry, _):
        cache, tok, tok_logp, rng = carry
        logits, vars_ = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt, nxt_logp = sample(logits[:, -1], sub)
        return (vars_["cache"], nxt, nxt_logp, rng), (tok, tok_logp)

    (_, last_tok, last_logp, _), (toks, logps) = jax.lax.scan(
        step, (cache, tok, tok_logp, rng), None,
        length=max_new_tokens - 1,
    )
    # scan emits the INPUT token of each step; append the final sample
    new_tokens = jnp.concatenate(
        [toks.T, last_tok[:, None]], axis=1
    )
    new_logps = jnp.concatenate(
        [logps.T, last_logp[:, None]], axis=1
    )
    sequences = jnp.concatenate([prompts, new_tokens], axis=1)
    return sequences, new_logps
