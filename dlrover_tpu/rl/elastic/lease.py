"""Rollout leases: deterministic batch derivation + AOT step routing.

A rollout lease is one master-dispatched shard task (the journaled
dispatch/ack/requeue machinery of
:class:`~dlrover_tpu.master.task_manager.TaskManager`) whose id IS
the rollout's identity: prompts and the generation RNG both derive
purely from the lease id, so a lease requeued off a dead worker and
regenerated on its replacement produces the bit-identical experience
batch — exactly-once rollout semantics without any rollout-side
journal.
"""

from typing import Dict, Optional

import numpy as np


def lease_prompts(
    lease_id: int,
    batch_size: int,
    prompt_len: int,
    vocab_size: int,
    base_seed: int = 20_000,
) -> np.ndarray:
    """The prompt batch of one rollout lease — a pure function of the
    lease id (counter-based PRNG), never of worker identity or
    restart history."""
    rng = np.random.default_rng(base_seed + int(lease_id))
    return rng.integers(
        0, vocab_size, (batch_size, prompt_len), dtype=np.int32
    )


def lease_rng(seed: int, lease_id: int):
    """The generation PRNG key of one rollout lease: ``fold_in`` of
    the job seed with the lease id — replayable on any incarnation,
    independent of how many leases this worker saw before."""
    import jax

    return jax.random.fold_in(
        jax.random.PRNGKey(int(seed)), int(lease_id)
    )


def resolve_role_steps(
    engine,
    batch: Dict,
    roles=None,
    cache_dir: Optional[str] = None,
    label_prefix: str = "rl",
) -> Dict[str, object]:
    """Route the trainable roles' train steps through the AOT
    executable cache (:func:`dlrover_tpu.common.aot_cache.
    resolve_step`) so an RL respawn deserializes its compiled
    actor/critic steps instead of re-tracing them — the same
    retrace-free recovery the dense loop gets.

    Returns ``{role: Resolution}``; call ``resolved[role].fn(state,
    placed_batch)`` exactly like ``engine.train_step(role)``.  Each
    role gets its own label (``rl_actor_step`` / ``rl_critic_step``),
    so the warm fast path resolves per role without example builds."""
    from dlrover_tpu.common.aot_cache import resolve_step
    from dlrover_tpu.rl.model_engine import ModelRole

    if roles is None:
        roles = ModelRole.TRAINABLE
    resolved = {}
    for role in roles:
        def example_args(role=role):
            return (
                engine.state(role),
                engine.place_batch(role, batch),
            )

        resolved[role] = resolve_step(
            engine.train_step(role),
            example_args,
            label=f"{label_prefix}_{role}_step",
            cache_dir=cache_dir,
        )
    return resolved
