"""PPO-iteration flash checkpoints: the four-role state rides the
flash engine through the sparse-adapter contract.

:class:`PPOStateAdapter` duck-types the surface
:class:`~dlrover_tpu.checkpoint.sparse.SparseStateAdapter` exposes to
:class:`~dlrover_tpu.checkpoint.engine.CheckpointEngine` —
``export_for_checkpoint`` / ``import_state`` / the delta-chain and
cross-world hooks — so ``Checkpointer.register_sparse`` accepts it
unchanged and the PPO state nests under the reserved ``__kv__`` key of
every flash snapshot, alongside whatever dense state the script saves.

What one snapshot carries (the ISSUE-16 contract):

- both trainable roles' FULL train states (params + optimizer slots +
  step counters) as donation-safe host copies;
- the PPO cursor: rollout leases completed, PPO updates taken, and
  the loop's RNG key — the coordinates a replacement needs to resume
  at the last completed rollout lease rather than iteration start;
- the partially-accumulated rollout buffer (the experience batches of
  the in-flight iteration), so a mid-iteration kill loses at most the
  single lease that was being generated — and THAT lease requeues
  through the master and regenerates bit-identically.

Cross-world restores ride the engine's storage-tier path: the import
rebuilds each role against the engine's CURRENT train state as the
template, so ``restore_to_template``'s batched ``device_put`` lands
the actor's GSPMD state on the new world's shardings (the reshard is
one placement, exactly like the dense path).
"""

import time
from typing import Any, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.rl.model_engine import ModelRole

ROLES_KEY = "__roles__"
BUFFER_KEY = "__buffer__"
CURSOR_KEY = "__cursor__"


class PPOCursor:
    """Where the PPO loop is, in lease coordinates.

    ``leases_done`` counts rollout leases whose batch is IN the
    buffer (or already trained on); ``ppo_updates`` counts completed
    PPO train steps (the trainer's global step); ``rng_key`` is the
    loop's root PRNG key as host numpy.  All three ride every flash
    snapshot and come back on restore, so the replacement's very
    first action — skip-and-ack an already-buffered lease, or train
    on the restored buffer — is decided by the cursor, not by
    guesswork."""

    def __init__(self, leases_done: int = 0, ppo_updates: int = 0,
                 rng_key: Optional[np.ndarray] = None):
        self.leases_done = int(leases_done)
        self.ppo_updates = int(ppo_updates)
        self.rng_key = (
            None if rng_key is None else np.array(rng_key)
        )

    def to_state(self) -> Dict[str, Any]:
        out = {
            "leases_done": int(self.leases_done),
            "ppo_updates": int(self.ppo_updates),
        }
        if self.rng_key is not None:
            out["rng_key"] = np.array(self.rng_key)
        return out

    def load_state(self, state: Dict[str, Any]) -> None:
        self.leases_done = int(np.asarray(state["leases_done"]))
        self.ppo_updates = int(np.asarray(state["ppo_updates"]))
        key = state.get("rng_key")
        self.rng_key = None if key is None else np.array(key)


class PPOStateAdapter:
    """Checkpoint adapter for an :class:`RLModelEngine` + replay
    buffer + :class:`PPOCursor`.

    ``include_roles=True`` (the default) carries the trainable roles'
    train states in the snapshot — correct for replicated-host PPO
    state (the single-worker RL job, or per-rank identical state).
    Multi-host GSPMD actors should instead save their sharded train
    state through the DENSE state dict (per-rank shards) and run the
    adapter with ``include_roles=False`` so only buffer + cursor ride
    the ``__kv__`` subtree."""

    def __init__(self, engine, buffer=None, cursor=None,
                 roles=(ModelRole.ACTOR, ModelRole.CRITIC),
                 include_roles: bool = True):
        self._engine = engine
        self._buffer = buffer
        self.cursor = cursor if cursor is not None else PPOCursor()
        self._role_names = tuple(roles)
        self._include_roles = include_roles

    # -- export --------------------------------------------------------------

    def export_state(self) -> Dict[str, Any]:
        """The PPO subtree of one flash snapshot: plain numpy leaves
        only (forced host copies — the train steps DONATE their
        state, so a zero-copy view would be invalidated by the next
        step while the async writer still reads it)."""
        import jax

        out: Dict[str, Any] = {CURSOR_KEY: self.cursor.to_state()}
        if self._include_roles:
            out[ROLES_KEY] = {
                role: jax.tree.map(
                    lambda x: np.array(x), self._engine.state(role)
                )
                for role in self._role_names
            }
        batches: Dict[str, Any] = {}
        if self._buffer is not None:
            for i, batch in enumerate(self._buffer_batches()):
                batches[f"b{i:04d}"] = {
                    k: np.array(v) for k, v in batch.items()
                }
        if batches:
            out[BUFFER_KEY] = batches
        out[CURSOR_KEY]["buffer_batches"] = len(batches)
        return out

    def _buffer_batches(self) -> List[Dict[str, np.ndarray]]:
        if self._buffer is None:
            return []
        if hasattr(self._buffer, "batches"):
            return self._buffer.batches()
        return list(self._buffer._batches)

    def export_for_checkpoint(
        self, step: Optional[int] = None,
        rank: Optional[int] = None, durable: bool = False,
    ) -> Dict[str, Any]:
        """Engine entry point (mirrors the sparse adapter): every
        save exports the full PPO subtree — there is no delta mode;
        the state is a few MB of tiny-role params + buffer, and the
        shm segment must stand alone."""
        return self.export_state()

    # -- import --------------------------------------------------------------

    def import_state(
        self, state: Dict[str, Any], tier: str = "",
        step: Optional[int] = None, rank: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Rebuild engine states, buffer and cursor from a restored
        (plain-nested-dict) subtree.  Role states rebuild against the
        engine's CURRENT states as templates — ``restore_to_template``
        re-types the optax containers and ``device_put``s onto the
        current shardings, which IS the cross-world reshard when the
        template's layout differs from the writer's."""
        from dlrover_tpu.checkpoint.checkpointer import (
            restore_to_template,
        )

        t0 = time.perf_counter()
        roles = state.get(ROLES_KEY)
        restored_roles = 0
        if self._include_roles and roles:
            for role in self._role_names:
                saved = roles.get(role)
                if saved is None:
                    logger.warning(
                        "PPO checkpoint step %s carries no %r role "
                        "state; role left at its fresh init",
                        step, role,
                    )
                    continue
                template = self._engine.state(role)
                self._engine.set_state(
                    role, restore_to_template(template, saved)
                )
                restored_roles += 1
        rows = 0
        if self._buffer is not None:
            self._buffer.reset()
            batches = state.get(BUFFER_KEY) or {}
            for name in sorted(batches):
                self._buffer.add(batches[name])
            rows = int(self._buffer.num)
        cursor_state = state.get(CURSOR_KEY)
        if cursor_state:
            want = cursor_state.pop("buffer_batches", None)
            self.cursor.load_state(cursor_state)
            if want is not None and self._buffer is not None:
                got = len(self._buffer._batches)
                if int(np.asarray(want)) != got:
                    raise RuntimeError(
                        f"PPO checkpoint step {step} is torn: cursor "
                        f"says {int(np.asarray(want))} buffered "
                        f"batch(es), snapshot carries {got}"
                    )
        seconds = time.perf_counter() - t0
        logger.info(
            "PPO state restored from %s step %s: %d role(s), %d "
            "buffered sample(s), cursor leases=%d updates=%d "
            "(%.3fs)",
            tier or "?", step, restored_roles, rows,
            self.cursor.leases_done, self.cursor.ppo_updates,
            seconds,
        )
        # lands in stats.extra -> the checkpoint_restore event and
        # the timeline's "+kv" restore stage, same as sparse tables
        return {
            "kv_s": round(seconds, 4),
            "kv_rows": rows,
            "rl_roles": restored_roles,
        }

    # -- delta-chain / cross-world hooks (engine contract) -------------------

    def delta_checkpoints_enabled(self) -> bool:
        return False

    def delta_full_every(self) -> int:
        return 0

    def checkpoint_chain_poison(self) -> None:
        """No delta chain to poison — every export is a full base."""

    def import_chain(
        self, links: List[Dict[str, Any]], tier: str = "",
        step: Optional[int] = None, rank: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Defensive: every PPO export is a full base, so a 'chain'
        restore is just its newest link."""
        return self.import_state(
            links[-1], tier=tier, step=step, rank=rank
        )

    def import_shards_streaming(
        self, chains: Dict[int, List[Dict[str, Any]]],
        world_size: int = 1, rank: int = 0, from_world: int = 1,
        tier: str = "storage", step: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Cross-world restore: PPO host state is replicated across
        ranks (unlike kv shards), so any old rank's newest link is the
        whole state — import rank 0's (or the lowest present) and let
        ``restore_to_template`` place it on the new world's
        shardings."""
        if not chains:
            raise RuntimeError(
                f"cross-world PPO restore of step {step}: no source "
                "shards readable"
            )
        src = chains[min(chains)]
        info = self.import_state(
            src[-1], tier=tier, step=step, rank=rank
        )
        info["rl_from_world"] = int(from_world)
        return info
