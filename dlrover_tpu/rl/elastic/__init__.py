"""Elastic control plane for the PPO loop (ISSUE 16).

Three pieces make the four-role RLHF workload
(:mod:`dlrover_tpu.rl`) a first-class elastic citizen:

- **rollout leases** (:mod:`.lease`): rollout batches are
  master-dispatched shard leases — a dead rollout worker's in-flight
  batch requeues through the journaled dispatch/ack machinery and is
  REGENERATED bit-identically (the batch is a pure function of the
  lease id), so exactly-once rollout accounting is decidable from
  ``shard_dispatch``/``shard_ack`` events alone;
- **PPO-iteration flash checkpoints** (:mod:`.adapter`): the full
  four-role state (actor+critic train states, RNG key, iteration
  cursor, the partially-accumulated rollout buffer) rides the flash
  engine through a :class:`PPOStateAdapter` duck-typing the sparse
  adapter contract, so a mid-iteration kill restores to the last
  completed rollout lease instead of iteration start;
- **retrace-free recovery** (:func:`.lease.resolve_role_steps`): the
  actor/critic train steps route through the AOT executable cache,
  so an RL respawn deserializes its compiled steps like the dense
  loop does.
"""

from dlrover_tpu.rl.elastic.adapter import PPOCursor, PPOStateAdapter
from dlrover_tpu.rl.elastic.lease import (
    lease_prompts,
    lease_rng,
    resolve_role_steps,
)

__all__ = [
    "PPOCursor",
    "PPOStateAdapter",
    "lease_prompts",
    "lease_rng",
    "resolve_role_steps",
]
