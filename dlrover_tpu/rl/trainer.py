"""RL trainer loop: experience generation -> replay buffer -> PPO
epochs, with engine layout transitions.

Reference: ``atorch/rl/trainer/rl_trainer.py`` (the
make-experience / rl-training cycle with pre/post hooks and a replay
buffer filled to ``num_rollouts`` before each training phase) +
``atorch/rl/replay_buffer/replay_buffer.py`` +
``atorch/rl/config.py`` (YAML-loaded training config).

TPU shape: experience batches are host numpy pytrees (the buffer is
host memory, like the reference's), PPO epochs re-place shuffled
minibatches through the engine's sharded train steps, and when a
:class:`~dlrover_tpu.rl.hybrid_engine.HybridRolloutEngine` is
attached the actor is resharded into its rollout layout ONCE per
experience phase (amortized across every rollout in the phase — the
reference's engine-state transition, not a per-batch swap).
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.rl.model_engine import RLModelEngine
from dlrover_tpu.rl.rollout import make_experience, train_on_batch


class ReplayBuffer:
    """Host-side experience store (reference: ReplayBuffer).

    Samples are dicts of equal-leading-dim numpy arrays; minibatches
    come back shuffled across everything accumulated in the phase.
    """

    def __init__(self):
        self._batches: List[Dict[str, np.ndarray]] = []
        self._merged: Optional[Dict[str, np.ndarray]] = None
        self.num = 0

    def add(self, batch: Dict[str, Any]) -> None:
        host = {k: np.asarray(v) for k, v in batch.items()}
        n = next(iter(host.values())).shape[0]
        for k, v in host.items():
            if v.shape[0] != n:
                raise ValueError(
                    f"ragged batch: {k} has leading dim "
                    f"{v.shape[0]} != {n}"
                )
        self._batches.append(host)
        self._merged = None
        self.num += n

    def reset(self) -> None:
        self._batches = []
        self._merged = None
        self.num = 0

    def batches(self) -> List[Dict[str, np.ndarray]]:
        """The accumulated batches in INSERTION order — the elastic
        plane's checkpoint adapter exports these, and the
        deterministic chaos loop trains on them in this order so a
        restored incarnation replays identical PPO steps."""
        return list(self._batches)

    def minibatches(self, batch_size: int, rng: np.random.Generator):
        """Shuffled minibatches over the whole buffer; a short final
        remainder is dropped (jitted steps need static shapes)."""
        if not self._batches:
            return
        if self._merged is None:
            keys = self._batches[0].keys()
            self._merged = {
                k: np.concatenate([b[k] for b in self._batches])
                for k in keys
            }
        data = self._merged
        order = rng.permutation(self.num)
        for i in range(self.num // batch_size):
            idx = order[i * batch_size:(i + 1) * batch_size]
            yield {k: v[idx] for k, v in data.items()}


@dataclass
class RLTrainConfig:
    """Training-loop knobs (reference: atorch/rl/config.py train +
    ppo_config sections; YAML-loadable via :meth:`from_yaml`)."""

    epochs: int = 1
    num_rollouts: int = 64        # buffer fill before each training
    ppo_epochs: int = 4           # passes over the buffer per phase
    train_batch_size: int = 8
    max_new_tokens: int = 16
    temperature: float = 1.0
    kl_coef: float = 0.05
    gamma: float = 1.0
    lam: float = 0.95
    seed: int = 0
    extra: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_yaml(cls, path: str) -> "RLTrainConfig":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        known = {
            k: v for k, v in raw.items()
            if k in cls.__dataclass_fields__ and k != "extra"
        }
        extra = {
            k: v for k, v in raw.items()
            if k not in cls.__dataclass_fields__
        }
        return cls(**known, extra=extra)


class RLTrainer:
    """The experience/training cycle (reference: RLTrainer.train).

    Subclasses implement :meth:`make_experience` (fill the buffer
    from a prompt batch) and :meth:`rl_training` (consume the
    buffer); hooks mark the phase transitions — the hybrid layout
    swap lives in them.
    """

    def __init__(
        self,
        engine: RLModelEngine,
        config: RLTrainConfig,
        hybrid=None,
    ):
        self.engine = engine
        self.config = config
        self.hybrid = hybrid
        self.replay_buffer = ReplayBuffer()
        self.metrics_history: List[Dict[str, float]] = []
        self._np_rng = np.random.default_rng(config.seed)

    # -- phase hooks -------------------------------------------------------

    def pre_experience_hook(self):
        """Entering the experience phase: swap the actor into its
        rollout layout ONCE — every rollout of the phase reuses the
        copy (the actor only trains between phases)."""
        if self.hybrid is not None:
            self._rollout_params = (
                self.hybrid.reshard_actor_for_rollout()
            )

    def post_experience_hook(self):
        # drop the rollout-layout param copy (full actor size)
        self._rollout_params = None

    def pre_training_hook(self):
        pass

    def post_training_hook(self):
        self.replay_buffer.reset()

    # -- to be implemented by subclasses -----------------------------------

    def make_experience(self, prompts, rng) -> Dict[str, float]:
        raise NotImplementedError

    def rl_training(self) -> Dict[str, float]:
        raise NotImplementedError

    # -- the cycle ---------------------------------------------------------

    def train(self, prompt_batches) -> List[Dict[str, float]]:
        """Run ``config.epochs`` passes over ``prompt_batches``
        (an iterable of prompt arrays): fill the buffer to
        ``num_rollouts``, then run ``ppo_epochs`` of training, and
        repeat (reference: RLTrainer.train's tqdm loop)."""
        import jax

        # a generator would be exhausted after epoch 0 and epochs
        # 1..n would silently train on nothing
        prompt_list = list(prompt_batches)
        rng = jax.random.PRNGKey(self.config.seed)
        in_experience = False
        try:
            for epoch in range(self.config.epochs):
                for prompts in prompt_list:
                    if not in_experience:
                        self.pre_experience_hook()
                        in_experience = True
                    rng, sub = jax.random.split(rng)
                    exp_metrics = self.make_experience(prompts, sub)
                    if (
                        self.replay_buffer.num
                        >= self.config.num_rollouts
                    ):
                        self.post_experience_hook()
                        in_experience = False
                        self.pre_training_hook()
                        train_metrics = self.rl_training()
                        self.post_training_hook()
                        self.metrics_history.append(
                            {"epoch": epoch, **exp_metrics,
                             **train_metrics}
                        )
                # drain a partial buffer at epoch end
                if self.replay_buffer.num > 0:
                    if in_experience:
                        self.post_experience_hook()
                        in_experience = False
                    self.pre_training_hook()
                    train_metrics = self.rl_training()
                    self.post_training_hook()
                    self.metrics_history.append(
                        {"epoch": epoch, **train_metrics}
                    )
        finally:
            if in_experience:
                # never retain the rollout-layout param copy
                self.post_experience_hook()
        return self.metrics_history


class PPOTrainer(RLTrainer):
    """PPO over the four-role engine (reference: PPOTrainer).

    ``reward_fn(sequences) -> [b]`` overrides the reward role.
    """

    def __init__(
        self,
        engine: RLModelEngine,
        config: RLTrainConfig,
        reward_fn: Optional[Callable] = None,
        hybrid=None,
    ):
        super().__init__(engine, config, hybrid=hybrid)
        self.reward_fn = reward_fn
        sample = getattr(engine, "_sample_batch", None)
        if isinstance(sample, dict) and "tokens" in sample:
            built_b = sample["tokens"].shape[0]
            if config.train_batch_size != built_b:
                raise ValueError(
                    f"train_batch_size {config.train_batch_size} != "
                    f"the engine's built batch dim {built_b}: the "
                    "jitted sharded steps have static shapes — build "
                    "the engine with a sample batch of the training "
                    "minibatch size"
                )

    def make_experience(self, prompts, rng) -> Dict[str, float]:
        cfg = self.config
        batch, metrics = make_experience(
            self.engine, prompts, rng,
            max_new_tokens=cfg.max_new_tokens,
            temperature=cfg.temperature,
            kl_coef=cfg.kl_coef, gamma=cfg.gamma, lam=cfg.lam,
            reward_fn=self.reward_fn,
            # the phase hook resharded once; every rollout of the
            # phase reuses that copy
            hybrid=self.hybrid,
            rollout_params=getattr(self, "_rollout_params", None),
        )
        self.replay_buffer.add(batch)
        return metrics

    def rl_training(self) -> Dict[str, float]:
        cfg = self.config
        losses: Dict[str, List[float]] = {}
        steps = 0
        for _ in range(cfg.ppo_epochs):
            for mb in self.replay_buffer.minibatches(
                cfg.train_batch_size, self._np_rng
            ):
                out = train_on_batch(self.engine, mb)
                steps += 1
                for k, v in out.items():
                    losses.setdefault(k, []).append(v)
        if steps == 0:
            logger.warning(
                "rl_training ran with an empty buffer (buffer %d < "
                "train_batch_size %d?)",
                self.replay_buffer.num, cfg.train_batch_size,
            )
            return {"ppo_steps": 0}
        out = {
            k: float(np.mean(v)) for k, v in losses.items()
        }
        out["ppo_steps"] = steps
        return out
