"""RL model engine: per-role models with PER-ROLE strategies.

Reference: ``ModelEngine`` (``atorch/rl/model_engine/
model_engine.py:35``) manages actor/critic/ref/reward models, each
accelerated with its OWN ATorch strategy (the reference's
``auto_accelerate`` runs per model-type).  The TPU engine builds:

- trainable roles (actor, critic): an accelerated sharded train step
  via :func:`dlrover_tpu.accel.auto_accelerate` — each role either
  declares an explicit :class:`Strategy` or opts into the bounded
  strategy SEARCH (``RoleSpec.search=True``), so the inference-heavy
  critic can land on a different sharding/remat than the actor;
- frozen roles (ref, reward): a jitted apply, optionally under an
  explicit inference layout (``RoleSpec.mesh`` + ``RoleSpec.rules``
  — e.g. tensor-sliced for wide single-token matmuls) instead of
  replicated.

All four can share one device set (per-role strategies emit
compatible meshes over the same chips) — on TPU the roles are
time-multiplexed rather than placed on separate GPU groups.  Moving
state between role layouts (e.g. refreshing the frozen ref from the
actor) is one ``device_put`` per leaf; the engine times those
transitions per role in :attr:`reshard_stats`.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax

from dlrover_tpu.accel import Strategy, auto_accelerate
from dlrover_tpu.common.log import default_logger as logger


class ModelRole:
    ACTOR = "actor"
    CRITIC = "critic"
    REF = "ref"
    REWARD = "reward"

    TRAINABLE = (ACTOR, CRITIC)
    FROZEN = (REF, REWARD)


@dataclass
class RoleSpec:
    model: Any
    loss_fn: Optional[Callable] = None       # trainable roles
    optim_factory: Optional[Callable] = None
    strategy: Optional[Strategy] = None
    params: Any = None                       # frozen roles: given params
    # per-role strategy SEARCH (trainable roles): generate/prune/rank
    # candidates for THIS role's model+loss instead of accepting the
    # declared strategy — reference ModelEngine accelerates each role
    # with its own searched strategy
    search: bool = False
    rank_mode: str = "cost_model"   # chip-free default for searches
    cost_budget: int = 0
    # frozen roles: explicit inference layout (mesh + partition
    # rules); None = replicated jit (single-chip shape)
    mesh: Any = None
    rules: Any = None


class RLModelEngine:
    def __init__(self, sample_batch, roles: Dict[str, RoleSpec]):
        self._sample_batch = sample_batch
        self._roles = roles
        self._accel: Dict[str, Any] = {}
        self._frozen_apply: Dict[str, Callable] = {}
        self._frozen_params: Dict[str, Any] = {}
        self._frozen_shardings: Dict[str, Any] = {}
        # per-role layout-transition timings (seconds), e.g. the
        # ref refresh from the actor's train layout
        self.reshard_stats: Dict[str, List[float]] = {}

    def build(self):
        for name, spec in self._roles.items():
            if name in ModelRole.TRAINABLE:
                if spec.loss_fn is None or spec.optim_factory is None:
                    raise ValueError(
                        f"trainable role {name} needs loss_fn and "
                        "optim_factory"
                    )
                if spec.search:
                    # this role's own bounded search: candidates are
                    # generated against ITS model/loss, so e.g. the
                    # critic (scalar head, no generation) ranks a
                    # different winner than the actor
                    self._accel[name] = auto_accelerate(
                        spec.model,
                        spec.optim_factory,
                        spec.loss_fn,
                        self._sample_batch,
                        strategy=None,
                        dry_run_candidates=True,
                        rank_mode=spec.rank_mode,
                        cost_budget=spec.cost_budget,
                    )
                else:
                    self._accel[name] = auto_accelerate(
                        spec.model,
                        spec.optim_factory,
                        spec.loss_fn,
                        self._sample_batch,
                        strategy=spec.strategy
                        or Strategy(opts=[("parallel_mode", {})]),
                        dry_run_candidates=False,
                    )
                logger.info(
                    "built trainable role %s with strategy %s%s",
                    name, self._accel[name].strategy.names(),
                    " (searched)" if spec.search else "",
                )
            else:
                params = (
                    spec.params
                    if spec.params is not None
                    else spec.model.init_params(jax.random.PRNGKey(0))
                )
                if spec.mesh is not None:
                    # explicit inference layout: tensor-sliced (or
                    # whatever the rules say) params instead of a
                    # replicated copy per chip
                    from dlrover_tpu.parallel.sharding import (
                        sharding_tree,
                    )

                    shardings = sharding_tree(
                        params, spec.mesh,
                        spec.rules if spec.rules is not None
                        else _default_frozen_rules(),
                    )
                    params = jax.device_put(params, shardings)
                    self._frozen_shardings[name] = shardings
                self._frozen_params[name] = params
                model = spec.model

                def apply_fn(p, batch, model=model):
                    return model.apply({"params": p}, batch)

                self._frozen_apply[name] = jax.jit(apply_fn)
        return self

    # -- accessors ---------------------------------------------------------

    def train_step(self, role: str):
        return self._accel[role].train_step

    def state(self, role: str):
        return self._accel[role].state

    def set_state(self, role: str, state):
        self._accel[role].state = state

    def place_batch(self, role: str, batch):
        return self._accel[role].place_batch(batch)

    def infer(self, role: str, inputs):
        """Frozen-role forward (ref logprobs / reward scores)."""
        return self._frozen_apply[role](
            self._frozen_params[role], inputs
        )

    def sync_ref_from_actor(self):
        """Refresh the frozen reference policy from the actor (the
        periodic ref update some RLHF recipes use).  A real device
        copy, not aliasing: the actor's train step donates its state,
        so held references to the live params would be invalidated on
        the next step.  When the ref has its own inference layout the
        copy is a cross-layout reshard (one device_put against the
        ref's sharding tree — XLA inserts the collectives); the
        transition is timed into :attr:`reshard_stats`."""
        import time

        import jax.numpy as jnp

        actor_params = self._accel[ModelRole.ACTOR].state.params
        t0 = time.perf_counter()
        shardings = self._frozen_shardings.get(ModelRole.REF)
        if shardings is not None:
            out = jax.device_put(actor_params, shardings)
        else:
            out = jax.tree.map(jnp.copy, actor_params)
        jax.block_until_ready(out)
        self.reshard_stats.setdefault(ModelRole.REF, []).append(
            time.perf_counter() - t0
        )
        self._frozen_params[ModelRole.REF] = out

    def record_reshard(self, role: str, seconds: float) -> None:
        """External layout transitions (e.g. the hybrid rollout
        engine's actor train->rollout swap) report here so the
        per-role accounting is complete."""
        self.reshard_stats.setdefault(role, []).append(seconds)

    def role_report(self) -> Dict[str, Dict[str, Any]]:
        """Per-role strategy + layout + reshard accounting — the
        multi-model ModelEngine contract (reference:
        atorch/rl/model_engine/model_engine.py:35 builds a strategy
        per model type; this is the observable record of it)."""
        report: Dict[str, Dict[str, Any]] = {}
        for name in self._roles:
            entry: Dict[str, Any] = {}
            if name in self._accel:
                entry["kind"] = "trainable"
                entry["strategy"] = self._accel[name].strategy.names()
                entry["searched"] = bool(self._roles[name].search)
            else:
                entry["kind"] = "frozen"
                entry["layout"] = (
                    "sharded" if name in self._frozen_shardings
                    else "replicated"
                )
            ts = self.reshard_stats.get(name, [])
            entry["reshards"] = len(ts)
            if ts:
                entry["mean_reshard_s"] = round(sum(ts) / len(ts), 4)
            report[name] = entry
        return report


def _default_frozen_rules():
    from dlrover_tpu.parallel.sharding import gpt_tp_rules

    return gpt_tp_rules()
