"""RL model engine: per-role models with per-role strategies.

Reference: ``ModelEngine`` (``atorch/rl/model_engine/
model_engine.py:35``) manages actor/critic/ref/reward models, each
accelerated with its own ATorch strategy.  The TPU engine builds:

- trainable roles (actor, critic): an accelerated sharded train step
  via :func:`dlrover_tpu.accel.auto_accelerate`;
- frozen roles (ref, reward): a jitted apply for inference only.

All four can share one mesh (per-role strategies emit compatible mesh
configs) — on TPU the roles are time-multiplexed on the same chips
rather than placed on separate GPU groups.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import jax

from dlrover_tpu.accel import Strategy, auto_accelerate
from dlrover_tpu.common.log import default_logger as logger


class ModelRole:
    ACTOR = "actor"
    CRITIC = "critic"
    REF = "ref"
    REWARD = "reward"

    TRAINABLE = (ACTOR, CRITIC)
    FROZEN = (REF, REWARD)


@dataclass
class RoleSpec:
    model: Any
    loss_fn: Optional[Callable] = None       # trainable roles
    optim_factory: Optional[Callable] = None
    strategy: Optional[Strategy] = None
    params: Any = None                       # frozen roles: given params


class RLModelEngine:
    def __init__(self, sample_batch, roles: Dict[str, RoleSpec]):
        self._sample_batch = sample_batch
        self._roles = roles
        self._accel: Dict[str, Any] = {}
        self._frozen_apply: Dict[str, Callable] = {}
        self._frozen_params: Dict[str, Any] = {}

    def build(self):
        for name, spec in self._roles.items():
            if name in ModelRole.TRAINABLE:
                if spec.loss_fn is None or spec.optim_factory is None:
                    raise ValueError(
                        f"trainable role {name} needs loss_fn and "
                        "optim_factory"
                    )
                self._accel[name] = auto_accelerate(
                    spec.model,
                    spec.optim_factory,
                    spec.loss_fn,
                    self._sample_batch,
                    strategy=spec.strategy
                    or Strategy(opts=[("parallel_mode", {})]),
                    dry_run_candidates=False,
                )
                logger.info(
                    "built trainable role %s with strategy %s",
                    name, self._accel[name].strategy.names(),
                )
            else:
                params = (
                    spec.params
                    if spec.params is not None
                    else spec.model.init_params(jax.random.PRNGKey(0))
                )
                self._frozen_params[name] = params
                model = spec.model

                def apply_fn(p, batch, model=model):
                    return model.apply({"params": p}, batch)

                self._frozen_apply[name] = jax.jit(apply_fn)
        return self

    # -- accessors ---------------------------------------------------------

    def train_step(self, role: str):
        return self._accel[role].train_step

    def state(self, role: str):
        return self._accel[role].state

    def set_state(self, role: str, state):
        self._accel[role].state = state

    def place_batch(self, role: str, batch):
        return self._accel[role].place_batch(batch)

    def infer(self, role: str, inputs):
        """Frozen-role forward (ref logprobs / reward scores)."""
        return self._frozen_apply[role](
            self._frozen_params[role], inputs
        )

    def sync_ref_from_actor(self):
        """Refresh the frozen reference policy from the actor (the
        periodic ref update some RLHF recipes use).  A real device
        copy, not aliasing: the actor's train step donates its state,
        so held references to the live params would be invalidated on
        the next step."""
        import jax.numpy as jnp

        self._frozen_params[ModelRole.REF] = jax.tree.map(
            jnp.copy, self._accel[ModelRole.ACTOR].state.params
        )
