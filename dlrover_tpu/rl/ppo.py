"""PPO math for RLHF (pure functions).

Reference: ATorch's PPO utilities under ``atorch/rl/`` (model-type
registry + ppo loss helpers).  Standard PPO-clip with GAE; everything
is jit-compatible and batched over [batch, time].
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def gae_advantages(
    rewards: jax.Array,      # [b, t]
    values: jax.Array,       # [b, t]
    dones: jax.Array,        # [b, t] 1.0 where episode ends
    gamma: float = 0.99,
    lam: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation via reverse scan.

    Returns (advantages [b, t], returns [b, t]).
    """
    b, t = rewards.shape
    next_values = jnp.concatenate(
        [values[:, 1:], jnp.zeros((b, 1))], axis=1
    )
    not_done = 1.0 - dones
    deltas = rewards + gamma * next_values * not_done - values

    def step(carry, x):
        delta_t, nd_t = x
        carry = delta_t + gamma * lam * nd_t * carry
        return carry, carry

    # scan over time reversed; inputs transposed to [t, b]
    _, adv_rev = jax.lax.scan(
        step,
        jnp.zeros(b),
        (deltas.T[::-1], not_done.T[::-1]),
    )
    advantages = adv_rev[::-1].T
    returns = advantages + values
    # normalize advantages (standard PPO practice)
    advantages = (advantages - advantages.mean()) / (
        advantages.std() + 1e-8
    )
    return advantages, returns


def ppo_policy_loss(
    logprobs: jax.Array,      # [b, t] new log pi(a|s)
    old_logprobs: jax.Array,  # [b, t]
    advantages: jax.Array,    # [b, t]
    clip_ratio: float = 0.2,
    mask: jax.Array = None,   # [b, t] valid-token mask
) -> jax.Array:
    ratio = jnp.exp(logprobs - old_logprobs)
    clipped = jnp.clip(ratio, 1 - clip_ratio, 1 + clip_ratio)
    loss = -jnp.minimum(ratio * advantages, clipped * advantages)
    if mask is not None:
        return (loss * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss.mean()


def ppo_critic_loss(
    values: jax.Array,       # [b, t]
    returns: jax.Array,      # [b, t]
    old_values: jax.Array = None,
    clip_value: float = 0.2,
    mask: jax.Array = None,
) -> jax.Array:
    if old_values is not None:
        v_clipped = old_values + jnp.clip(
            values - old_values, -clip_value, clip_value
        )
        loss = jnp.maximum(
            (values - returns) ** 2, (v_clipped - returns) ** 2
        )
    else:
        loss = (values - returns) ** 2
    if mask is not None:
        return 0.5 * (loss * mask).sum() / jnp.maximum(
            mask.sum(), 1.0
        )
    return 0.5 * loss.mean()


def kl_penalty(
    logprobs: jax.Array, ref_logprobs: jax.Array, kl_coef: float
) -> jax.Array:
    """Per-token KL penalty against the frozen reference policy."""
    return kl_coef * (logprobs - ref_logprobs)


def token_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """log pi of the taken tokens: [b, t, v] x [b, t] -> [b, t]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(
        logp, tokens[..., None], axis=-1
    )[..., 0]
