"""End-to-end PPO iteration over the four-role engine.

Reference: the RLHF loop the ATorch engine drives
(``atorch/rl/model_engine/model_engine.py:35`` + ppo utils): actor
generates rollouts, reward/ref score them, critic values + GAE turn
them into advantages, actor/critic take PPO steps.  Everything heavy
(generation, scoring, the two train steps) is jitted; the glue here is
plain Python per iteration.
"""

import dataclasses
import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from dlrover_tpu.rl.generation import decode_variant, generate
from dlrover_tpu.rl.model_engine import ModelRole, RLModelEngine
from dlrover_tpu.rl.ppo import (
    gae_advantages,
    kl_penalty,
    ppo_critic_loss,
    ppo_policy_loss,
    token_logprobs,
)


@functools.lru_cache(maxsize=8)
def _jitted_apply(model):
    """One jitted forward per (hashable) flax module."""
    return jax.jit(
        lambda params, x: model.apply({"params": params}, x)
    )


def make_actor_loss(model, prompt_len: int, clip_ratio: float = 0.2):
    """PPO-clip policy loss over the response region of the rollout
    batch {"tokens", "old_logprobs", "advantages"}."""

    def loss_fn(params, batch, model=model):
        logits = model.apply({"params": params}, batch["tokens"][:, :-1])
        lp = token_logprobs(logits, batch["tokens"][:, 1:])
        lp_resp = lp[:, prompt_len - 1:]
        return ppo_policy_loss(
            lp_resp, batch["old_logprobs"], batch["advantages"],
            clip_ratio=clip_ratio,
        )

    return loss_fn


def make_critic_loss(model, prompt_len: int):
    """Value regression over the response region of
    {"tokens", "returns"}; ``model`` must have head="value"."""

    def loss_fn(params, batch, model=model):
        values = model.apply({"params": params}, batch["tokens"][:, :-1])
        return ppo_critic_loss(
            values[:, prompt_len - 1:], batch["returns"]
        )

    return loss_fn


def sample_rollout_batch(prompts, max_new_tokens: int) -> Dict:
    """Abstract batch matching ppo_iteration's real batches — what the
    engine needs at build time to shape the jitted train steps."""
    b, prompt_len = prompts.shape
    total = prompt_len + max_new_tokens
    return {
        "tokens": jnp.zeros((b, total), prompts.dtype),
        "old_logprobs": jnp.zeros((b, max_new_tokens), jnp.float32),
        "advantages": jnp.zeros((b, max_new_tokens), jnp.float32),
        "returns": jnp.zeros((b, max_new_tokens), jnp.float32),
    }


def make_experience(
    engine: RLModelEngine,
    prompts: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 16,
    temperature: float = 1.0,
    kl_coef: float = 0.05,
    gamma: float = 1.0,
    lam: float = 0.95,
    reward_fn: Callable = None,
    hybrid=None,
    rollout_params=None,
):
    """EXPERIENCE phase of one PPO cycle (reference:
    RLTrainer.make_experience): rollout -> ref-KL scoring -> reward
    -> GAE, producing the training batch WITHOUT taking a gradient
    step — so a trainer can fill a replay buffer with several
    rollouts before the training phase (the reference's
    num_rollouts contract).

    ``hybrid`` swaps the actor into its rollout layout for
    generation.  ``rollout_params`` (already-resharded actor params,
    e.g. from a phase hook) skips the per-call reshard — the actor
    does not train inside an experience phase, so one swap serves
    every rollout of the phase.  Returns (batch dict, metrics);
    metrics carry the measured phase seconds (``rollout_s`` =
    generation, ``score_s`` = ref-KL + reward, ``gae_s`` = critic
    values + GAE) feeding the elastic plane's ``rl_iteration``
    timeline slices."""
    import time as _time

    b, prompt_len = prompts.shape
    actor = engine._roles[ModelRole.ACTOR].model
    actor_decode = decode_variant(actor)
    fresh_reshard = False
    if rollout_params is not None:
        actor_params = rollout_params
        if hybrid is not None:
            prompts = hybrid.place_rollout_batch(prompts)
    elif hybrid is not None:
        actor_params = hybrid.reshard_actor_for_rollout()
        fresh_reshard = True
        prompts = hybrid.place_rollout_batch(prompts)
    else:
        actor_params = engine.state(ModelRole.ACTOR).params

    t0 = _time.perf_counter()
    sequences, old_logps = generate(
        actor_decode, actor_params, prompts, rng,
        max_new_tokens=max_new_tokens, temperature=temperature,
    )
    jax.block_until_ready(old_logps)
    t_rollout = _time.perf_counter()

    # reference logprobs over the response region (KL anchor)
    ref_logits = engine.infer(ModelRole.REF, sequences[:, :-1])
    ref_lp = token_logprobs(
        ref_logits, sequences[:, 1:]
    )[:, prompt_len - 1:]

    if reward_fn is not None:
        seq_reward = reward_fn(sequences)
    else:
        # reward model: per-token values, last token scores the seq
        seq_reward = engine.infer(ModelRole.REWARD, sequences)[:, -1]
    seq_reward = jnp.asarray(seq_reward, jnp.float32)

    # per-token reward = -KL penalty, terminal reward on the last token
    kl = kl_penalty(old_logps, ref_lp, kl_coef)
    rewards = (-kl).at[:, -1].add(seq_reward)
    jax.block_until_ready(rewards)
    t_score = _time.perf_counter()

    critic_model = engine._roles[ModelRole.CRITIC].model
    critic_params = engine.state(ModelRole.CRITIC).params
    values = _jitted_apply(critic_model)(
        critic_params, sequences[:, :-1]
    )[:, prompt_len - 1:]

    dones = jnp.zeros_like(rewards).at[:, -1].set(1.0)
    advantages, returns = gae_advantages(
        rewards, values, dones, gamma=gamma, lam=lam
    )

    batch = {
        "tokens": sequences,
        "old_logprobs": old_logps,
        "advantages": advantages,
        "returns": returns,
    }
    jax.block_until_ready(returns)
    t_gae = _time.perf_counter()
    metrics = {
        "mean_reward": float(seq_reward.mean()),
        "mean_kl": float(kl.mean()),
        "rollout_s": round(t_rollout - t0, 4),
        "score_s": round(t_score - t_rollout, 4),
        "gae_s": round(t_gae - t_score, 4),
    }
    if fresh_reshard:
        metrics["reshard_s"] = hybrid.reshard_times[-1]
    return batch, metrics


def train_on_batch(
    engine: RLModelEngine, batch: Dict, steps: Dict = None
) -> Dict[str, float]:
    """TRAINING phase: one actor + one critic PPO step on an
    experience batch (reference: RLTrainer.rl_training inner
    update).  ``steps`` optionally maps role -> step callable (e.g.
    AOT-cache resolutions from
    :func:`dlrover_tpu.rl.elastic.resolve_role_steps`) in place of
    the engine's jitted steps — same signature, same donation."""
    losses = {}
    for role in (ModelRole.ACTOR, ModelRole.CRITIC):
        placed = engine.place_batch(role, batch)
        step_fn = (
            steps[role] if steps and role in steps
            else engine.train_step(role)
        )
        state, metrics = step_fn(engine.state(role), placed)
        engine.set_state(role, state)
        losses[f"{role}_loss"] = float(metrics["loss"])
    return losses


def ppo_iteration(
    engine: RLModelEngine,
    prompts: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 16,
    temperature: float = 1.0,
    kl_coef: float = 0.05,
    gamma: float = 1.0,
    lam: float = 0.95,
    reward_fn: Callable = None,
    hybrid=None,
) -> Dict[str, float]:
    """One full PPO iteration: make_experience + train_on_batch.
    ``reward_fn(sequences) -> [b]`` overrides the reward role
    (otherwise the reward model scores the final token).
    Returns metrics including the mean sequence reward."""
    batch, metrics = make_experience(
        engine, prompts, rng, max_new_tokens=max_new_tokens,
        temperature=temperature, kl_coef=kl_coef, gamma=gamma,
        lam=lam, reward_fn=reward_fn, hybrid=hybrid,
    )
    metrics.update(train_on_batch(engine, batch))
    return metrics
