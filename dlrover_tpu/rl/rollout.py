"""End-to-end PPO iteration over the four-role engine.

Reference: the RLHF loop the ATorch engine drives
(``atorch/rl/model_engine/model_engine.py:35`` + ppo utils): actor
generates rollouts, reward/ref score them, critic values + GAE turn
them into advantages, actor/critic take PPO steps.  Everything heavy
(generation, scoring, the two train steps) is jitted; the glue here is
plain Python per iteration.
"""

import dataclasses
import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from dlrover_tpu.rl.generation import decode_variant, generate
from dlrover_tpu.rl.model_engine import ModelRole, RLModelEngine
from dlrover_tpu.rl.ppo import (
    gae_advantages,
    kl_penalty,
    ppo_critic_loss,
    ppo_policy_loss,
    token_logprobs,
)


@functools.lru_cache(maxsize=8)
def _jitted_apply(model):
    """One jitted forward per (hashable) flax module."""
    return jax.jit(
        lambda params, x: model.apply({"params": params}, x)
    )


def make_actor_loss(model, prompt_len: int, clip_ratio: float = 0.2):
    """PPO-clip policy loss over the response region of the rollout
    batch {"tokens", "old_logprobs", "advantages"}."""

    def loss_fn(params, batch, model=model):
        logits = model.apply({"params": params}, batch["tokens"][:, :-1])
        lp = token_logprobs(logits, batch["tokens"][:, 1:])
        lp_resp = lp[:, prompt_len - 1:]
        return ppo_policy_loss(
            lp_resp, batch["old_logprobs"], batch["advantages"],
            clip_ratio=clip_ratio,
        )

    return loss_fn


def make_critic_loss(model, prompt_len: int):
    """Value regression over the response region of
    {"tokens", "returns"}; ``model`` must have head="value"."""

    def loss_fn(params, batch, model=model):
        values = model.apply({"params": params}, batch["tokens"][:, :-1])
        return ppo_critic_loss(
            values[:, prompt_len - 1:], batch["returns"]
        )

    return loss_fn


def sample_rollout_batch(prompts, max_new_tokens: int) -> Dict:
    """Abstract batch matching ppo_iteration's real batches — what the
    engine needs at build time to shape the jitted train steps."""
    b, prompt_len = prompts.shape
    total = prompt_len + max_new_tokens
    return {
        "tokens": jnp.zeros((b, total), prompts.dtype),
        "old_logprobs": jnp.zeros((b, max_new_tokens), jnp.float32),
        "advantages": jnp.zeros((b, max_new_tokens), jnp.float32),
        "returns": jnp.zeros((b, max_new_tokens), jnp.float32),
    }


def ppo_iteration(
    engine: RLModelEngine,
    prompts: jax.Array,
    rng: jax.Array,
    max_new_tokens: int = 16,
    temperature: float = 1.0,
    kl_coef: float = 0.05,
    gamma: float = 1.0,
    lam: float = 0.95,
    reward_fn: Callable = None,
    hybrid=None,
) -> Dict[str, float]:
    """One full PPO iteration: rollout -> score -> GAE -> two PPO
    steps.  ``reward_fn(sequences) -> [b]`` overrides the reward role
    (otherwise the reward model scores the final token).

    ``hybrid`` (a :class:`dlrover_tpu.rl.hybrid_engine.
    HybridRolloutEngine`) swaps the actor into its rollout layout for
    generation — train and rollout may use different meshes; the
    timed reshard latency lands in the returned metrics.
    Returns metrics including the mean sequence reward."""
    b, prompt_len = prompts.shape
    actor = engine._roles[ModelRole.ACTOR].model
    actor_decode = decode_variant(actor)
    if hybrid is not None:
        actor_params = hybrid.reshard_actor_for_rollout()
        prompts = hybrid.place_rollout_batch(prompts)
    else:
        actor_params = engine.state(ModelRole.ACTOR).params

    sequences, old_logps = generate(
        actor_decode, actor_params, prompts, rng,
        max_new_tokens=max_new_tokens, temperature=temperature,
    )

    # reference logprobs over the response region (KL anchor)
    ref_logits = engine.infer(ModelRole.REF, sequences[:, :-1])
    ref_lp = token_logprobs(
        ref_logits, sequences[:, 1:]
    )[:, prompt_len - 1:]

    if reward_fn is not None:
        seq_reward = reward_fn(sequences)
    else:
        # reward model: per-token values, last token scores the seq
        seq_reward = engine.infer(ModelRole.REWARD, sequences)[:, -1]
    seq_reward = jnp.asarray(seq_reward, jnp.float32)

    # per-token reward = -KL penalty, terminal reward on the last token
    kl = kl_penalty(old_logps, ref_lp, kl_coef)
    rewards = (-kl).at[:, -1].add(seq_reward)

    critic_model = engine._roles[ModelRole.CRITIC].model
    critic_params = engine.state(ModelRole.CRITIC).params
    values = _jitted_apply(critic_model)(
        critic_params, sequences[:, :-1]
    )[:, prompt_len - 1:]

    dones = jnp.zeros_like(rewards).at[:, -1].set(1.0)
    advantages, returns = gae_advantages(
        rewards, values, dones, gamma=gamma, lam=lam
    )

    batch = {
        "tokens": sequences,
        "old_logprobs": old_logps,
        "advantages": advantages,
        "returns": returns,
    }
    losses = {}
    for role in (ModelRole.ACTOR, ModelRole.CRITIC):
        placed = engine.place_batch(role, batch)
        state, metrics = engine.train_step(role)(
            engine.state(role), placed
        )
        engine.set_state(role, state)
        losses[f"{role}_loss"] = float(metrics["loss"])

    metrics = {
        "mean_reward": float(seq_reward.mean()),
        "mean_kl": float(kl.mean()),
        **losses,
    }
    if hybrid is not None:
        metrics["reshard_s"] = hybrid.reshard_times[-1]
    return metrics
