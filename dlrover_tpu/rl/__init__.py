"""RLHF engine (reference: ``atorch/atorch/rl/`` — ``ModelEngine``
managing actor/critic/ref/reward models each with its own
acceleration strategy, DeepSpeed-hybrid-engine re-implementation, PPO
utilities)."""

from dlrover_tpu.rl.hybrid_engine import HybridRolloutEngine
from dlrover_tpu.rl.model_engine import ModelRole, RLModelEngine
from dlrover_tpu.rl.ppo import (
    gae_advantages,
    ppo_critic_loss,
    ppo_policy_loss,
)
from dlrover_tpu.rl.trainer import (
    PPOTrainer,
    ReplayBuffer,
    RLTrainConfig,
    RLTrainer,
)

__all__ = [
    "HybridRolloutEngine",
    "ModelRole",
    "PPOTrainer",
    "ReplayBuffer",
    "RLTrainConfig",
    "RLTrainer",
    "RLModelEngine",
    "gae_advantages",
    "ppo_critic_loss",
    "ppo_policy_loss",
]
