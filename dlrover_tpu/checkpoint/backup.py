"""Peer-host checkpoint shard backup.

Reference: ``flash_checkpoint/ckpt_backup.py`` (peer-node backup and
restore of checkpoint shards via torch collectives): each host sends
its shm checkpoint shard to a partner host, so when a host is lost and
replaced, the replacement recovers the shard from the partner instead
of storage.  TPU version: the shard bytes ride the ICI/DCN fabric as a
uint8 ``ppermute`` over the ``data`` axis inside ``shard_map`` — one
collective, no host networking code.
"""

import pickle
from typing import Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.jax_compat import shard_map


def _to_u8(payload: bytes, size: int) -> np.ndarray:
    buf = np.zeros(size, dtype=np.uint8)
    arr = np.frombuffer(payload, dtype=np.uint8)
    buf[: arr.size] = arr
    return buf


def exchange_with_peer(
    payload: bytes,
    mesh,
    axis: str = "data",
    max_bytes: Optional[int] = None,
    shift: int = 1,
) -> Tuple[bytes, int]:
    """Every rank sends ``payload`` to rank+shift (ring) and receives
    rank-shift's payload.  Returns (peer_payload, peer_len).

    All ranks must call this collectively with the same ``max_bytes``
    (defaults to a power-of-two bound of the local payload; callers
    should agree on it out of band, e.g. via the master KV store).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    if n == 1:
        return payload, len(payload)
    if max_bytes is None:
        if jax.process_count() > 1:
            # a default derived from the *local* payload length lets
            # processes disagree on the collective's buffer shape and
            # deadlock/crash the ppermute — callers must agree out of
            # band (e.g. via the master KV store)
            raise ValueError(
                "exchange_with_peer requires an explicitly agreed "
                "max_bytes in multi-host runs"
            )
        max_bytes = 1 << (len(payload)).bit_length()
    if len(payload) > max_bytes:
        # fail fast on every rank's next call instead of dying with an
        # opaque broadcast error after peers entered the collective
        raise ValueError(
            f"payload ({len(payload)} bytes) exceeds the agreed "
            f"max_bytes ({max_bytes}); raise max_bytes collectively"
        )
    size = max_bytes
    # [n, size+8] buffer: 8-byte length header + padded payload
    header = np.frombuffer(
        np.int64(len(payload)).tobytes(), dtype=np.uint8
    )
    local = np.concatenate([header, _to_u8(payload, size)])
    stacked = np.zeros((n, size + 8), dtype=np.uint8)
    for i in range(n):
        stacked[i] = local  # every row holds this process's payload

    perm = [(i, (i + shift) % n) for i in range(n)]

    def shard_fn(x):
        return jax.lax.ppermute(x, axis, perm)

    sharded = jax.device_put(
        jnp.asarray(stacked),
        NamedSharding(mesh, P(axis)),
    )
    received = shard_map(
        shard_fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )(sharded)
    # extract only this process's addressable rows — np.asarray on the
    # global array would raise multi-host where most rows live on
    # other hosts' devices
    local_rows = []
    for sh in received.addressable_shards:
        data = np.asarray(sh.data)
        start = sh.index[0].start or 0
        for j in range(data.shape[0]):
            local_rows.append((start + j, data[j]))
    local_rows.sort(key=lambda t: t[0])
    # multi-host: the single addressable row is what *this* process
    # received; single-host virtual mesh: every row is addressable and
    # the first is rank 0's view (test mode)
    row = local_rows[0][1]
    length = int(np.frombuffer(row[:8].tobytes(), dtype=np.int64)[0])
    peer = bytes(row[8 : 8 + length].tobytes())
    return peer, len(peer)


class BackupManager:
    """Keeps the partner's shard alongside ours (reference:
    ckpt_backup BackupManger semantics)."""

    def __init__(self, mesh, axis: str = "data"):
        self._mesh = mesh
        self._axis = axis
        self._peer_shard: Optional[bytes] = None
        self._own_meta: Optional[dict] = None

    def backup(self, state_dict, step: int, max_bytes: int):
        payload = pickle.dumps({"step": step, "state": state_dict})
        peer, _ = exchange_with_peer(
            payload, self._mesh, self._axis, max_bytes=max_bytes
        )
        self._peer_shard = peer
        logger.info(
            "backed up step %s shard with peer (%s bytes held)",
            step, len(peer),
        )

    def peer_state(self) -> Optional[Tuple[int, dict]]:
        if self._peer_shard is None:
            return None
        data = pickle.loads(self._peer_shard)
        return data["step"], data["state"]
