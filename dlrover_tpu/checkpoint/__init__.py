"""Flash checkpoint: sub-second in-memory snapshots of JAX pytrees with
asynchronous persistence from the agent process (reference:
``dlrover/python/elastic_agent/torch/ckpt_saver.py`` +
``dlrover/trainer/torch/flash_checkpoint/``)."""

from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver

__all__ = [
    "AsyncCheckpointSaver",
    "CheckpointEngine",
    "Checkpointer",
    "StorageType",
]
