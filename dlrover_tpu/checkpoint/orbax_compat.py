"""Re-shardable global checkpoints via orbax/tensorstore.

Reference capability: the FSDP/Megatron distributed-checkpoint paths
(``flash_checkpoint/fsdp_engine.py`` implementing torch-DCP
StorageWriter/Reader, ``megatron_dist_ckpt.py``) whose value is
*re-sharding on load* — a checkpoint written at one topology restores
at another.  On TPU the ecosystem-native answer is orbax: global
``jax.Array`` pytrees are written with sharding metadata and restored
with *target* shardings, so world-size changes re-shard transparently
(the SURVEY §7 hard-part about shm shard topology changes is solved at
the storage tier).

This composes with flash checkpointing: shm snapshots give the
seconds-order restart path on the same topology; the orbax tier is the
re-shard-capable durable path.
"""

from typing import Any, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger


class GlobalCheckpointer:
    """Orbax-backed save/restore of (possibly sharded) pytrees."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state, wait: bool = False):
        """Async by default (orbax writes in background threads)."""
        self._mngr.save(
            step, args=self._ocp.args.StandardSave(state)
        )
        if wait:
            self._mngr.wait_until_finished()

    def restore(
        self, target_state: Optional[Any] = None,
        step: Optional[int] = None,
    ) -> Tuple[Optional[int], Any]:
        """Restore the latest (or given) step.

        ``target_state`` is a pytree of abstract arrays / concrete
        arrays whose shardings define the RESTORE placement — pass the
        new topology's state to re-shard an old checkpoint.
        """
        step = step if step is not None else self._mngr.latest_step()
        if step is None:
            return None, None
        if target_state is not None:
            import jax

            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=getattr(x, "sharding", None),
                ) if hasattr(x, "shape") else x,
                target_state,
            )
            restored = self._mngr.restore(
                step,
                args=self._ocp.args.StandardRestore(abstract),
            )
        else:
            restored = self._mngr.restore(step)
        logger.info("orbax restore of step %s complete", step)
        return step, restored

    def wait(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()
