"""Staged, pipelined checkpoint restore executor.

The save side of Flash Checkpoint is nearly free (the training stall
is one on-device copy); the restore side is the paper's actual
recovery promise — "seconds-order restore from host shared memory"
(reference: ckpt_saver.py) — and it was serial end to end: per-leaf
``arr.copy()`` detaches from shm (each copy page-faulting the mapping
single-threaded), then shard blobs read one after another, then
``device_put`` leaf by leaf.  Like Orbax's async restore and the
Pathways/GSPMD checkpointing pipelines, the fix is overlap, not a
faster single stream:

- **read**: storage shard blobs attach as mmap views (posix) or are
  fetched concurrently, so byte k+1 is paged in while byte k is being
  assembled;
- **assemble**: detach copies run as ~64 MB chunks on a small thread
  pool through :func:`dlrover_tpu.ops.fastcopy.copy_into` — the GIL is
  released for the memcpy AND the page faults it triggers, which is
  the dominant restore term on a cold mapping (~seconds/GB
  single-threaded);
- **h2d**: host arrays go to the device in batched ``device_put``
  calls issued while later leaves are still assembling, so the
  host→device transfer of leaf k overlaps the memcpy of leaf k+1.

``DLROVER_RESTORE_WORKERS`` sizes the pool; ``1`` bypasses the pool
entirely and reproduces the serial path exactly (the equivalence
tests pin this).  Stage wall times land in :class:`RestoreStats`
(``read_s``/``assemble_s``/``h2d_s``), which the engine exports to
the restore span/event/histograms and bench.py reports.
"""

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from dlrover_tpu.ops.fastcopy import copy_into_chunked

RESTORE_WORKERS_ENV = "DLROVER_RESTORE_WORKERS"
RESTORE_CHUNK_MB_ENV = "DLROVER_RESTORE_CHUNK_MB"
RESTORE_ZERO_COPY_ENV = "DLROVER_RESTORE_ZERO_COPY"

_DEFAULT_CHUNK_MB = 64


def restore_workers() -> int:
    """Pool size for the restore pipeline.  Default: half the host's
    cores capped at 8 — restore shares the host with the agent, the
    respawning trainer and jit re-trace, and memcpy saturates memory
    bandwidth long before it saturates cores."""
    val = os.getenv(RESTORE_WORKERS_ENV, "").strip()
    if val:
        try:
            return max(1, int(val))
        except ValueError:
            pass
    return min(8, max(2, (os.cpu_count() or 4) // 2))


def chunk_bytes() -> int:
    try:
        mb = int(os.getenv(RESTORE_CHUNK_MB_ENV, str(_DEFAULT_CHUNK_MB)))
    except ValueError:
        mb = _DEFAULT_CHUNK_MB
    return max(1, mb) * 2**20


def zero_copy_device_put() -> bool:
    """Whether ``np.frombuffer`` views of shm/mmap may be fed straight
    to ``device_put``.  On a real accelerator H2D always copies, so
    views are safe and save one host memcpy per leaf.  On the CPU
    backend jax may alias a suitably-aligned host buffer instead of
    copying — a restored param aliased to shm would be silently
    corrupted by the next snapshot — so views are detached first.
    ``DLROVER_RESTORE_ZERO_COPY=1/0`` overrides the probe."""
    val = os.getenv(RESTORE_ZERO_COPY_ENV, "").strip().lower()
    if val:
        return val not in ("0", "false", "no", "off")
    try:
        import jax

        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 - no jax yet: be safe
        return False


@dataclass
class RestoreStats:
    """Per-restore stage accounting (seconds of main-thread wall per
    stage; with mmap-lazy reads the page-fault cost lands in
    ``assemble_s``, where the faulting copies actually run)."""

    read_s: float = 0.0
    assemble_s: float = 0.0
    h2d_s: float = 0.0
    bytes: int = 0
    workers: int = field(default_factory=restore_workers)
    # tier-specific extras surfaced on the restore event/phase dict —
    # the sparse (KvVariable) import records kv_s/kv_rows here so the
    # timeline's restore slices show the kv stage
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_phases(self) -> Dict[str, Any]:
        phases = {
            "read_s": round(self.read_s, 4),
            "assemble_s": round(self.assemble_s, 4),
            "h2d_s": round(self.h2d_s, 4),
            "bytes": int(self.bytes),
            "workers": int(self.workers),
        }
        phases.update(self.extra)
        return phases


class _InlineFuture:
    """Future-shaped LAZY call so the workers==1 path runs the EXACT
    serial sequence behind the same driving code: nothing executes at
    submit time — the work runs when (and in the order) the driving
    loop consumes ``result()``, which also keeps the serial path's
    one-leaf-at-a-time memory profile."""

    __slots__ = ("_fn", "_args", "_done", "_value", "_exc")

    def __init__(self, fn, args):
        self._fn = fn
        self._args = args
        self._done = False
        self._value = None
        self._exc = None

    def result(self):
        if not self._done:
            self._done = True
            try:
                self._value = self._fn(*self._args)
            except BaseException as e:  # noqa: BLE001
                self._exc = e
            self._fn = self._args = None
        if self._exc is not None:
            raise self._exc
        return self._value


class StagedRestore:
    """Owns the restore thread pool (or nothing, when workers==1).

    Use as a context manager; ``submit`` returns something with
    ``.result()``.  With one worker every submit executes inline at
    the call site, which makes the pipeline degrade to the exact
    serial path — the `DLROVER_RESTORE_WORKERS=1` guard tests rely on
    this, and it doubles as the zero-risk escape hatch.
    """

    def __init__(self, workers: Optional[int] = None):
        self.workers = workers if workers is not None else restore_workers()
        self._pool: Optional[ThreadPoolExecutor] = None

    def __enter__(self) -> "StagedRestore":
        if self.workers > 1:
            self._pool = ThreadPoolExecutor(
                max_workers=self.workers,
                thread_name_prefix="ckpt-restore",
            )
        return self

    def __exit__(self, *exc):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        return False

    def submit(self, fn: Callable, *args):
        if self._pool is None:
            return _InlineFuture(fn, args)
        return self._pool.submit(fn, *args)

    def map_ordered(self, fn: Callable, items: Iterable) -> List:
        """Run ``fn`` over ``items`` concurrently, results in input
        order (inline when serial)."""
        futs = [self.submit(fn, item) for item in items]
        return [f.result() for f in futs]

    def map_pipelined(
        self, fn: Callable, items: Iterable, depth: int = 2,
    ):
        """Generator of ``fn(item)`` results in input order with at
        most ``depth`` calls in flight — the bounded-lookahead shape
        of the streaming reshard: window k+1's partition runs on the
        pool while the caller imports window k, and peak memory stays
        ~``depth`` windows instead of the whole item list.  Serial
        mode (workers==1) degrades to the exact inline sequence via
        the lazy inline futures."""
        from collections import deque

        pending: deque = deque()
        for item in items:
            pending.append(self.submit(fn, item))
            if len(pending) >= max(1, depth):
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()

    # -- chunked detach ----------------------------------------------------

    def copy_chunked(self, dst: np.ndarray, src: np.ndarray) -> List:
        """``dst[...] = src`` split into ~chunk_bytes pieces, each a
        GIL-released :func:`fastcopy.copy_into`; returns the futures
        (already done when serial).  Splitting a single large leaf is
        what parallelizes the page faults of a cold shm mapping."""
        return copy_into_chunked(
            dst, src, submit=self.submit, chunk_bytes=chunk_bytes()
        )

    def detach_flat(
        self,
        views: Dict[str, np.ndarray],
        stats: Optional[RestoreStats] = None,
    ) -> Dict[str, np.ndarray]:
        """Copy every view into a private array (chunked, parallel).
        Replaces the serial per-leaf ``arr.copy()`` detach; bit-
        identical output, wall time into ``stats.assemble_s``."""
        import time as _time

        t0 = _time.perf_counter()
        out: Dict[str, np.ndarray] = {}
        pending: List = []
        for key, view in views.items():
            dst = np.empty(view.shape, dtype=view.dtype)
            out[key] = dst
            pending.extend(self.copy_chunked(dst, view))
        for f in pending:
            f.result()
        if stats is not None:
            stats.assemble_s += _time.perf_counter() - t0
            stats.bytes += sum(v.nbytes for v in views.values())
        return out


def detach_flat(
    views: Dict[str, np.ndarray],
    stats: Optional[RestoreStats] = None,
    workers: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """One-shot convenience around :meth:`StagedRestore.detach_flat`."""
    with StagedRestore(workers) as staged:
        return staged.detach_flat(views, stats)


def detach_for_device_put(arr: np.ndarray) -> np.ndarray:
    """Return ``arr`` ready to hand to ``device_put``: the view itself
    when zero-copy is safe (H2D copies anyway), else a private copy so
    a CPU-backend jax array can never alias the shm/mmap buffer."""
    if not isinstance(arr, np.ndarray) or arr.base is None:
        return arr
    if zero_copy_device_put():
        return arr
    return np.array(arr, copy=True)
