"""Agent-process asynchronous checkpoint saver.

Reference: ``AsyncCheckpointSaver``
(``dlrover/python/elastic_agent/torch/ckpt_saver.py:344``): a factory
thread in the *agent* process waits for the trainer to ship a saver
config, then an event loop persists shared-memory snapshots to storage
— so a checkpoint written to shm survives a crashed trainer and is
still persisted.  Commit protocol: per-shard done files polled by the
lead agent, then an atomic tracker-file update
(``commit_checkpoint:860``, ``update_tracker_file:783``).  Signal
handlers persist the shm snapshot on SIGTERM
(``register_signal_handler:472``).
"""

import os
import pickle
import queue
import signal
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.checkpoint.shm_handler import (
    CheckpointConfig,
    SharedMemoryHandler,
)
from dlrover_tpu.common.constants import CheckpointConstant
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedLock, SharedQueue
from dlrover_tpu.common.storage import (
    CheckpointStorage,
    get_checkpoint_storage,
)
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_PERSIST_SECONDS = _REG.histogram(
    "dlrover_checkpoint_persist_seconds",
    "Agent-side shm->storage persist time per step",
)
_PERSIST_ERRORS_TOTAL = _REG.counter(
    "dlrover_checkpoint_persist_errors_total",
    "Persist rounds with failed shards or timed-out commits",
)
_COMMITTED_STEP = _REG.gauge(
    "dlrover_checkpoint_committed_step",
    "Latest step whose tracker file was committed",
)
_PREFETCH_SECONDS = _REG.histogram(
    "dlrover_shm_prefetch_seconds",
    "Agent-side page-in of the shm snapshot overlapping the "
    "replacement trainer's import (restore prefetch hint)",
)

FACTORY_QUEUE = "ckpt_factory"
EVENT_QUEUE = "ckpt_event_queue"
LOCK_PREFIX = "ckpt_lock"


class CheckpointEventType:
    SAVE = "save"
    UPDATE_SHARD = "update_shard"
    EXIT = "exit"


@dataclass
class CheckpointEvent:
    event_type: str = CheckpointEventType.SAVE
    step: int = 0
    global_shard_num: int = 1


@dataclass
class SaverConfig:
    """Shipped from trainer to agent on first save (reference:
    ``ClassMeta`` on SharedQueue("factory"), engine.py:253)."""

    checkpoint_dir: str = ""
    local_shard_num: int = 1
    global_shard_num: int = 1
    node_rank: int = 0
    storage_type: str = "posix"
    deletion_keep_latest: int = 0
    extra: Dict = field(default_factory=dict)


def shard_file(rank: int) -> str:
    return f"rank_{rank}.ckpt"


def meta_file(rank: int) -> str:
    return f"rank_{rank}.meta"


def step_dirname(step: int) -> str:
    return f"{CheckpointConstant.CKPT_NAME_PREFIX}{step}"


class AsyncCheckpointSaver:
    """One instance per agent; class-level singleton + factory thread."""

    _instance: Optional["AsyncCheckpointSaver"] = None
    _factory_thread: Optional[threading.Thread] = None
    _factory_queue: Optional[SharedQueue] = None
    _lock = threading.Lock()

    def __init__(self, config: SaverConfig,
                 storage: Optional[CheckpointStorage] = None):
        self.config = config
        self.storage = storage or get_checkpoint_storage(
            path=config.checkpoint_dir
        )
        self._shm_handlers = [
            SharedMemoryHandler(r, host=True)
            for r in range(config.local_shard_num)
        ]
        self._shm_locks = [
            SharedLock(f"{LOCK_PREFIX}_{r}", create=True)
            for r in range(config.local_shard_num)
        ]
        self._event_queue = SharedQueue(EVENT_QUEUE, create=True)
        self._executor = ThreadPoolExecutor(
            max_workers=max(4, config.local_shard_num),
            thread_name_prefix="ckpt-persist",
        )
        self._stopped = threading.Event()
        self._last_persisted_step = -1
        self._event_thread = threading.Thread(
            target=self._sync_shm_to_storage, daemon=True,
            name="ckpt-event-loop",
        )
        self._event_thread.start()

    # -- class-level lifecycle (agent entry) -------------------------------

    @classmethod
    def start_async_saving_ckpt(cls):
        """Start the factory thread that waits for a trainer's saver
        config (reference: start_async_saving_ckpt, ckpt_saver.py:410)."""
        with cls._lock:
            if cls._factory_thread is not None:
                return
            cls._factory_queue = SharedQueue(FACTORY_QUEUE, create=True)
            cls._factory_thread = threading.Thread(
                target=cls._factory_loop, daemon=True, name="ckpt-factory"
            )
            cls._factory_thread.start()

    @classmethod
    def _factory_loop(cls):
        while True:
            try:
                config = cls._factory_queue.get(timeout=3600.0)
            except queue.Empty:
                continue
            except Exception:  # queue server closed
                return
            if config is None:
                return
            with cls._lock:
                if cls._instance is None:
                    logger.info("creating checkpoint saver: %s", config)
                    cls._instance = cls(config)
                else:
                    cls._instance.config = config

    @classmethod
    def get_ckpt_saver(cls) -> Optional["AsyncCheckpointSaver"]:
        return cls._instance

    @classmethod
    def save_shm_to_storage(cls):
        """Persist whatever snapshot is in shm (breakpoint save before
        an agent-driven restart or on SIGTERM; reference:
        save_shm_to_storage, ckpt_saver.py:633)."""
        saver = cls._instance
        if saver is None:
            return
        steps = [
            cfg.step
            for cfg in (
                h.get_checkpoint_config() for h in saver._shm_handlers
            )
            if cfg is not None and not cfg.writing
        ]
        if not steps:
            return
        step = min(steps)
        if step > saver._last_persisted_step:
            logger.info("breakpoint-saving shm checkpoint step %s", step)
            # bounded commit wait: a breakpoint save runs INSIDE the
            # agent's restart path, and in a multi-node world the
            # commit needs every node's shard — a world that just
            # SHRANK can never produce them.  The local shard upload
            # is the durable part; an uncommitted step dir is
            # harmless (restores read the tracker), so the commit
            # poll must not stall a resize for SAVE_TIMEOUT.
            try:
                commit_timeout = float(os.environ.get(
                    "DLROVER_BREAKPOINT_COMMIT_TIMEOUT_S", "20"
                ))
            except ValueError:
                commit_timeout = 20.0
            saver.save_step_checkpoint(
                step, commit_timeout=commit_timeout
            )

    @classmethod
    def prefetch_shm_snapshots(cls, restart_count: int = 0) -> int:
        """Restore prefetch hint (ROADMAP 3b): touch every page of
        each shm snapshot so the segment is resident BEFORE the
        replacement trainer attaches it.  Called by the agent on a
        daemon thread the moment a death is witnessed — the page-ins
        overlap the breakpoint save, the worker stop AND the new
        trainer's interpreter + jax import.  Read-only strided
        touches on a PINNED thread budget (``prefault_workers``): the
        prefetch exists to hide latency from the respawn, so it must
        never out-compete the respawn for cores.  Returns bytes
        touched."""
        saver = cls._instance
        if saver is None:
            return 0
        from dlrover_tpu.checkpoint.shm_handler import prefault_workers

        t0 = time.time()
        touched = 0
        segments = 0
        workers = prefault_workers()
        for handler in saver._shm_handlers:
            try:
                nbytes = handler.prefault(workers=workers)
                if nbytes:
                    touched += nbytes
                    segments += 1
            except Exception:  # noqa: BLE001 - best-effort warmup
                logger.exception("shm prefetch failed for a shard")
        seconds = time.time() - t0
        if segments:
            _PREFETCH_SECONDS.observe(seconds)
            emit_event(
                "shm_prefetch",
                bytes=touched,
                seconds=round(seconds, 4),
                segments=segments,
                restart_count=restart_count,
            )
            logger.info(
                "prefetched %d shm snapshot segment(s), %.1f MB in "
                "%.3fs", segments, touched / 2**20, seconds,
            )
        return touched

    @classmethod
    def register_signal_handler(cls):
        """SIGTERM -> persist shm then re-raise default behaviour
        (reference: register_signal_handler, ckpt_saver.py:472)."""

        def _on_term(signum, frame):
            cls.save_shm_to_storage()
            os._exit(143)

        signal.signal(signal.SIGTERM, _on_term)

    @classmethod
    def stop_all(cls):
        with cls._lock:
            if cls._instance is not None:
                cls._instance.stop()
                cls._instance = None
            if cls._factory_queue is not None:
                cls._factory_queue.close()
                cls._factory_queue = None
            cls._factory_thread = None

    @classmethod
    def reset(cls):
        """Test helper: tear down singletons."""
        cls.stop_all()

    # -- event loop ---------------------------------------------------------

    def _sync_shm_to_storage(self):
        """Reference: _sync_shm_to_storage loop, ckpt_saver.py:517."""
        while not self._stopped.is_set():
            try:
                event: CheckpointEvent = self._event_queue.get(timeout=2.0)
            except queue.Empty:
                continue
            except Exception:
                return
            if event.event_type == CheckpointEventType.EXIT:
                return
            if event.event_type == CheckpointEventType.UPDATE_SHARD:
                self.config.global_shard_num = event.global_shard_num
                continue
            if event.event_type == CheckpointEventType.SAVE:
                try:
                    self.save_step_checkpoint(event.step)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "persisting checkpoint step %s failed", event.step
                    )

    # -- persist -----------------------------------------------------------

    def save_step_checkpoint(
        self, step: int, commit_timeout: Optional[float] = None,
    ):
        """Persist every local shard of ``step`` then commit
        (reference: save_step_checkpoint, ckpt_saver.py:795)."""
        start = time.time()
        step_dir = os.path.join(
            self.config.checkpoint_dir, step_dirname(step)
        )
        self.storage.safe_makedirs(step_dir)
        futures = []
        for local_rank, handler in enumerate(self._shm_handlers):
            futures.append(
                self._executor.submit(
                    self._save_shard, step, local_rank, handler, step_dir
                )
            )
        # a shard whose storage write RAISES (IO fault, chaos
        # injection) is a failed shard, not an escape past the
        # persist-failure telemetry below
        results = []
        for f in futures:
            try:
                results.append(bool(f.result()))
            except Exception:  # noqa: BLE001 - storage backends vary
                logger.exception(
                    "step %s: shard persist raised", step
                )
                results.append(False)
        ok = all(results)
        if not ok:
            logger.error("step %s: some shards failed to persist", step)
            _PERSIST_ERRORS_TOTAL.inc(reason="shard_failed")
            emit_event(
                "checkpoint_persist", step=step, ok=False,
                seconds=round(time.time() - start, 3),
            )
            return
        if self.config.node_rank == 0:
            self.commit_checkpoint(
                step, step_dir,
                timeout=(
                    commit_timeout if commit_timeout is not None
                    else CheckpointConstant.SAVE_TIMEOUT
                ),
            )
        self._last_persisted_step = step
        elapsed = time.time() - start
        _PERSIST_SECONDS.observe(elapsed)
        emit_event(
            "checkpoint_persist", step=step, ok=True,
            seconds=round(elapsed, 3),
        )
        logger.info(
            "persisted checkpoint step %s in %.2fs", step, elapsed,
        )

    def _save_shard(
        self, step: int, local_rank: int,
        handler: SharedMemoryHandler, step_dir: str,
    ) -> bool:
        """One shard shm -> storage.  The shard's shm lock is held only
        for a fast in-RAM copy of the segment, NOT for the storage
        write: holding it across seconds of disk/remote IO blocks the
        trainer's next snapshot behind the persist (VERDICT r2 weak #1)
        — the writer thread waits on this very lock.  The copy holds
        the GIL for one memcpy (~0.3 s/GB); the torn-shard guarantee is
        unchanged because the copy is taken under the lock (reference
        lock protocol: _save_shard, ckpt_saver.py:558-574)."""
        lock = self._shm_locks[local_rank]
        # prefault the segment BEFORE taking the lock: the agent's
        # first touch of a multi-GB mapping page-faults the whole
        # range, and doing that inside the lock stalls the trainer's
        # next snapshot for ~10 s/GB on slow hosts.  A lock-free
        # read-only touch is safe — the data read is discarded; only
        # the page mappings persist.
        try:
            meta = handler.metadata()
            if meta:
                total = meta["scalar_offset"] + meta["scalar_nbytes"]
                shm = handler._attach(min_size=total)
                if shm is not None:
                    import numpy as _np

                    _np.frombuffer(
                        shm.buf, dtype=_np.uint8, count=total
                    )[::4096].sum()
        except Exception:  # noqa: BLE001 - best-effort warmup
            pass
        acquired = lock.acquire(timeout=60.0)
        if not acquired:
            # reading shm unlocked races the trainer's next save; a torn
            # shard must never reach storage (reference aborts too,
            # ckpt_saver.py:558-574)
            logger.error(
                "rank %s: shm lock not acquired within 60s; skipping "
                "persist of step %s", local_rank, step,
            )
            return False
        try:
            config, raw, meta = handler.read_raw()
            if config is None:
                logger.warning(
                    "rank %s has no shm snapshot for step %s",
                    local_rank, step,
                )
                return False
            if config.rank >= self.config.global_shard_num:
                # shard outside the commit protocol (replicated mode
                # only persists global rank 0); its shm snapshot exists
                # purely for fast restart-restore — skipping is success
                return True
            if config.step != step:
                # shm was overwritten by a newer save (or holds an older
                # one): persisting it under this step dir would let
                # commit_checkpoint advance the tracker to a dir with
                # mixed-step shards (reference: ckpt_saver.py:561)
                logger.warning(
                    "rank %s shm holds step %s, wanted %s; aborting "
                    "shard save", local_rank, config.step, step,
                )
                return False
        finally:
            lock.release(force=True)
        # storage IO runs lock-free on the private copy
        global_rank = config.rank
        self.storage.write(
            raw, os.path.join(step_dir, shard_file(global_rank))
        )
        self.storage.write(
            pickle.dumps(meta),
            os.path.join(step_dir, meta_file(global_rank)),
        )
        # done file marks this shard committed
        self.storage.write(
            b"", os.path.join(
                step_dir,
                f"{CheckpointConstant.DONE_FILE_PREFIX}{global_rank}",
            ),
        )
        return True

    def commit_checkpoint(
        self, step: int, step_dir: str,
        timeout: float = CheckpointConstant.SAVE_TIMEOUT,
    ):
        """Poll done files == global_shard_num then atomically update
        the tracker file (reference: commit_checkpoint,
        ckpt_saver.py:860)."""
        deadline = time.time() + timeout
        expected = self.config.global_shard_num
        done: List[str] = []
        # adaptive poll: single-node commits find every done file on
        # the FIRST listdir (our own executor just wrote them — the
        # wakeup is effectively event-driven); only a multi-node
        # commit genuinely waits, and its cadence backs off from 20 ms
        # to 500 ms instead of paying a flat half-second floor that
        # used to sit on the recovery critical path
        poll = 0.02
        while time.time() < deadline:
            # re-read each iteration: an elastic resize ships a new
            # SaverConfig through the FACTORY thread (which replaces
            # self.config live), so a poll waiting for a world that
            # no longer exists picks up the shrunken shard count and
            # unwedges — whichever thread it runs on
            expected = self.config.global_shard_num
            try:
                done = [
                    f for f in self.storage.listdir(step_dir)
                    if f.startswith(CheckpointConstant.DONE_FILE_PREFIX)
                ]
            except FileNotFoundError:
                done = []
            if len(done) >= expected:
                tracker = os.path.join(
                    self.config.checkpoint_dir,
                    CheckpointConstant.TRACKER_FILE,
                )
                self.storage.write(str(step), tracker)
                self.storage.commit(step, True)
                self._clean_old_checkpoints(step)
                _COMMITTED_STEP.set(step)
                emit_event("checkpoint_commit", step=step)
                return
            time.sleep(poll)
            poll = min(0.5, poll * 1.7)
        _PERSIST_ERRORS_TOTAL.inc(reason="commit_timeout")
        logger.error(
            "commit of step %s timed out (%s/%s done files)",
            step, len(done), expected,
        )

    def _clean_old_checkpoints(self, current_step: int):
        keep = self.config.deletion_keep_latest
        if keep <= 0:
            return
        root = self.config.checkpoint_dir
        try:
            steps = sorted(
                int(d[len(CheckpointConstant.CKPT_NAME_PREFIX):])
                for d in self.storage.listdir(root)
                if d.startswith(CheckpointConstant.CKPT_NAME_PREFIX)
                and d[len(CheckpointConstant.CKPT_NAME_PREFIX):].isdigit()
            )
        except FileNotFoundError:
            return
        for s in steps[:-keep]:
            self.storage.safe_rmtree(os.path.join(root, step_dirname(s)))

    def stop(self):
        self._stopped.set()
        try:
            self._event_queue.put(
                CheckpointEvent(event_type=CheckpointEventType.EXIT)
            )
        except Exception:  # noqa: BLE001
            pass
        # wait for in-flight persist threads before closing handlers
        self._executor.shutdown(wait=True)
        for h in self._shm_handlers:
            h.close()
        for lk in self._shm_locks:
            lk.close()
        self._event_queue.close()


def read_last_checkpoint(
    checkpoint_dir: str, storage: Optional[CheckpointStorage] = None,
    workers: Optional[int] = None, stats=None,
    only_rank: Optional[int] = None,
):
    """Storage-side load: tracker file -> per-rank shard dict
    (reference: the load fallback in engine.py:325 when shm misses).
    Returns (step, {global_rank: (meta, raw_bytes)}) or (None, {}).

    Shard blobs attach via ``storage.read_view`` — an O(1) lazy mmap
    on the posix backend, so the bytes page in while the restore
    pipeline's assembly stage consumes them — and the per-rank
    meta/blob fetches run concurrently on the restore pool (remote
    backends pay one round trip per rank instead of a serial chain).
    ``workers=1`` (or ``DLROVER_RESTORE_WORKERS=1``) degrades to the
    exact serial sequence.  ``only_rank`` narrows the fetch to one
    rank's files — the replicated/single-shard restore must not pull
    every rank's blob off a remote backend to use one of them (the
    sharded re-assembly path genuinely needs them all and leaves it
    None).
    """
    storage = storage or get_checkpoint_storage(path=checkpoint_dir)
    tracker = os.path.join(checkpoint_dir, CheckpointConstant.TRACKER_FILE)
    if not storage.exists(tracker):
        return None, {}
    step = int(str(storage.read(tracker, mode="r")).strip())
    return read_checkpoint_at(
        checkpoint_dir, step, storage, workers=workers, stats=stats,
        only_rank=only_rank,
    )


def read_checkpoint_at(
    checkpoint_dir: str, step: int,
    storage: Optional[CheckpointStorage] = None,
    workers: Optional[int] = None, stats=None,
    only_rank: Optional[int] = None,
):
    """Per-rank shard dict of one SPECIFIC committed step (the
    delta-checkpoint chain replay reads its base and intermediate
    links this way; :func:`read_last_checkpoint` resolves the tracker
    and delegates here).  Returns ``(step, {rank: (meta, raw)})`` or
    ``(None, {})`` when the step dir is absent."""
    import time as _time

    from dlrover_tpu.checkpoint.restore import StagedRestore

    t0 = _time.perf_counter()
    storage = storage or get_checkpoint_storage(path=checkpoint_dir)
    step_dir = os.path.join(checkpoint_dir, step_dirname(step))
    try:
        names = [
            fname for fname in storage.listdir(step_dir)
            if fname.startswith("rank_") and fname.endswith(".ckpt")
        ]
    except OSError:
        return None, {}
    if only_rank is not None:
        names = [f for f in names if f == shard_file(only_rank)]
    # an empty shard set for a LISTABLE step dir still returns the
    # step with {} — a caller narrowing to only_rank relies on that
    # to notice "the step exists but not my shard" and fall back to
    # the all-ranks read (the cross-world sparse reshard's trigger);
    # only a missing dir (pruned chain link) reads as None above

    def _one(fname: str):
        rank = int(fname[len("rank_"):-len(".ckpt")])
        raw = storage.read_view(os.path.join(step_dir, fname))
        meta = pickle.loads(
            storage.read(os.path.join(step_dir, meta_file(rank)))
        )
        return rank, (meta, raw)

    with StagedRestore(workers) as staged:
        shards: Dict[int, tuple] = dict(staged.map_ordered(_one, names))
    if stats is not None:
        stats.read_s += _time.perf_counter() - t0
    return step, shards
