"""Trainer-process checkpoint engine: shm write + async persist enqueue.

Reference: ``CheckpointEngine`` / ``FullCheckpointEngine``
(``dlrover/trainer/torch/flash_checkpoint/engine.py:135,291``,
``full_ckpt_engine.py``): ``save_to_memory`` copies the state dict
into agent-owned shared memory under the shm lock (sub-second,
blocking the train step only for the device->host copy);
``save_to_storage`` additionally enqueues a SAVE event the agent
persists asynchronously; ``load`` prefers the shm snapshot (process
restart with agent alive) and falls back to storage.
"""

import os
import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

from dlrover_tpu.checkpoint.saver import (
    EVENT_QUEUE,
    FACTORY_QUEUE,
    LOCK_PREFIX,
    CheckpointEvent,
    CheckpointEventType,
    SaverConfig,
    read_last_checkpoint,
)
import numpy as np

from dlrover_tpu.checkpoint.sharded import SHARD_SEP
from dlrover_tpu.checkpoint.shm_handler import (
    CheckpointConfig,
    SharedMemoryHandler,
    flat_from_raw,
    state_dict_from_raw,
)
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedLock, SharedQueue
from dlrover_tpu.common.storage import get_checkpoint_storage
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_SHM_SAVE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_shm_save_seconds",
    "Device->host + shm memcpy time of one flash save (incl. lock)",
)
_ASYNC_WRITE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_async_write_seconds",
    "Background writer latency from dequeue to shm write done",
)
_SAVE_SKIPPED_TOTAL = _REG.counter(
    "dlrover_checkpoint_save_skipped_total",
    "Flash saves skipped because the saver/writer was busy",
)
_SAVE_ERRORS_TOTAL = _REG.counter(
    "dlrover_checkpoint_save_errors_total",
    "Failed async snapshot writes",
)
_RESTORE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_restore_seconds",
    "Restore latency by tier (shm fast path vs storage)",
)


class CheckpointEngine:
    """Base engine: one per training process.

    ``replicated=True`` (DDP-style full checkpoint): every rank writes
    shm for fast restart-restore, only global rank 0's shard is
    persisted (global_shard_num=1).  ``replicated=False``
    (FSDP/GSPMD-style): every process persists its addressable shard
    (global_shard_num=world_size).
    """

    def __init__(
        self,
        checkpoint_dir: str,
        replicated: bool = True,
        local_rank: Optional[int] = None,
        global_rank: Optional[int] = None,
        world_size: Optional[int] = None,
        deletion_keep_latest: int = 0,
        async_snapshot: bool = True,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.replicated = replicated
        # Async-snapshot mode exploits jax.Array immutability: the
        # training stall of a flash save is only a cheap on-device copy
        # (guarding against buffer donation invalidating the refs); the
        # device->host fetch, shm write and persist enqueue all happen
        # on a background writer thread.  The reference must copy
        # synchronously because torch tensors mutate in place
        # (ckpt_saver.py:174 _traverse_copy_to_shm); JAX does not.
        # Trade-off: a crash between ``save_to_storage`` returning and
        # the background shm write completing loses that snapshot (the
        # previous one remains) — same exposure as the reference's
        # async persist window.
        self._async_snapshot = async_snapshot
        self._writer_queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._writer_thread: Optional[threading.Thread] = None
        self._writer_lock = threading.Lock()
        self._jit_copy = None
        self._last_async_error: Optional[Exception] = None
        # phase breakdown of the last completed shm save (lock wait,
        # device->host fetch, memcpy) — surfaced so benches report the
        # dominant term instead of burying it in logs (VERDICT r2)
        self.last_save_phases: Dict[str, float] = {}
        self._local_rank = (
            local_rank if local_rank is not None
            else env_utils.get_local_rank()
        )
        self._rank = (
            global_rank if global_rank is not None else env_utils.get_rank()
        )
        self._world_size = (
            world_size if world_size is not None
            else env_utils.get_world_size()
        )
        self._shm_handler = SharedMemoryHandler(self._local_rank, host=False)
        self._shm_lock = SharedLock(
            f"{LOCK_PREFIX}_{self._local_rank}", create=False
        )
        self._event_queue = (
            SharedQueue(EVENT_QUEUE, create=False)
            if self._rank == 0 else None
        )
        self._storage = get_checkpoint_storage(path=checkpoint_dir)
        self._notified_agent = False
        self._deletion_keep_latest = deletion_keep_latest
        self._cached_step = -1
        # ship the saver config now so the agent-side saver (and its
        # shm/meta/lock servers) exists before the first load()
        # (reference creates the saver at engine construction too,
        # engine.py:253)
        self._notify_agent_to_create_saver()

    @property
    def global_shard_num(self) -> int:
        return 1 if self.replicated else self._world_size

    def _notify_agent_to_create_saver(self):
        """Ship the saver config to the agent's factory queue once
        (reference: engine.py:253)."""
        if self._notified_agent or self._local_rank != 0:
            self._notified_agent = True
            return
        from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
        from dlrover_tpu.common.multi_process import _socket_path

        if AsyncCheckpointSaver.get_ckpt_saver() is not None:
            # saver already exists in this process (tests / local mode)
            self._notified_agent = True
            return
        if not os.path.exists(_socket_path(FACTORY_QUEUE)):
            # standalone mode (no tpurun agent): host the saver in this
            # process so the shm/meta/lock servers exist and persists
            # still happen asynchronously — they just no longer survive
            # a crash of *this* process (the agent-process deployment
            # does; reference behaviour is a warning + no persistence)
            logger.warning(
                "no agent checkpoint-saver factory found; hosting an "
                "in-process saver (snapshots will not survive a crash "
                "of this process)"
            )
            AsyncCheckpointSaver._instance = AsyncCheckpointSaver(
                SaverConfig(
                    checkpoint_dir=self.checkpoint_dir,
                    local_shard_num=1,
                    global_shard_num=self.global_shard_num,
                    node_rank=env_utils.get_node_rank(),
                    deletion_keep_latest=self._deletion_keep_latest,
                )
            )
            self._notified_agent = True
            return
        factory = SharedQueue(FACTORY_QUEUE, create=False)
        factory.put(
            SaverConfig(
                checkpoint_dir=self.checkpoint_dir,
                local_shard_num=env_utils.get_local_world_size(),
                global_shard_num=self.global_shard_num,
                node_rank=env_utils.get_node_rank(),
                deletion_keep_latest=self._deletion_keep_latest,
            )
        )
        self._notified_agent = True

    # -- save ---------------------------------------------------------------

    def save_to_memory(
        self, step: int, state_dict, path: str = "",
        block_lock: bool = False,
    ) -> bool:
        """Synchronous part of a flash save: device->host copy into
        shm under the shm lock.  Non-blocking lock by default: if the
        agent is still persisting the previous snapshot, skip this
        save rather than stall training (reference:
        save_state_dict_to_memory, engine.py:291).  The async writer
        thread passes ``block_lock=True`` — it is off the training
        path, so waiting for the agent is free and the save must not
        be silently dropped."""
        self._notify_agent_to_create_saver()
        # every rank locks its shard: the agent's breakpoint save reads
        # all local shards, so an unlocked write can be torn even for
        # ranks that never persist to storage; without an agent there
        # is no concurrent reader and no lock server to talk to
        locked = False
        lock_wait = 0.0
        if self._agent_lock_available():
            t0 = time.perf_counter()
            if not self._shm_lock.acquire(
                blocking=block_lock, timeout=600.0
            ):
                logger.info(
                    "step %s: saver busy persisting; skipping shm save",
                    step,
                )
                _SAVE_SKIPPED_TOTAL.inc(reason="saver_busy")
                return False
            lock_wait = time.perf_counter() - t0
            locked = True
        try:
            config = CheckpointConfig(
                step=step,
                path=path or self.checkpoint_dir,
                rank=self._rank,
                world_size=self._world_size,
                global_shard_num=self.global_shard_num,
            )
            start = time.time()
            self._shm_handler.save_state_dict(state_dict, config)
            self._cached_step = step
            phases = dict(self._shm_handler.last_save_phases)
            phases["lock_wait_s"] = round(lock_wait, 3)
            phases["total_s"] = round(time.time() - start + lock_wait, 3)
            self.last_save_phases = phases
            _SHM_SAVE_SECONDS.observe(phases["total_s"])
            emit_event(
                "checkpoint_shm_save",
                step=step,
                rank=self._rank,
                **{k: v for k, v in phases.items()},
            )
            logger.info(
                "rank %s shm save of step %s took %.3fs "
                "(lock %.2fs, d2h fetch %.2fs, memcpy %.2fs)",
                self._rank, step, time.time() - start,
                lock_wait, phases.get("fetch_s", 0.0),
                phases.get("memcpy_s", 0.0),
            )
            return True
        finally:
            if locked:
                self._shm_lock.release()

    def _agent_lock_available(self) -> bool:
        """Whether an agent-side lock server exists for this shard
        (absent in standalone/no-agent mode, where save_to_memory has
        no concurrent reader to guard against)."""
        from dlrover_tpu.common.multi_process import _socket_path

        return os.path.exists(
            _socket_path(f"{LOCK_PREFIX}_{self._local_rank}")
        )

    # -- async snapshot path -------------------------------------------------

    def _device_snapshot(self, state_dict):
        """Copy every device-array leaf to a fresh on-device buffer.

        The copy runs at HBM bandwidth (milliseconds) and protects the
        snapshot from buffer donation in the caller's jitted train
        step; mutable host arrays are copied too (typically tiny —
        step counters and the like), immutable scalars pass through.
        """
        import jax
        import numpy as np

        leaves, treedef = jax.tree_util.tree_flatten(state_dict)
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, np.ndarray):
                leaves[i] = leaf.copy()
        idx = [
            i for i, leaf in enumerate(leaves)
            if isinstance(leaf, jax.Array)
        ]
        if idx:
            if self._jit_copy is None:
                import jax.numpy as jnp

                self._jit_copy = jax.jit(
                    lambda xs: [jnp.copy(x) for x in xs]
                )
            copied = self._jit_copy([leaves[i] for i in idx])
            for i, c in zip(idx, copied):
                leaves[i] = c
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _ensure_writer(self):
        with self._writer_lock:
            if self._writer_thread is None or (
                not self._writer_thread.is_alive()
            ):
                self._writer_thread = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name="ckpt-snapshot-writer",
                )
                self._writer_thread.start()

    def _writer_loop(self):
        while True:
            item = self._writer_queue.get()
            if item is None:
                return
            step, snap, path, enqueue = item
            try:
                with _ASYNC_WRITE_SECONDS.time():
                    ok = self.save_to_memory(
                        step, snap, path, block_lock=True
                    )
                if ok and enqueue and self._event_queue is not None:
                    self._event_queue.put(
                        CheckpointEvent(
                            event_type=CheckpointEventType.SAVE, step=step
                        )
                    )
            except Exception as e:  # noqa: BLE001
                self._last_async_error = e
                _SAVE_ERRORS_TOTAL.inc()
                logger.exception(
                    "async snapshot of step %s failed", step
                )
            finally:
                self._writer_queue.task_done()

    def wait_async(self, timeout: float = 600.0) -> bool:
        """Block until in-flight async snapshots are written to shm
        (tests / shutdown); returns False on timeout.
        ``unfinished_tasks`` counts queued and in-progress items."""
        deadline = time.monotonic() + timeout
        while self._writer_queue.unfinished_tasks:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.02)
        return True

    def save_to_storage(self, step: int, state_dict, path: str = "") -> bool:
        """Flash save: shm write + async persist by the agent
        (reference: save_to_storage in full_ckpt_engine.py).

        With ``async_snapshot`` (default) the training stall is only
        the on-device copy; the host fetch + shm write happen on the
        writer thread, which then enqueues the agent persist."""
        import jax

        has_device_arrays = any(
            isinstance(leaf, jax.Array)
            for leaf in jax.tree_util.tree_leaves(state_dict)
        )
        if self._async_snapshot and has_device_arrays:
            if self._writer_queue.unfinished_tasks:
                logger.info(
                    "step %s: previous snapshot still writing; "
                    "skipping save", step,
                )
                _SAVE_SKIPPED_TOTAL.inc(reason="writer_busy")
                return False
            snap = self._device_snapshot(state_dict)
            # kick off the device->host transfers without blocking
            for leaf in jax.tree_util.tree_leaves(snap):
                if isinstance(leaf, jax.Array):
                    try:
                        leaf.copy_to_host_async()
                    except Exception:  # noqa: BLE001
                        break
            self._ensure_writer()
            self._writer_queue.put((step, snap, path, True))
            return True
        ok = self.save_to_memory(step, state_dict, path)
        if ok and self._event_queue is not None:
            self._event_queue.put(
                CheckpointEvent(
                    event_type=CheckpointEventType.SAVE, step=step
                )
            )
        return ok

    # -- load ---------------------------------------------------------------

    def load(self) -> Tuple[Optional[int], Any]:
        """Restore: shm snapshot if present (fast path after process
        restart), else storage via the tracker file."""
        t0 = time.perf_counter()
        config, state = self.get_state_dict_from_memory()
        if config is not None:
            logger.info("restored step %s from shared memory", config.step)
            _RESTORE_SECONDS.observe(
                time.perf_counter() - t0, tier="shm"
            )
            emit_event(
                "checkpoint_restore", step=config.step, tier="shm",
                rank=self._rank,
            )
            return config.step, state
        step, state = self.load_from_storage()
        if step is not None:
            _RESTORE_SECONDS.observe(
                time.perf_counter() - t0, tier="storage"
            )
            emit_event(
                "checkpoint_restore", step=step, tier="storage",
                rank=self._rank,
            )
        return step, state

    def get_state_dict_from_memory(self):
        try:
            return self._shm_handler.load_state_dict()
        except Exception as e:  # noqa: BLE001
            logger.warning("shm restore failed: %s", e)
            return None, {}

    def load_from_storage(self) -> Tuple[Optional[int], Any]:
        step, shards = read_last_checkpoint(
            self.checkpoint_dir, self._storage
        )
        if step is None:
            return None, {}
        want_rank = 0 if self.replicated else self._rank
        if want_rank not in shards:
            logger.error(
                "checkpoint step %s has no shard for rank %s "
                "(topology changed? shards=%s)",
                step, want_rank, sorted(shards),
            )
            return None, {}
        meta, raw = shards[want_rank]
        logger.info("restored step %s from storage", step)
        return step, state_dict_from_raw(meta, raw)

    def load_sharded(
        self, target_state, orbax_dir: str = "",
    ) -> Tuple[Optional[int], Any]:
        """Restore a GSPMD-sharded pytree onto ``target_state``'s
        shardings, re-sharding as needed (reference capability:
        fsdp_engine.py re-shard on load).

        Tier order: (1) this rank's shm snapshot, (2) all visible
        rank files of the last committed storage step (covers any
        topology change on a shared filesystem), (3) the orbax tier at
        ``orbax_dir``.  Every target shard is assembled from the
        overlapping saved shard boxes; a tier is skipped when its
        shards do not cover the target arrays.
        """
        config, flat, metas = self._shm_handler.load_flat()
        if config is not None and flat:
            state = self._assemble_to_target(target_state, flat, metas)
            if state is not None:
                logger.info(
                    "restored sharded step %s from shared memory",
                    config.step,
                )
                return config.step, state
        step, shards = read_last_checkpoint(
            self.checkpoint_dir, self._storage
        )
        if step is not None and shards:
            flat_all: Dict[str, Any] = {}
            metas_all: Dict[str, Any] = {}
            for rank, (meta, raw) in sorted(shards.items()):
                f, m = flat_from_raw(meta, raw)
                for key, val in f.items():
                    # shard keys collide across ranks; namespace them
                    nk = (
                        f"{key}~r{rank}" if SHARD_SEP in key else key
                    )
                    flat_all[nk] = val
                    if key in m:
                        metas_all[nk] = m[key]
            state = self._assemble_to_target(
                target_state, flat_all, metas_all
            )
            if state is not None:
                logger.info(
                    "restored sharded step %s from storage "
                    "(%d rank files)", step, len(shards),
                )
                return step, state
        if orbax_dir:
            from dlrover_tpu.checkpoint.orbax_compat import (
                GlobalCheckpointer,
            )

            ckptr = GlobalCheckpointer(orbax_dir)
            try:
                return ckptr.restore(target_state)
            finally:
                ckptr.close()
        return None, {}

    def _assemble_to_target(self, target_state, flat, metas):
        """Assemble every leaf of ``target_state`` from saved entries;
        None when coverage is incomplete (caller tries next tier)."""
        import jax

        from dlrover_tpu.checkpoint.sharded import (
            assemble_global_array,
            group_shard_entries,
            is_sharded_leaf,
        )
        from dlrover_tpu.checkpoint.shm_handler import (
            _flatten_state_dict,
        )

        grouped, plain = group_shard_entries(flat, metas)
        target_flat = _flatten_state_dict(target_state)
        out: Dict[str, Any] = {}
        for key, target_leaf in target_flat.items():
            if is_sharded_leaf(target_leaf):
                entries = grouped.get(key)
                if entries is None and key in plain:
                    # saved unsharded (replicated whole array)
                    entries = [(
                        tuple((0, d) for d in plain[key].shape),
                        plain[key],
                    )]
                if entries is None:
                    logger.warning("no saved shards for '%s'", key)
                    return None
                arr = assemble_global_array(
                    tuple(target_leaf.shape),
                    np.dtype(target_leaf.dtype),
                    target_leaf.sharding,
                    entries,
                )
                if arr is None:
                    logger.warning(
                        "saved shards do not cover '%s'", key
                    )
                    return None
                out[key] = arr
            elif key in plain:
                val = plain[key]
                if isinstance(
                    target_leaf, jax.Array
                ) and isinstance(val, np.ndarray):
                    val = jax.device_put(val, target_leaf.sharding)
                out[key] = val
            elif key in grouped:
                # saved sharded, target unsharded: assemble fully
                from dlrover_tpu.checkpoint.sharded import (
                    assemble_shard,
                )

                m = None
                for mk, mv in metas.items():
                    if mk.split(SHARD_SEP, 1)[0] == key:
                        m = mv
                        break
                full = assemble_shard(
                    tuple((0, d) for d in m.global_shape),
                    np.dtype(m.dtype),
                    grouped[key],
                )
                if full is None:
                    return None
                out[key] = full
            else:
                logger.warning("missing leaf '%s' in checkpoint", key)
                return None
        # rebuild with the target's tree structure
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
            target_state
        )
        from dlrover_tpu.checkpoint.shm_handler import _path_str

        ordered = []
        for path, _ in leaves_with_path:
            key = "/".join(_path_str(p) for p in path)
            ordered.append(out[key])
        return jax.tree_util.tree_unflatten(treedef, ordered)

    def close(self):
        self.wait_async(timeout=60.0)
        if self._writer_thread is not None and self._writer_thread.is_alive():
            self._writer_queue.put(None)
            self._writer_thread.join(timeout=5.0)
        self._shm_handler.close()
