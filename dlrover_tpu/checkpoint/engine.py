"""Trainer-process checkpoint engine: shm write + async persist enqueue.

Reference: ``CheckpointEngine`` / ``FullCheckpointEngine``
(``dlrover/trainer/torch/flash_checkpoint/engine.py:135,291``,
``full_ckpt_engine.py``): ``save_to_memory`` copies the state dict
into agent-owned shared memory under the shm lock (sub-second,
blocking the train step only for the device->host copy);
``save_to_storage`` additionally enqueues a SAVE event the agent
persists asynchronously; ``load`` prefers the shm snapshot (process
restart with agent alive) and falls back to storage.
"""

import os
import queue
import threading
import time
from typing import Any, Dict, Optional, Tuple

from dlrover_tpu.checkpoint.saver import (
    EVENT_QUEUE,
    FACTORY_QUEUE,
    LOCK_PREFIX,
    CheckpointEvent,
    CheckpointEventType,
    SaverConfig,
    read_last_checkpoint,
)
import numpy as np

from dlrover_tpu.checkpoint.sharded import SHARD_SEP
from dlrover_tpu.checkpoint.sparse import KV_META_KEY, KV_STATE_KEY
from dlrover_tpu.checkpoint.shm_handler import (
    CheckpointConfig,
    SharedMemoryHandler,
    flat_from_raw,
    state_dict_from_raw,
)
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedLock, SharedQueue
from dlrover_tpu.common.storage import get_checkpoint_storage
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_SHM_SAVE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_shm_save_seconds",
    "Device->host + shm memcpy time of one flash save (incl. lock)",
)
_ASYNC_WRITE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_async_write_seconds",
    "Background writer latency from dequeue to shm write done",
)
_SAVE_SKIPPED_TOTAL = _REG.counter(
    "dlrover_checkpoint_save_skipped_total",
    "Flash saves skipped because the saver/writer was busy",
)
_SAVE_ERRORS_TOTAL = _REG.counter(
    "dlrover_checkpoint_save_errors_total",
    "Failed async snapshot writes",
)
_RESTORE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_restore_seconds",
    "Restore latency by tier (shm fast path vs storage)",
)
_RESTORE_STAGE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_restore_stage_seconds",
    "Per-stage restore pipeline time (labels: tier, stage = "
    "read / assemble / h2d)",
)
_SAVE_STAGE_SECONDS = _REG.histogram(
    "dlrover_checkpoint_save_stage_seconds",
    "Per-stage save pipeline time (labels: mode = flat / paged, "
    "stage = fetch / compare / memcpy / kv / publish)",
)


class CheckpointEngine:
    """Base engine: one per training process.

    ``replicated=True`` (DDP-style full checkpoint): every rank writes
    shm for fast restart-restore, only global rank 0's shard is
    persisted (global_shard_num=1).  ``replicated=False``
    (FSDP/GSPMD-style): every process persists its addressable shard
    (global_shard_num=world_size).
    """

    def __init__(
        self,
        checkpoint_dir: str,
        replicated: bool = True,
        local_rank: Optional[int] = None,
        global_rank: Optional[int] = None,
        world_size: Optional[int] = None,
        deletion_keep_latest: int = 0,
        async_snapshot: bool = True,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.replicated = replicated
        # Async-snapshot mode exploits jax.Array immutability: the
        # training stall of a flash save is only a cheap on-device copy
        # (guarding against buffer donation invalidating the refs); the
        # device->host fetch, shm write and persist enqueue all happen
        # on a background writer thread.  The reference must copy
        # synchronously because torch tensors mutate in place
        # (ckpt_saver.py:174 _traverse_copy_to_shm); JAX does not.
        # Trade-off: a crash between ``save_to_storage`` returning and
        # the background shm write completing loses that snapshot (the
        # previous one remains) — same exposure as the reference's
        # async persist window.
        self._async_snapshot = async_snapshot
        self._writer_queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._writer_thread: Optional[threading.Thread] = None
        self._writer_lock = threading.Lock()
        self._jit_copy = None
        self._last_async_error: Optional[Exception] = None
        # phase breakdown of the last completed shm save (lock wait,
        # device->host fetch, memcpy) — surfaced so benches report the
        # dominant term instead of burying it in logs (VERDICT r2)
        self.last_save_phases: Dict[str, float] = {}
        # stage breakdown of the last restore (tier + read/assemble/
        # h2d seconds) — same surfacing contract as the save phases
        self.last_restore_phases: Dict[str, Any] = {}
        self._local_rank = (
            local_rank if local_rank is not None
            else env_utils.get_local_rank()
        )
        self._rank = (
            global_rank if global_rank is not None else env_utils.get_rank()
        )
        self._world_size = (
            world_size if world_size is not None
            else env_utils.get_world_size()
        )
        self._shm_handler = SharedMemoryHandler(self._local_rank, host=False)
        self._shm_lock = SharedLock(
            f"{LOCK_PREFIX}_{self._local_rank}", create=False
        )
        # the LOCAL lead process drives its node's saver: each agent
        # hosts one saver and persists its node's shards, so every
        # node's local rank 0 must enqueue SAVE events.  (Gating on
        # GLOBAL rank 0 — the old condition — meant a multi-NODE
        # GSPMD job never persisted rank>0 shards: node 1's saver got
        # no events, and the world-2 commit waited forever for a done
        # file nobody would write.  Found by the elastic-resize chaos
        # run.)
        self._event_queue = (
            SharedQueue(EVENT_QUEUE, create=False)
            if self._local_rank == 0 else None
        )
        self._storage = get_checkpoint_storage(path=checkpoint_dir)
        # sparse (KvVariable) state adapter: when registered, every
        # save asks it for an export snapshot that rides the shm
        # segment under the reserved "__kv__" key, and every restore
        # imports (or cross-world reshards) the blobs back before the
        # dense state is returned
        self._sparse = None
        self._warned_keep_latest = False
        self._notified_agent = False
        self._deletion_keep_latest = deletion_keep_latest
        self._cached_step = -1
        # ship the saver config now so the agent-side saver (and its
        # shm/meta/lock servers) exists before the first load()
        # (reference creates the saver at engine construction too,
        # engine.py:253)
        self._notify_agent_to_create_saver()
        # trainer-side restore pre-fault: page-table population is
        # per process, so the agent's prefetch warms the AGENT — a
        # respawned trainer still cold-faults every page of the shm
        # snapshot inside the restore's assemble stage (measured ~5x
        # the warm copy).  Kick the strided touches on a daemon
        # thread NOW, overlapped with the caller's model build / jit
        # trace; by the time load() runs, the mapping is (mostly)
        # warm.  Only for respawns — a first incarnation has no
        # snapshot to warm.
        self._prefault_thread = None
        if env_utils.get_restart_count() > 0 and os.getenv(
            "DLROVER_RESTORE_PREFETCH", "1"
        ).strip().lower() not in ("0", "false", "no", "off"):
            self._prefault_thread = threading.Thread(
                target=self._prefault_shm,
                daemon=True,
                name="restore-prefault",
            )
            self._prefault_thread.start()

    def _prefault_shm(self):
        try:
            nbytes = self._shm_handler.prefault()
            if nbytes:
                logger.info(
                    "pre-faulted %.1f MB of shm snapshot during "
                    "trainer setup", nbytes / 2**20,
                )
        except Exception:  # noqa: BLE001 - warmup must never break
            logger.exception("shm pre-fault failed")

    @property
    def global_shard_num(self) -> int:
        return 1 if self.replicated else self._world_size

    def register_sparse(self, adapter) -> None:
        """Attach a
        :class:`~dlrover_tpu.checkpoint.sparse.SparseStateAdapter`:
        its KvVariable tables become checkpoint state alongside the
        dense pytree.  Requires dict-shaped state dicts (the blobs
        nest under the reserved ``__kv__`` key)."""
        if self.replicated and self._world_size > 1:
            # replicated persists only rank 0's shard
            # (global_shard_num=1): every other rank's kv rows would
            # silently vanish on a storage-tier restore.
            raise ValueError(
                "sparse state requires per-rank shards: construct the "
                "engine with replicated=False for world_size "
                f"{self._world_size} (replicated=True persists only "
                "rank 0, losing every other rank's kv rows)"
            )
        self._sparse = adapter

    def _merge_sparse(self, state_dict, step: int,
                      durable: bool = False):
        """Fold the adapter's export snapshot into a COPY of the
        state dict.  Runs synchronously with respect to table
        mutation (before the async writer takes over), so the sparse
        snapshot is consistent with the dense one: the save stall
        grows only by the export memcpy — the tables are host RAM
        already, there is no device fetch to wait on.

        ``durable`` marks a save headed for a committed storage step
        dir: with delta checkpoints enabled the adapter then exports
        only the rows touched since the previous durable export
        (periodic full bases, chain metadata under the kv subtree);
        memory-only saves always export full state — the shm segment
        holds exactly one snapshot and must stand alone."""
        if self._sparse is None:
            return state_dict
        if not isinstance(state_dict, dict):
            raise TypeError(
                "a sparse adapter requires a dict state_dict (the kv "
                f"blobs ride under {KV_STATE_KEY!r}); got "
                f"{type(state_dict).__name__}"
            )
        if KV_STATE_KEY in state_dict:
            return state_dict
        if durable and self._sparse.delta_checkpoints_enabled() and (
            # the newest delta's chain spans at most full_every
            # committed steps (base included), so keep_latest >=
            # full_every retains every link — the documented contract
            0 < self._deletion_keep_latest
            < self._sparse.delta_full_every()
        ) and not self._warned_keep_latest:
            self._warned_keep_latest = True
            logger.warning(
                "delta flash checkpoints need every chain link on "
                "storage, but deletion_keep_latest=%d < full_every="
                "%d — a pruned link breaks restore; raise "
                "keep_latest or lower full_every",
                self._deletion_keep_latest,
                self._sparse.delta_full_every(),
            )
        merged = dict(state_dict)
        merged[KV_STATE_KEY] = self._sparse.export_for_checkpoint(
            step=step, rank=self._rank, durable=durable
        )
        return merged

    def _notify_agent_to_create_saver(self):
        """Ship the saver config to the agent's factory queue once
        (reference: engine.py:253)."""
        if self._notified_agent or self._local_rank != 0:
            self._notified_agent = True
            return
        from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
        from dlrover_tpu.common.multi_process import _socket_path

        if AsyncCheckpointSaver.get_ckpt_saver() is not None:
            # saver already exists in this process (tests / local mode)
            self._notified_agent = True
            return
        if not os.path.exists(_socket_path(FACTORY_QUEUE)):
            # standalone mode (no tpurun agent): host the saver in this
            # process so the shm/meta/lock servers exist and persists
            # still happen asynchronously — they just no longer survive
            # a crash of *this* process (the agent-process deployment
            # does; reference behaviour is a warning + no persistence)
            logger.warning(
                "no agent checkpoint-saver factory found; hosting an "
                "in-process saver (snapshots will not survive a crash "
                "of this process)"
            )
            AsyncCheckpointSaver._instance = AsyncCheckpointSaver(
                SaverConfig(
                    checkpoint_dir=self.checkpoint_dir,
                    local_shard_num=1,
                    global_shard_num=self.global_shard_num,
                    node_rank=env_utils.get_node_rank(),
                    deletion_keep_latest=self._deletion_keep_latest,
                )
            )
            self._notified_agent = True
            return
        factory = SharedQueue(FACTORY_QUEUE, create=False)
        factory.put(
            SaverConfig(
                checkpoint_dir=self.checkpoint_dir,
                local_shard_num=env_utils.get_local_world_size(),
                global_shard_num=self.global_shard_num,
                node_rank=env_utils.get_node_rank(),
                deletion_keep_latest=self._deletion_keep_latest,
            )
        )
        self._notified_agent = True

    # -- save ---------------------------------------------------------------

    def save_to_memory(
        self, step: int, state_dict, path: str = "",
        block_lock: bool = False, durable: bool = False,
    ) -> bool:
        """Synchronous part of a flash save: device->host copy into
        shm under the shm lock.  Non-blocking lock by default: if the
        agent is still persisting the previous snapshot, skip this
        save rather than stall training (reference:
        save_state_dict_to_memory, engine.py:291).  The async writer
        thread passes ``block_lock=True`` — it is off the training
        path, so waiting for the agent is free and the save must not
        be silently dropped."""
        self._notify_agent_to_create_saver()
        from dlrover_tpu.checkpoint.shm_handler import paged_enabled

        # paged hot saves (DLROVER_SHM_PAGED): write only what
        # changed — dense leaves copy-skipped, sparse rows as delta
        # pages via the shm dirty-consumer slot.  Sparse DURABLE
        # saves stay flat: their delta chain belongs to the storage
        # consumer and replays from committed step dirs, not shm.
        use_paged = (
            paged_enabled()
            and not durable
            and isinstance(state_dict, dict)
            and KV_STATE_KEY not in state_dict
        )
        # sparse tables export here on the SYNC path (MEMORY saves /
        # no-device-array states); the async path already merged a
        # consistent export before queueing, which the key guard skips
        merged_here = (
            not use_paged
            and self._sparse is not None
            and isinstance(state_dict, dict)
            and KV_STATE_KEY not in state_dict
        )
        if not use_paged:
            state_dict = self._merge_sparse(state_dict, step, durable)
        # every rank locks its shard: the agent's breakpoint save reads
        # all local shards, so an unlocked write can be torn even for
        # ranks that never persist to storage; without an agent there
        # is no concurrent reader and no lock server to talk to
        locked = False
        lock_wait = 0.0
        if self._agent_lock_available():
            t0 = time.perf_counter()
            if not self._shm_lock.acquire(
                blocking=block_lock, timeout=600.0
            ):
                logger.info(
                    "step %s: saver busy persisting; skipping shm save",
                    step,
                )
                _SAVE_SKIPPED_TOTAL.inc(reason="saver_busy")
                if merged_here and durable:
                    # a delta export already DRAINED its baseline;
                    # the skipped save means those rows never became
                    # durable — the next export must re-base
                    self._sparse.checkpoint_chain_poison()
                return False
            lock_wait = time.perf_counter() - t0
            locked = True
        try:
            config = CheckpointConfig(
                step=step,
                path=path or self.checkpoint_dir,
                rank=self._rank,
                world_size=self._world_size,
                global_shard_num=self.global_shard_num,
            )
            start = time.time()
            if use_paged:
                self._save_paged(step, state_dict, config)
            else:
                self._shm_handler.save_state_dict(state_dict, config)
            self._cached_step = step
            phases = dict(self._shm_handler.last_save_phases)
            phases["lock_wait_s"] = round(lock_wait, 3)
            phases["total_s"] = round(time.time() - start + lock_wait, 3)
            self.last_save_phases = phases
            _SHM_SAVE_SECONDS.observe(phases["total_s"])
            mode = "paged" if phases.get("paged") else "flat"
            for stage in ("fetch", "compare", "memcpy", "kv", "publish"):
                sec = phases.get(f"{stage}_s")
                if sec is not None:
                    _SAVE_STAGE_SECONDS.observe(
                        float(sec), mode=mode, stage=stage
                    )
            emit_event(
                "checkpoint_shm_save",
                step=step,
                rank=self._rank,
                **{k: v for k, v in phases.items()},
            )
            logger.info(
                "rank %s shm save of step %s took %.3fs "
                "(lock %.2fs, d2h fetch %.2fs, memcpy %.2fs)",
                self._rank, step, time.time() - start,
                lock_wait, phases.get("fetch_s", 0.0),
                phases.get("memcpy_s", 0.0),
            )
            return True
        finally:
            if locked:
                self._shm_lock.release()

    def _save_paged(self, step: int, state_dict, config) -> None:
        """One paged hot save under the shm lock: export the sparse
        delta on the shm consumer slot, hand it to the handler as a
        delta page; when the handler cannot take a delta (fresh/
        invalid epoch, arena overflow) poison the shm chain,
        re-export a full base and retry once.  Any failure after the
        delta drained its baseline also poisons — those rows must
        ride the next base, not vanish."""
        from dlrover_tpu.checkpoint.shm_handler import (
            PagedNeedBase,
            shm_full_every,
        )

        kv_payload = None
        if self._sparse is not None:
            kv_payload = self._sparse.export_for_shm(
                step=step, rank=self._rank,
                full_every=shm_full_every(),
            )
        try:
            try:
                self._shm_handler.save_state_dict_paged(
                    state_dict, config, kv_payload=kv_payload
                )
                return
            except PagedNeedBase as e:
                logger.info(
                    "paged save of step %s re-basing: %s", step, e
                )
                if self._sparse is not None:
                    self._sparse.shm_chain_poison()
                    kv_payload = self._sparse.export_for_shm(
                        step=step, rank=self._rank,
                        full_every=shm_full_every(),
                    )
                self._shm_handler.save_state_dict_paged(
                    state_dict, config, kv_payload=kv_payload
                )
        except Exception:
            if self._sparse is not None:
                self._sparse.shm_chain_poison()
            raise

    def _agent_lock_available(self) -> bool:
        """Whether an agent-side lock server exists for this shard
        (absent in standalone/no-agent mode, where save_to_memory has
        no concurrent reader to guard against)."""
        from dlrover_tpu.common.multi_process import _socket_path

        return os.path.exists(
            _socket_path(f"{LOCK_PREFIX}_{self._local_rank}")
        )

    # -- async snapshot path -------------------------------------------------

    def _device_snapshot(self, state_dict):
        """Copy every device-array leaf to a fresh on-device buffer.

        The copy runs at HBM bandwidth (milliseconds) and protects the
        snapshot from buffer donation in the caller's jitted train
        step; mutable host arrays are copied too (typically tiny —
        step counters and the like), immutable scalars pass through.
        """
        import jax
        import numpy as np

        leaves, treedef = jax.tree_util.tree_flatten(state_dict)
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, np.ndarray):
                leaves[i] = leaf.copy()
        idx = [
            i for i, leaf in enumerate(leaves)
            if isinstance(leaf, jax.Array)
        ]
        if idx:
            if self._jit_copy is None:
                import jax.numpy as jnp

                self._jit_copy = jax.jit(
                    lambda xs: [jnp.copy(x) for x in xs]
                )
            copied = self._jit_copy([leaves[i] for i in idx])
            for i, c in zip(idx, copied):
                leaves[i] = c
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _ensure_writer(self):
        with self._writer_lock:
            if self._writer_thread is None or (
                not self._writer_thread.is_alive()
            ):
                self._writer_thread = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name="ckpt-snapshot-writer",
                )
                self._writer_thread.start()

    def _writer_loop(self):
        while True:
            item = self._writer_queue.get()
            if item is None:
                return
            step, snap, path, enqueue = item
            try:
                with _ASYNC_WRITE_SECONDS.time():
                    ok = self.save_to_memory(
                        step, snap, path, block_lock=True
                    )
                if ok and enqueue and self._event_queue is not None:
                    self._event_queue.put(
                        CheckpointEvent(
                            event_type=CheckpointEventType.SAVE, step=step
                        )
                    )
            except Exception as e:  # noqa: BLE001
                self._last_async_error = e
                _SAVE_ERRORS_TOTAL.inc()
                if self._sparse is not None:
                    # the queued snapshot may hold a drained delta
                    # that never reached shm — re-base next export
                    self._sparse.checkpoint_chain_poison()
                logger.exception(
                    "async snapshot of step %s failed", step
                )
            finally:
                self._writer_queue.task_done()

    def wait_async(self, timeout: float = 600.0) -> bool:
        """Block until in-flight async snapshots are written to shm
        (tests / shutdown); returns False on timeout.
        ``unfinished_tasks`` counts queued and in-progress items."""
        deadline = time.monotonic() + timeout
        while self._writer_queue.unfinished_tasks:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.02)
        return True

    def save_to_storage(self, step: int, state_dict, path: str = "") -> bool:
        """Flash save: shm write + async persist by the agent
        (reference: save_to_storage in full_ckpt_engine.py).

        With ``async_snapshot`` (default) the training stall is only
        the on-device copy; the host fetch + shm write happen on the
        writer thread, which then enqueues the agent persist."""
        import jax

        has_device_arrays = any(
            isinstance(leaf, jax.Array)
            for leaf in jax.tree_util.tree_leaves(state_dict)
        )
        if self._async_snapshot and has_device_arrays:
            if self._writer_queue.unfinished_tasks:
                logger.info(
                    "step %s: previous snapshot still writing; "
                    "skipping save", step,
                )
                _SAVE_SKIPPED_TOTAL.inc(reason="writer_busy")
                return False
            snap = self._device_snapshot(state_dict)
            # sparse export joins the snapshot NOW — synchronous with
            # respect to table mutation, like the on-device copy is
            # for the dense leaves; the writer thread must not read a
            # table the next train step is already scattering into
            snap = self._merge_sparse(snap, step, durable=True)
            # kick off the device->host transfers without blocking
            for leaf in jax.tree_util.tree_leaves(snap):
                if isinstance(leaf, jax.Array):
                    try:
                        leaf.copy_to_host_async()
                    except Exception:  # noqa: BLE001
                        break
            self._ensure_writer()
            self._writer_queue.put((step, snap, path, True))
            return True
        ok = self.save_to_memory(step, state_dict, path, durable=True)
        if ok and self._event_queue is not None:
            self._event_queue.put(
                CheckpointEvent(
                    event_type=CheckpointEventType.SAVE, step=step
                )
            )
        return ok

    # -- load ---------------------------------------------------------------

    def _record_restore(
        self, tier: str, step: Optional[int], total_s: float,
        phases: Dict[str, Any], sp=None,
    ):
        """One restore's telemetry: phase dict on the engine (bench
        reads it), stage histograms, restore span attributes and the
        ``checkpoint_restore`` event (its ``tier`` field is what the
        chaos tier-fallback invariant keys on)."""
        phases = dict(phases)
        phases["total_s"] = round(total_s, 4)
        self.last_restore_phases = {"tier": tier, **phases}
        _RESTORE_SECONDS.observe(total_s, tier=tier)
        for stage in ("read", "assemble", "h2d"):
            # absent stages record nothing: orbax is opaque (no
            # stages at all), and the host-array load paths have no
            # h2d stage — their phases report h2d_s=0 for humans,
            # but 0.0 samples would fabricate the percentiles this
            # histogram exists to surface
            val = phases.get(f"{stage}_s")
            if val is not None and (stage != "h2d" or val > 0):
                _RESTORE_STAGE_SECONDS.observe(
                    val, tier=tier, stage=stage
                )
        if sp is not None:
            sp.set_attribute("tier", tier)
            for key, val in phases.items():
                sp.set_attribute(key, val)
        emit_event(
            "checkpoint_restore", step=step, tier=tier,
            rank=self._rank, **phases,
        )

    def load(self) -> Tuple[Optional[int], Any]:
        """Restore: shm snapshot if present (fast path after process
        restart), else storage via the tracker file.  Both tiers run
        the staged read/assemble pipeline; the per-stage breakdown
        lands in ``last_restore_phases``, the ``ckpt.restore`` span
        and the ``checkpoint_restore`` event."""
        from dlrover_tpu.checkpoint.restore import RestoreStats
        from dlrover_tpu.telemetry.tracing import span as _span

        with _span("ckpt.restore") as sp:
            stats = RestoreStats()
            t0 = time.perf_counter()
            config, state = self.get_state_dict_from_memory(stats)
            if (
                config is not None
                and self._sparse is not None
                and int(getattr(config, "world_size", 0) or 0)
                != self._world_size
            ):
                # the dense cross-world rule applies to kv state too:
                # an shm snapshot of another world is per-node state —
                # sparse cross-world restores reshard the hash table
                # from the globally COMMITTED storage tier
                logger.warning(
                    "shm snapshot is from world size %s but this "
                    "world is %s; skipping the shm tier (sparse "
                    "cross-world restores reshard from storage)",
                    config.world_size, self._world_size,
                )
                config, state = None, {}
            if config is not None:
                state = self._consume_sparse(
                    state, stats, tier="shm", step=config.step
                )
                self._record_restore(
                    "shm", config.step, time.perf_counter() - t0,
                    stats.to_phases(), sp,
                )
                logger.info(
                    "restored step %s from shared memory "
                    "(read %.3fs, assemble %.3fs, %d workers)",
                    config.step, stats.read_s, stats.assemble_s,
                    stats.workers,
                )
                return config.step, state
            stats = RestoreStats()
            t0 = time.perf_counter()
            step, state = self.load_from_storage(stats)
            if step is not None:
                self._record_restore(
                    "storage", step, time.perf_counter() - t0,
                    stats.to_phases(), sp,
                )
            else:
                sp.set_attribute("tier", "none")
            return step, state

    def get_state_dict_from_memory(self, stats=None):
        """shm-tier restore.  With ``stats=None`` (direct callers,
        e.g. the bench's shm-only measurement) the engine records the
        restore itself; inside :meth:`load` the caller passes its
        accumulator and records with the tier decision."""
        from dlrover_tpu.checkpoint.restore import RestoreStats

        own = stats is None
        if own:
            stats = RestoreStats()
        t0 = time.perf_counter()
        try:
            config, state = self._shm_handler.load_state_dict(
                stats=stats
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("shm restore failed: %s", e)
            return None, {}
        if own and config is not None:
            self._record_restore(
                "shm", config.step, time.perf_counter() - t0,
                stats.to_phases(),
            )
        return config, state

    def _consume_sparse(self, state, stats, tier: str, step):
        """Pop the ``__kv__`` subtree out of a restored (same-world)
        state dict and import it into the registered tables; the kv
        stage timings land in ``stats.extra`` so the restore event
        and the timeline's restore slices show them."""
        if self._sparse is None or not isinstance(state, dict):
            return state
        kv_state = state.pop(KV_STATE_KEY, None)
        if kv_state is None:
            logger.warning(
                "sparse adapter registered but checkpoint step %s "
                "carries no kv state; tables left untouched", step,
            )
            return state
        self._import_kv_same_world(kv_state, tier, step, stats)
        return state

    def _import_kv_same_world(self, kv_state, tier, step, stats):
        """Same-world kv import, delta-chain aware: a full/base blob
        imports verbatim; a delta blob replays its chain — base +
        intermediate deltas read from the committed storage step dirs
        named in the link metadata, then the blob in hand.  A broken
        chain (pruned or never-persisted link) raises: silently
        restoring partial sparse state would be worse than failing
        the tier loudly."""
        meta = kv_state.get(KV_META_KEY)
        if isinstance(meta, dict) and meta.get("kind") == "delta":
            want_rank = 0 if self.replicated else self._rank
            links = self._kv_chain_links(meta, want_rank)
            if links is None:
                raise RuntimeError(
                    f"kv delta checkpoint of step {step} is "
                    "unusable: a chain link is missing from storage "
                    "(pruned by deletion_keep_latest, or its persist "
                    "never committed)"
                )
            info = self._sparse.import_chain(
                links + [kv_state], tier=tier, step=step,
                rank=self._rank,
            )
        else:
            info = self._sparse.import_state(
                kv_state, tier=tier, step=step, rank=self._rank
            )
        stats.extra.update(info)

    def _read_kv_state_at(self, step: int, rank: int):
        """One rank's nested kv subtree of a SPECIFIC committed step,
        as lazy views into the shard's mmap (the streaming import
        pages in only the window it copies).  None when the step dir
        or the kv subtree is absent."""
        from dlrover_tpu.checkpoint.saver import read_checkpoint_at
        from dlrover_tpu.checkpoint.sparse import SparseStateAdapter

        got_step, shards = read_checkpoint_at(
            self.checkpoint_dir, step, self._storage, only_rank=rank,
        )
        if got_step is None or rank not in shards:
            return None
        meta, raw = shards[rank]
        flat, _metas = flat_from_raw(meta, raw, detach=False)
        kv_flat, _rest = SparseStateAdapter.split_flat(flat)
        if not kv_flat:
            return None
        # the views reference `raw` via .base, so the mapping stays
        # alive for as long as the caller holds the nested dict
        return SparseStateAdapter.nest_flat(kv_flat)

    def _kv_chain_links(self, kv_meta, rank: int):
        """Resolve a delta link's replay prefix (base + intermediate
        deltas, oldest first) for one rank; None when any link is
        missing."""
        from dlrover_tpu.checkpoint.sparse import SparseStateAdapter

        links = []
        for s in SparseStateAdapter.chain_steps(kv_meta):
            st = self._read_kv_state_at(int(s), rank)
            if st is None:
                logger.error(
                    "kv delta chain broken: step %s has no kv shard "
                    "for rank %s on storage", s, rank,
                )
                return None
            links.append(st)
        return links

    def _kv_chains_for(self, nested_per_rank):
        """{rank: [links..., blob]} for a cross-world streaming
        reshard, resolving each rank's delta chain; None when any
        chain is broken."""
        chains = {}
        for rank, kv_state in sorted(nested_per_rank.items()):
            meta = kv_state.get(KV_META_KEY)
            if isinstance(meta, dict) and meta.get("kind") == "delta":
                links = self._kv_chain_links(meta, rank)
                if links is None:
                    return None
                chains[rank] = links + [kv_state]
            else:
                chains[rank] = [kv_state]
        return chains

    def _checkpoint_world(self, meta) -> Optional[int]:
        """World size stamped on a persisted shard's meta (the
        CheckpointConfig every save publishes)."""
        cfg = meta.get("config") if isinstance(meta, dict) else None
        if cfg is None:
            return None
        return int(getattr(cfg, "world_size", 0) or 0) or None

    def load_from_storage(self, stats=None) -> Tuple[Optional[int], Any]:
        """Storage-tier restore: tracker -> this rank's shard, read
        as a lazy mmap view and detached through the chunked parallel
        pipeline (page-in overlaps the copies).

        With a sparse adapter registered, every rank file is read:
        same-world restores import this rank's own kv shard verbatim;
        a WORLD CHANGE reshards — all old ranks' kv rows are
        re-partitioned by key hash and this rank imports its owned
        subset (the dense part then comes from the lowest surviving
        rank, which is only meaningful for replicated dense state —
        GSPMD jobs restore through :meth:`load_sharded`)."""
        from dlrover_tpu.checkpoint.restore import RestoreStats

        own = stats is None
        if own:
            stats = RestoreStats()
        t0 = time.perf_counter()
        want_rank = 0 if self.replicated else self._rank
        step, shards = read_last_checkpoint(
            self.checkpoint_dir, self._storage, stats=stats,
            only_rank=want_rank,
        )
        if step is None:
            return None, {}
        if self._sparse is not None:
            own_shard = shards.get(want_rank)
            ckpt_world = (
                self._checkpoint_world(own_shard[0])
                if own_shard else None
            )
            if own_shard is None or ckpt_world != self._world_size:
                # missing own shard or a world-stamp mismatch: only
                # now pay the all-ranks read (a cross-world reshard
                # needs every old rank's kv shard; the routine
                # same-world restore above reads exactly one file)
                step, shards = read_last_checkpoint(
                    self.checkpoint_dir, self._storage, stats=stats,
                )
                if step is None:
                    return None, {}
            if shards:
                return self._load_sparse_from_storage(
                    step, shards, want_rank, stats, t0, own
                )
        if want_rank not in shards:
            logger.error(
                "checkpoint step %s has no shard for rank %s "
                "(topology changed? shards=%s)",
                step, want_rank, sorted(shards),
            )
            return None, {}
        meta, raw = shards[want_rank]
        state = state_dict_from_raw(meta, raw, stats=stats)
        if own:
            self._record_restore(
                "storage", step, time.perf_counter() - t0,
                stats.to_phases(),
            )
        logger.info(
            "restored step %s from storage (read %.3fs, assemble "
            "%.3fs, %d workers)",
            step, stats.read_s, stats.assemble_s, stats.workers,
        )
        return step, state

    def _load_sparse_from_storage(
        self, step, shards, want_rank, stats, t0, own,
    ):
        """Storage restore with kv state: same-world = own shard
        verbatim; cross-world = dense from the lowest surviving rank
        + the hash-resharded kv subset."""
        any_meta = shards[min(shards)][0]
        ckpt_world = self._checkpoint_world(any_meta) or len(shards)
        if ckpt_world == self._world_size and want_rank not in shards:
            # the world did NOT change — a missing own shard is a
            # broken checkpoint (partial commit, lost file), not a
            # reshard: falling through would silently hand this rank
            # another rank's DENSE state
            logger.error(
                "checkpoint step %s has no shard for rank %s though "
                "the world size (%s) is unchanged; treating the "
                "checkpoint as unusable", step, want_rank, ckpt_world,
            )
            return None, {}
        same_world = (
            ckpt_world == self._world_size and want_rank in shards
        )
        if same_world:
            meta, raw = shards[want_rank]
            state = state_dict_from_raw(meta, raw, stats=stats)
            state = self._consume_sparse(
                state, stats, tier="storage", step=step
            )
        else:
            logger.warning(
                "checkpoint step %s is from world %s, this world is "
                "%s: streaming-resharding kv state from %d rank "
                "file(s)", step, ckpt_world, self._world_size,
                len(shards),
            )
            from dlrover_tpu.checkpoint.sparse import (
                SparseStateAdapter,
            )

            dense_rank = (
                want_rank if want_rank in shards else min(shards)
            )
            kv_per_rank = {}
            state = {}
            # kv subtrees stay LAZY VIEWS into each shard's mmap —
            # the streaming reshard copies one window at a time, so
            # peak extra RAM is O(window), not O(sum of shards).
            # Only the dense rank's remainder is materialized.
            for rank, (meta, raw) in sorted(shards.items()):
                flat, metas = flat_from_raw(
                    meta, raw, detach=False, stats=stats
                )
                kv_flat, _rest = SparseStateAdapter.split_flat(flat)
                if kv_flat:
                    kv_per_rank[rank] = SparseStateAdapter.nest_flat(
                        kv_flat
                    )
                if rank == dense_rank:
                    state = self._detach_dense_flat(
                        flat, metas, stats
                    )
            if kv_per_rank:
                chains = self._kv_chains_for(kv_per_rank)
                if chains is None:
                    # same contract as the load_sharded path: a
                    # broken chain fails the restore LOUDLY —
                    # returning "no checkpoint" would silently
                    # restart the job from scratch
                    raise RuntimeError(
                        f"kv delta chain of step {step} is unusable "
                        "for the cross-world reshard (a link is "
                        "missing from storage)"
                    )
                info = self._sparse.import_shards_streaming(
                    chains,
                    world_size=self._world_size,
                    rank=self._rank,
                    from_world=ckpt_world,
                    tier="storage",
                    step=step,
                )
                stats.extra.update(info)
        if own:
            self._record_restore(
                "storage", step, time.perf_counter() - t0,
                stats.to_phases(),
            )
        logger.info(
            "restored step %s from storage (read %.3fs, assemble "
            "%.3fs, %d workers)",
            step, stats.read_s, stats.assemble_s, stats.workers,
        )
        return step, state

    def _detach_dense_flat(self, flat, metas, stats):
        """Materialize the dense remainder of a flat VIEW dict (kv
        entries already split out): array views detach through the
        staged pipeline, scalars pass through, shard entries
        assemble — the pieces of ``state_dict_from_raw`` without
        re-reading (or detaching) the kv blobs."""
        import time as _time

        from dlrover_tpu.checkpoint.restore import detach_flat
        from dlrover_tpu.checkpoint.shm_handler import (
            _assemble_flat,
            _unflatten_to_nested,
        )

        views = {
            k: v for k, v in flat.items()
            if isinstance(v, np.ndarray) and v.base is not None
        }
        out = dict(flat)
        out.update(detach_flat(views, stats=stats))
        t0 = _time.perf_counter()
        out = _assemble_flat(out, metas)
        if stats is not None:
            stats.assemble_s += _time.perf_counter() - t0
        return _unflatten_to_nested(out)

    def load_sharded(
        self, target_state, orbax_dir: str = "",
    ) -> Tuple[Optional[int], Any]:
        """Restore a GSPMD-sharded pytree onto ``target_state``'s
        shardings, re-sharding as needed (reference capability:
        fsdp_engine.py re-shard on load).

        Tier order: (1) this rank's shm snapshot, (2) all visible
        rank files of the last committed storage step (covers any
        topology change on a shared filesystem), (3) the orbax tier at
        ``orbax_dir``.  Every target shard is assembled from the
        overlapping saved shard boxes; a tier is skipped when its
        shards do not cover the target arrays.

        Both flash tiers run the staged pipeline: the shm/mmap
        snapshot is consumed as zero-copy views (shard assembly copies
        straight out of them on the restore pool; plain leaves feed
        batched ``device_put``), so shard k+1 is paging in while shard
        k is in flight to the device.
        """
        from dlrover_tpu.checkpoint.restore import RestoreStats
        from dlrover_tpu.telemetry.tracing import span as _span

        with _span("ckpt.restore") as sp:
            sp.set_attribute("sharded", True)
            stats = RestoreStats()
            t0 = time.perf_counter()
            config, flat, metas = self._shm_handler.load_flat(
                detach=False, stats=stats
            )
            if config is not None and int(
                getattr(config, "world_size", 0) or 0
            ) != self._world_size:
                # elastic world-resize: an shm snapshot from a
                # DIFFERENT world size is per-node state — each
                # survivor's segment may hold a different step, so
                # assembling from them would desync the re-formed
                # world.  Cross-world restores use the globally
                # COMMITTED storage tier; that is where the N-hosts ->
                # M-hosts shard redistribution happens.
                logger.warning(
                    "shm snapshot is from world size %s but this "
                    "world is %s; skipping the shm tier (cross-world "
                    "restores reshard from committed storage)",
                    config.world_size, self._world_size,
                )
                config, flat = None, {}
            if config is not None and flat:
                kv_flat = (
                    self._split_kv_flat(flat)
                    if self._sparse is not None else {}
                )
                state = self._assemble_to_target(
                    target_state, flat, metas, stats
                )
                if state is not None:
                    if kv_flat:
                        from dlrover_tpu.checkpoint.sparse import (
                            SparseStateAdapter,
                        )

                        self._import_kv_same_world(
                            SparseStateAdapter.nest_flat(kv_flat),
                            tier="shm", step=config.step,
                            stats=stats,
                        )
                    self._record_restore(
                        "shm", config.step,
                        time.perf_counter() - t0, stats.to_phases(), sp,
                    )
                    logger.info(
                        "restored sharded step %s from shared memory "
                        "(read %.3fs, assemble %.3fs, h2d %.3fs)",
                        config.step, stats.read_s, stats.assemble_s,
                        stats.h2d_s,
                    )
                    return config.step, state
            stats = RestoreStats()
            t0 = time.perf_counter()
            step, shards = read_last_checkpoint(
                self.checkpoint_dir, self._storage, stats=stats
            )
            if step is not None and shards:
                flat_all: Dict[str, Any] = {}
                metas_all: Dict[str, Any] = {}
                kv_per_rank: Dict[int, Dict[str, Any]] = {}
                for rank, (meta, raw) in sorted(shards.items()):
                    f, m = flat_from_raw(
                        meta, raw, detach=False, stats=stats
                    )
                    if self._sparse is not None:
                        # kv keys carry no shard suffix, so across
                        # ranks they would collide in flat_all (last
                        # rank silently winning) — each rank's rows
                        # are DISTINCT table shards, not replicas
                        kv_f = self._split_kv_flat(f)
                        if kv_f:
                            kv_per_rank[rank] = kv_f
                    for key, val in f.items():
                        # shard keys collide across ranks; namespace them
                        nk = (
                            f"{key}~r{rank}" if SHARD_SEP in key else key
                        )
                        flat_all[nk] = val
                        if key in m:
                            metas_all[nk] = m[key]
                state = self._assemble_to_target(
                    target_state, flat_all, metas_all, stats
                )
                if state is not None:
                    if kv_per_rank:
                        self._import_sharded_kv(
                            kv_per_rank, shards, step, stats
                        )
                    self._record_restore(
                        "storage", step,
                        time.perf_counter() - t0, stats.to_phases(), sp,
                    )
                    logger.info(
                        "restored sharded step %s from storage "
                        "(%d rank files; read %.3fs, assemble %.3fs, "
                        "h2d %.3fs)", step, len(shards), stats.read_s,
                        stats.assemble_s, stats.h2d_s,
                    )
                    return step, state
            if orbax_dir:
                from dlrover_tpu.checkpoint.orbax_compat import (
                    GlobalCheckpointer,
                )

                t0 = time.perf_counter()
                ckptr = GlobalCheckpointer(orbax_dir)
                try:
                    step, state = ckptr.restore(target_state)
                finally:
                    ckptr.close()
                if step is not None:
                    # the orbax tier is opaque — total only
                    self._record_restore(
                        "orbax", step, time.perf_counter() - t0,
                        {}, sp,
                    )
                return step, state
            sp.set_attribute("tier", "none")
            return None, {}

    @staticmethod
    def _split_kv_flat(flat: Dict[str, Any]) -> Dict[str, Any]:
        """Pop the ``__kv__/``-prefixed entries out of a flat dict,
        returned keyed relative to the prefix."""
        from dlrover_tpu.checkpoint.sparse import SparseStateAdapter

        kv, rest = SparseStateAdapter.split_flat(flat)
        if kv:
            flat.clear()
            flat.update(rest)
        return kv

    def _import_sharded_kv(self, kv_per_rank, shards, step, stats):
        """kv import for the load_sharded storage tier: own shard
        verbatim (chain-replayed when it is a delta link) when the
        world is unchanged and this rank's file exists, the STREAMING
        hash-reshard otherwise — the nested values are live views
        into the shard mmaps, so only one window is ever private."""
        from dlrover_tpu.checkpoint.sparse import SparseStateAdapter

        nested = {
            rank: SparseStateAdapter.nest_flat(kv)
            for rank, kv in kv_per_rank.items()
        }
        ckpt_world = (
            self._checkpoint_world(shards[min(shards)][0])
            or len(shards)
        )
        if ckpt_world == self._world_size and self._rank in nested:
            self._import_kv_same_world(
                nested[self._rank], tier="storage", step=step,
                stats=stats,
            )
            return
        chains = self._kv_chains_for(nested)
        if chains is None:
            raise RuntimeError(
                f"kv delta chain of step {step} is unusable for the "
                "cross-world reshard (a link is missing from storage)"
            )
        info = self._sparse.import_shards_streaming(
            chains, world_size=self._world_size, rank=self._rank,
            from_world=ckpt_world, tier="storage", step=step,
        )
        stats.extra.update(info)

    def _assemble_to_target(self, target_state, flat, metas, stats=None):
        """Assemble every leaf of ``target_state`` from saved entries;
        None when coverage is incomplete (caller tries next tier).

        Staged: host-side shard assembly for leaf k+1 runs on the
        restore pool while this thread commits leaf k's pieces to the
        devices, and plain host leaves ride batched ``device_put``
        calls (zero-copy views where the backend provably copies, a
        private detach otherwise) — so H2D, memcpy and page-in
        overlap instead of chaining.  The final block_until_ready
        keeps the shm/mmap views alive until every transfer landed.
        """
        import jax

        from dlrover_tpu.checkpoint.restore import (
            RestoreStats,
            StagedRestore,
            chunk_bytes,
            detach_for_device_put,
        )
        from dlrover_tpu.checkpoint.sharded import (
            assemble_shard,
            assemble_target_pieces,
            commit_target_pieces,
            group_shard_entries,
            is_sharded_leaf,
        )
        from dlrover_tpu.checkpoint.shm_handler import (
            _flatten_state_dict,
            _path_str,
        )

        if stats is None:
            stats = RestoreStats()
        grouped, plain = group_shard_entries(flat, metas)
        target_flat = _flatten_state_dict(target_state)

        def host_job(key, target_leaf):
            """Host-side assembly of one leaf (pool thread; numpy
            only).  Returns (kind, payload): 'pieces' per-device host
            arrays for a sharded target, 'plain' a saved host leaf
            (possibly a view), 'plain_private' a freshly assembled
            private array, 'missing' a coverage failure message."""
            if is_sharded_leaf(target_leaf):
                entries = grouped.get(key)
                if entries is None and key in plain:
                    # saved unsharded (replicated whole array)
                    entries = [(
                        tuple((0, d) for d in plain[key].shape),
                        plain[key],
                    )]
                if entries is None:
                    return "missing", f"no saved shards for '{key}'"
                pieces = assemble_target_pieces(
                    tuple(target_leaf.shape),
                    np.dtype(target_leaf.dtype),
                    target_leaf.sharding,
                    entries,
                )
                if pieces is None:
                    return (
                        "missing", f"saved shards do not cover '{key}'"
                    )
                return "pieces", pieces
            if key in plain:
                return "plain", plain[key]
            if key in grouped:
                # saved sharded, target unsharded: assemble fully
                m = None
                for mk, mv in metas.items():
                    if mk.split(SHARD_SEP, 1)[0] == key:
                        m = mv
                        break
                if m is None:
                    return "missing", f"no shard metadata for '{key}'"
                full = assemble_shard(
                    tuple((0, d) for d in m.global_shape),
                    np.dtype(m.dtype),
                    grouped[key],
                )
                if full is None:
                    return (
                        "missing", f"saved shards do not cover '{key}'"
                    )
                return "plain_private", full
            return "missing", f"missing leaf '{key}' in checkpoint"

        out: Dict[str, Any] = {}
        failed: Optional[str] = None
        with StagedRestore() as staged:
            # BOUNDED in-flight window: submitting every leaf upfront
            # would let the pool assemble a full private copy of the
            # state ahead of consumption (serial mode would too — its
            # futures are lazy, but eager submission was the bug) —
            # peak host RAM must stay ~window leaves, not 2x the state
            window = max(2, staged.workers + 2)
            leaf_iter = iter(target_flat.items())
            jobs: list = []
            depth = 0

            def refill():
                nonlocal depth
                while depth < window:
                    nxt = next(leaf_iter, None)
                    if nxt is None:
                        return
                    key, leaf = nxt
                    jobs.append(
                        (key, leaf, staged.submit(host_job, key, leaf))
                    )
                    depth += 1

            refill()
            # batched H2D: plain host leaves accumulate and ship in one
            # device_put call per ~budget bytes — through a remote
            # device link the per-call dispatch overhead dominates
            # small leaves, and a batch issues all transfers at once
            budget = chunk_bytes()
            pending: list = []
            pending_bytes = 0

            def flush():
                nonlocal pending_bytes
                if not pending:
                    return
                t0 = time.perf_counter()
                arrs = jax.device_put(
                    [a for _, a, _ in pending],
                    [s for _, _, s in pending],
                )
                stats.h2d_s += time.perf_counter() - t0
                for (k, _, _), arr in zip(pending, arrs):
                    out[k] = arr
                pending.clear()
                pending_bytes = 0

            # index walk so refill() can append mid-loop AND each
            # consumed slot can be nulled — a completed future pins
            # its assembled host arrays via ._value, and keeping them
            # all would grow peak RAM to a full extra state copy
            i = 0
            while i < len(jobs):
                key, target_leaf, fut = jobs[i]
                jobs[i] = None
                i += 1
                t0 = time.perf_counter()
                try:
                    kind, payload = fut.result()
                except Exception as e:  # noqa: BLE001
                    kind, payload = "missing", f"'{key}': {e}"
                del fut
                stats.assemble_s += time.perf_counter() - t0
                depth -= 1
                if failed is None:
                    refill()
                if failed is not None:
                    continue  # drain remaining futures
                if kind == "missing":
                    failed = payload
                    continue
                if kind == "pieces":
                    t0 = time.perf_counter()
                    out[key] = commit_target_pieces(
                        tuple(target_leaf.shape),
                        target_leaf.sharding, payload,
                    )
                    stats.h2d_s += time.perf_counter() - t0
                    continue
                val = payload
                if isinstance(target_leaf, jax.Array) and isinstance(
                    val, np.ndarray
                ):
                    host = (
                        val if kind == "plain_private"
                        else detach_for_device_put(val)
                    )
                    pending.append((key, host, target_leaf.sharding))
                    pending_bytes += host.nbytes
                    if pending_bytes >= budget:
                        flush()
                elif isinstance(val, np.ndarray) and val.base is not None:
                    # view into shm/mmap headed back to the caller as a
                    # host array: detach — its buffer will be reused
                    out[key] = np.array(val, copy=True)
                else:
                    out[key] = val
            if failed is not None:
                logger.warning(failed)
                return None
            flush()
        # block so the views feeding any zero-copy transfer stay alive
        # until the bytes are on the device, and so h2d_s reports the
        # real transfer time rather than the async dispatch
        t0 = time.perf_counter()
        device_vals = [
            v for v in out.values() if isinstance(v, jax.Array)
        ]
        if device_vals:
            jax.block_until_ready(device_vals)
        stats.h2d_s += time.perf_counter() - t0
        # rebuild with the target's tree structure
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
            target_state
        )
        ordered = []
        for path, _ in leaves_with_path:
            key = "/".join(_path_str(p) for p in path)
            ordered.append(out[key])
        return jax.tree_util.tree_unflatten(treedef, ordered)

    def close(self):
        self.wait_async(timeout=60.0)
        if self._writer_thread is not None and self._writer_thread.is_alive():
            self._writer_queue.put(None)
            self._writer_thread.join(timeout=5.0)
        # the prefault thread holds a numpy view over shm.buf while it
        # touches pages; closing the segment under it raises
        # BufferError — wait it out (page touches are memory-speed)
        if (
            self._prefault_thread is not None
            and self._prefault_thread.is_alive()
        ):
            self._prefault_thread.join(timeout=30.0)
        self._prefault_thread = None
        self._shm_handler.close()
