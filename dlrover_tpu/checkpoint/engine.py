"""Trainer-process checkpoint engine: shm write + async persist enqueue.

Reference: ``CheckpointEngine`` / ``FullCheckpointEngine``
(``dlrover/trainer/torch/flash_checkpoint/engine.py:135,291``,
``full_ckpt_engine.py``): ``save_to_memory`` copies the state dict
into agent-owned shared memory under the shm lock (sub-second,
blocking the train step only for the device->host copy);
``save_to_storage`` additionally enqueues a SAVE event the agent
persists asynchronously; ``load`` prefers the shm snapshot (process
restart with agent alive) and falls back to storage.
"""

import os
import time
from typing import Any, Dict, Optional, Tuple

from dlrover_tpu.checkpoint.saver import (
    EVENT_QUEUE,
    FACTORY_QUEUE,
    LOCK_PREFIX,
    CheckpointEvent,
    CheckpointEventType,
    SaverConfig,
    read_last_checkpoint,
)
from dlrover_tpu.checkpoint.shm_handler import (
    CheckpointConfig,
    SharedMemoryHandler,
    state_dict_from_raw,
)
from dlrover_tpu.common import env_utils
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import SharedLock, SharedQueue
from dlrover_tpu.common.storage import PosixDiskStorage


class CheckpointEngine:
    """Base engine: one per training process.

    ``replicated=True`` (DDP-style full checkpoint): every rank writes
    shm for fast restart-restore, only global rank 0's shard is
    persisted (global_shard_num=1).  ``replicated=False``
    (FSDP/GSPMD-style): every process persists its addressable shard
    (global_shard_num=world_size).
    """

    def __init__(
        self,
        checkpoint_dir: str,
        replicated: bool = True,
        local_rank: Optional[int] = None,
        global_rank: Optional[int] = None,
        world_size: Optional[int] = None,
        deletion_keep_latest: int = 0,
    ):
        self.checkpoint_dir = checkpoint_dir
        self.replicated = replicated
        self._local_rank = (
            local_rank if local_rank is not None
            else env_utils.get_local_rank()
        )
        self._rank = (
            global_rank if global_rank is not None else env_utils.get_rank()
        )
        self._world_size = (
            world_size if world_size is not None
            else env_utils.get_world_size()
        )
        self._shm_handler = SharedMemoryHandler(self._local_rank, host=False)
        self._shm_lock = SharedLock(
            f"{LOCK_PREFIX}_{self._local_rank}", create=False
        )
        self._event_queue = (
            SharedQueue(EVENT_QUEUE, create=False)
            if self._rank == 0 else None
        )
        self._storage = PosixDiskStorage()
        self._notified_agent = False
        self._deletion_keep_latest = deletion_keep_latest
        self._cached_step = -1
        # ship the saver config now so the agent-side saver (and its
        # shm/meta/lock servers) exists before the first load()
        # (reference creates the saver at engine construction too,
        # engine.py:253)
        self._notify_agent_to_create_saver()

    @property
    def global_shard_num(self) -> int:
        return 1 if self.replicated else self._world_size

    def _notify_agent_to_create_saver(self):
        """Ship the saver config to the agent's factory queue once
        (reference: engine.py:253)."""
        if self._notified_agent or self._local_rank != 0:
            self._notified_agent = True
            return
        from dlrover_tpu.checkpoint.saver import AsyncCheckpointSaver
        from dlrover_tpu.common.multi_process import _socket_path

        if AsyncCheckpointSaver.get_ckpt_saver() is not None:
            # saver already exists in this process (tests / local mode)
            self._notified_agent = True
            return
        if not os.path.exists(_socket_path(FACTORY_QUEUE)):
            # standalone mode (no tpurun agent): host the saver in this
            # process so the shm/meta/lock servers exist and persists
            # still happen asynchronously — they just no longer survive
            # a crash of *this* process (the agent-process deployment
            # does; reference behaviour is a warning + no persistence)
            logger.warning(
                "no agent checkpoint-saver factory found; hosting an "
                "in-process saver (snapshots will not survive a crash "
                "of this process)"
            )
            AsyncCheckpointSaver._instance = AsyncCheckpointSaver(
                SaverConfig(
                    checkpoint_dir=self.checkpoint_dir,
                    local_shard_num=1,
                    global_shard_num=self.global_shard_num,
                    node_rank=env_utils.get_node_rank(),
                    deletion_keep_latest=self._deletion_keep_latest,
                )
            )
            self._notified_agent = True
            return
        factory = SharedQueue(FACTORY_QUEUE, create=False)
        factory.put(
            SaverConfig(
                checkpoint_dir=self.checkpoint_dir,
                local_shard_num=env_utils.get_local_world_size(),
                global_shard_num=self.global_shard_num,
                node_rank=env_utils.get_node_rank(),
                deletion_keep_latest=self._deletion_keep_latest,
            )
        )
        self._notified_agent = True

    # -- save ---------------------------------------------------------------

    def save_to_memory(self, step: int, state_dict, path: str = "") -> bool:
        """Synchronous part of a flash save: device->host copy into
        shm under the shm lock.  Non-blocking lock: if the agent is
        still persisting the previous snapshot, skip this save rather
        than stall training (reference: save_state_dict_to_memory,
        engine.py:291)."""
        self._notify_agent_to_create_saver()
        # every rank locks its shard: the agent's breakpoint save reads
        # all local shards, so an unlocked write can be torn even for
        # ranks that never persist to storage; without an agent there
        # is no concurrent reader and no lock server to talk to
        locked = False
        if self._agent_lock_available():
            if not self._shm_lock.acquire(blocking=False):
                logger.info(
                    "step %s: saver busy persisting; skipping shm save",
                    step,
                )
                return False
            locked = True
        try:
            config = CheckpointConfig(
                step=step,
                path=path or self.checkpoint_dir,
                rank=self._rank,
                world_size=self._world_size,
                global_shard_num=self.global_shard_num,
            )
            start = time.time()
            self._shm_handler.save_state_dict(state_dict, config)
            self._cached_step = step
            logger.info(
                "rank %s shm save of step %s took %.3fs",
                self._rank, step, time.time() - start,
            )
            return True
        finally:
            if locked:
                self._shm_lock.release()

    def _agent_lock_available(self) -> bool:
        """Whether an agent-side lock server exists for this shard
        (absent in standalone/no-agent mode, where save_to_memory has
        no concurrent reader to guard against)."""
        from dlrover_tpu.common.multi_process import _socket_path

        return os.path.exists(
            _socket_path(f"{LOCK_PREFIX}_{self._local_rank}")
        )

    def save_to_storage(self, step: int, state_dict, path: str = "") -> bool:
        """Flash save: shm write now, async persist by the agent
        (reference: save_to_storage in full_ckpt_engine.py)."""
        ok = self.save_to_memory(step, state_dict, path)
        if ok and self._event_queue is not None:
            self._event_queue.put(
                CheckpointEvent(
                    event_type=CheckpointEventType.SAVE, step=step
                )
            )
        return ok

    # -- load ---------------------------------------------------------------

    def load(self) -> Tuple[Optional[int], Any]:
        """Restore: shm snapshot if present (fast path after process
        restart), else storage via the tracker file."""
        config, state = self.get_state_dict_from_memory()
        if config is not None:
            logger.info("restored step %s from shared memory", config.step)
            return config.step, state
        return self.load_from_storage()

    def get_state_dict_from_memory(self):
        try:
            return self._shm_handler.load_state_dict()
        except Exception as e:  # noqa: BLE001
            logger.warning("shm restore failed: %s", e)
            return None, {}

    def load_from_storage(self) -> Tuple[Optional[int], Any]:
        step, shards = read_last_checkpoint(
            self.checkpoint_dir, self._storage
        )
        if step is None:
            return None, {}
        want_rank = 0 if self.replicated else self._rank
        if want_rank not in shards:
            logger.error(
                "checkpoint step %s has no shard for rank %s "
                "(topology changed? shards=%s)",
                step, want_rank, sorted(shards),
            )
            return None, {}
        meta, raw = shards[want_rank]
        logger.info("restored step %s from storage", step)
        return step, state_dict_from_raw(meta, raw)

    def close(self):
        self._shm_handler.close()
