"""Shard extraction/assembly for GSPMD flash checkpoints.

Reference capability: ``fsdp_engine.py:568`` (``SharedMemoryWriter`` /
``SharedMemoryReader`` — torch-DCP storage over shm, shard-aware, with
re-shard on load).  The TPU equivalent works on global ``jax.Array``s:

- **save**: each process extracts only its *addressable* shards
  (``arr.addressable_shards``) with their global index ranges — a
  multi-host global array is never device_get whole (that throws).
- **restore, same or different topology**: every target shard is
  assembled by copying the overlapping regions of whatever saved
  shards are visible, so a checkpoint written on mesh ``{fsdp:8}``
  restores onto ``{data:2, fsdp:4}`` without the orbax tier, as long
  as the shard files cover the arrays (always true single-host / on a
  shared filesystem).  When coverage is incomplete (per-host disks
  after a topology change), the caller falls back to the orbax tier
  (``orbax_compat.GlobalCheckpointer``).
"""

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

IndexRanges = Tuple[Tuple[int, int], ...]  # ((start, stop) per dim)

SHARD_SEP = "@shard"


def is_sharded_leaf(leaf) -> bool:
    """True for multi-device or non-addressable global jax.Arrays."""
    import jax

    if not isinstance(leaf, jax.Array):
        return False
    try:
        return (
            not leaf.is_fully_addressable
            or len(leaf.sharding.device_set) > 1
        )
    except Exception:  # noqa: BLE001 — deleted/donated arrays
        return False


def index_ranges(index: Sequence[slice], shape: Sequence[int]) -> IndexRanges:
    """Normalize a shard's tuple-of-slices to ((start, stop), ...)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        out.append((int(start), int(stop)))
    return tuple(out)


def local_shards(leaf) -> List[Tuple[IndexRanges, object]]:
    """This process's distinct shards as (global index ranges, device
    array).  Replicated copies are deduped (lowest replica id wins) so
    a fully-replicated leaf contributes exactly one entry per process.
    """
    shape = leaf.shape
    best: Dict[IndexRanges, Tuple[int, object]] = {}
    for shard in leaf.addressable_shards:
        ranges = index_ranges(shard.index, shape)
        rid = shard.replica_id or 0
        if ranges not in best or rid < best[ranges][0]:
            best[ranges] = (rid, shard.data)
    return [(ranges, data) for ranges, (_, data) in best.items()]


def _overlap(
    a: IndexRanges, b: IndexRanges
) -> Optional[Tuple[IndexRanges, Tuple[slice, ...], Tuple[slice, ...]]]:
    """Intersection of two range boxes; returns (global ranges,
    slices into a-local coords, slices into b-local coords)."""
    inter, a_sl, b_sl = [], [], []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        inter.append((lo, hi))
        a_sl.append(slice(lo - a0, hi - a0))
        b_sl.append(slice(lo - b0, hi - b0))
    return tuple(inter), tuple(a_sl), tuple(b_sl)


def assemble_shard(
    target_ranges: IndexRanges,
    dtype,
    entries: Sequence[Tuple[IndexRanges, np.ndarray]],
) -> Optional[np.ndarray]:
    """Build the target shard by copying overlaps from saved entries;
    None if the entries do not fully cover the target box."""
    shape = tuple(hi - lo for lo, hi in target_ranges)
    out = np.empty(shape, dtype=dtype)
    covered = np.zeros(shape, dtype=bool) if entries else None
    if covered is None:
        return None
    for ranges, data in entries:
        ov = _overlap(target_ranges, ranges)
        if ov is None:
            continue
        _, t_sl, s_sl = ov
        out[t_sl] = data[s_sl]
        covered[t_sl] = True
    if not covered.all():
        return None
    return out


def assemble_target_pieces(
    global_shape: Tuple[int, ...],
    dtype,
    sharding,
    entries: Sequence[Tuple[IndexRanges, np.ndarray]],
) -> Optional[List[Tuple[object, np.ndarray]]]:
    """Host-side half of a target-sharded restore: the per-device
    shard pieces as ``[(device, host_array)]``, or None when the
    saved entries do not cover the target.  Pure numpy — safe on a
    restore-pipeline worker thread; the returned pieces are private
    arrays, so committing them to devices later can never alias the
    source shm/mmap buffer."""
    pieces: List[Tuple[object, np.ndarray]] = []
    for device, index in sharding.addressable_devices_indices_map(
        tuple(global_shape)
    ).items():
        ranges = index_ranges(index, global_shape)
        piece = assemble_shard(ranges, dtype, entries)
        if piece is None:
            return None
        pieces.append((device, piece))
    return pieces


def commit_target_pieces(
    global_shape: Tuple[int, ...], sharding,
    pieces: Sequence[Tuple[object, np.ndarray]],
):
    """Device-side half: ship the host pieces and build the global
    jax.Array.  ``device_put`` transfers are issued back to back
    (asynchronous on real hardware), so piece k+1's H2D overlaps
    piece k's."""
    import jax

    device_arrays = [
        jax.device_put(piece, device) for device, piece in pieces
    ]
    return jax.make_array_from_single_device_arrays(
        tuple(global_shape), sharding, device_arrays
    )


def assemble_global_array(
    global_shape: Tuple[int, ...],
    dtype,
    sharding,
    entries: Sequence[Tuple[IndexRanges, np.ndarray]],
):
    """Assemble a global jax.Array for this process's devices from
    saved (ranges, data) entries; None if coverage is incomplete."""
    pieces = assemble_target_pieces(
        global_shape, dtype, sharding, entries
    )
    if pieces is None:
        return None
    return commit_target_pieces(global_shape, sharding, pieces)


def group_shard_entries(
    flat: Dict[str, np.ndarray], metas: Dict[str, object]
) -> Tuple[Dict[str, List[Tuple[IndexRanges, np.ndarray]]], Dict[str, object]]:
    """Split a flat {key or key@shardN: array} dict into
    (sharded entries grouped by base key, plain leaves)."""
    grouped: Dict[str, List[Tuple[IndexRanges, np.ndarray]]] = {}
    plain: Dict[str, object] = {}
    for key, arr in flat.items():
        if SHARD_SEP in key:
            base = key.split(SHARD_SEP, 1)[0]
            meta = metas.get(key)
            if meta is None or meta.index is None:
                continue
            grouped.setdefault(base, []).append((meta.index, arr))
        else:
            plain[key] = arr
    return grouped, plain
