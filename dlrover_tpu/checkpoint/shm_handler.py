"""Pytree <-> shared-memory serialization.

Reference: ``SharedMemoryHandler`` / ``TensorMeta``
(``dlrover/python/elastic_agent/torch/ckpt_saver.py:65,209``): a state
dict is traversed into one flat shared-memory buffer plus a meta dict
(shape/dtype/offset per leaf) published through a ``SharedDict``; the
agent process re-materializes tensors zero-copy with ``frombuffer``.

The JAX version traverses a pytree with ``jax.tree_util`` key paths.
Array leaves (jax/numpy) are device_get into the shm buffer — for a
sharded ``jax.Array`` only this host's addressable shards would be
copied by the sharded engine; this handler takes whatever ``np.asarray``
of the leaf yields.  Non-array leaves (step counters, strings, opt
hyperparams) are pickled into a trailing blob.
"""

import os
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

try:  # registers bfloat16/fp8 dtypes with numpy for np.dtype(str)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    pass

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import (
    PersistentSharedMemory,
    SharedDict,
    get_or_create_shm,
)


@dataclass
class TensorMeta:
    """Placement of one array leaf inside the flat buffer
    (reference: ckpt_saver.py:65).  For a shard of a global sharded
    ``jax.Array`` (key suffixed ``@shardN``), ``global_shape`` and
    ``index`` carry the reassembly metadata (reference shard-aware
    analog: fsdp_engine.py:568)."""

    shape: Tuple[int, ...] = ()
    dtype: str = "float32"
    offset: int = 0
    nbytes: int = 0
    global_shape: Optional[Tuple[int, ...]] = None
    index: Optional[Tuple[Tuple[int, int], ...]] = None


@dataclass
class CheckpointConfig:
    """Per-snapshot metadata carried with the shm segment
    (reference: ckpt_saver.py:74)."""

    step: int = 0
    path: str = ""
    rank: int = 0
    world_size: int = 1
    # shards expected globally for the commit protocol
    global_shard_num: int = 1
    writing: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)


def _flatten_state_dict(state_dict) -> Dict[str, Any]:
    """Pytree -> {"a/b/0": leaf} using jax key paths."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(state_dict)
    out = {}
    for path, leaf in flat:
        key = "/".join(_path_str(p) for p in path)
        out[key] = leaf
    return out


def _path_str(entry) -> str:
    import jax

    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def _unflatten_to_nested(flat: Dict[str, Any]) -> Dict[str, Any]:
    """{"a/b": v} -> {"a": {"b": v}}; integer-keyed dicts stay dicts
    (exact container types are the engine caller's concern — the state
    dict contract is string/index-keyed nesting, like the reference's
    torch state dicts)."""
    root: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def default_job_suffix() -> str:
    """Namespace shm segments per job so two jobs (or a test run next
    to a live job) on one host never collide: DLROVER_JOB_NAME if set,
    else a hash of the job's IPC socket dir (which agent and trainers
    already share)."""
    import hashlib

    from dlrover_tpu.common.multi_process import socket_dir

    name = os.getenv("DLROVER_JOB_NAME")
    if name:
        return name
    return hashlib.md5(socket_dir().encode()).hexdigest()[:8]


class SharedMemoryHandler:
    """Owns one shm segment + meta SharedDict for one local rank."""

    SHM_PREFIX = "dlrover_tpu_ckpt_shm"
    META_PREFIX = "ckpt_meta"

    def __init__(self, local_rank: int, host: bool = False,
                 job_name: str = ""):
        self._rank = local_rank
        job_name = job_name or default_job_suffix()
        suffix = f"{job_name}_{local_rank}" if job_name else str(local_rank)
        self._shm_name = f"{self.SHM_PREFIX}_{suffix}"
        self._meta = SharedDict(
            f"{self.META_PREFIX}_{suffix}", create=host
        )
        self._shm: Optional[PersistentSharedMemory] = None
        self._write_lock = threading.Lock()
        # phase timings of the last save (seconds): the engine logs
        # them and the bench reports them — the dominant term of a
        # flash save must be measurable, not buried (VERDICT r2)
        self.last_save_phases: Dict[str, float] = {}

    # -- write (trainer side) ---------------------------------------------

    def save_state_dict(self, state_dict, config: CheckpointConfig):
        """Serialize the pytree into shm and publish the meta dict.

        Layout (metas) is computed from array avals BEFORE any
        transfer, then device leaves are fetched in ~256 MB batched
        chunks (``jax.device_get`` issues a chunk's transfers
        concurrently — per-leaf waits pay a transport round trip per
        leaf, measured 3x slower over a high-latency device link)
        and memcpy'd chunk-by-chunk into shm, bounding extra host RAM
        to one chunk instead of a full second state copy.  The engine
        issues ``copy_to_host_async`` on the snapshot up front as a
        best-effort head start.  Note jax caches the host copy on
        each ``jax.Array`` (``_npy_value``): the async engine path
        drops its device snapshot right after this call, bounding
        that overhead to the save window.
        Reference hot path: _traverse_copy_to_shm, ckpt_saver.py:174.

        Phase timings land in ``last_save_phases`` (fetch_s = waiting
        on device->host transfers — the dominant term when the device
        is reached through a slow link; memcpy_s = shm writes).
        """
        import time as _time

        from dlrover_tpu.checkpoint.sharded import (
            SHARD_SEP,
            is_sharded_leaf,
            local_shards,
        )

        flat = _flatten_state_dict(state_dict)
        entries = []  # (key, leaf) in shm layout order
        scalars: Dict[str, Any] = {}
        shard_info: Dict[str, Tuple[Tuple[int, ...], Tuple]] = {}
        for key, leaf in flat.items():
            if isinstance(leaf, (np.ndarray, np.generic)):
                entries.append((key, np.ascontiguousarray(leaf)))
            elif is_sharded_leaf(leaf):
                # global sharded array: only this process's addressable
                # shards go to shm, with reassembly metadata
                gshape = tuple(leaf.shape)
                for i, (ranges, data) in enumerate(local_shards(leaf)):
                    skey = f"{key}{SHARD_SEP}{i}"
                    entries.append((skey, data))
                    shard_info[skey] = (gshape, ranges)
            elif type(leaf).__module__.startswith(("jaxlib", "jax")):
                entries.append((key, leaf))
            else:
                scalars[key] = leaf
        scalar_blob = pickle.dumps(scalars)

        # layout from shapes/dtypes only — no transfer needed yet
        metas: Dict[str, TensorMeta] = {}
        offset = 0
        for key, arr in entries:
            gshape, ranges = shard_info.get(key, (None, None))
            dt = np.dtype(arr.dtype)
            count = int(np.prod(arr.shape, dtype=np.int64)) if (
                arr.shape
            ) else 1
            nbytes = count * dt.itemsize
            metas[key] = TensorMeta(
                shape=tuple(arr.shape),
                dtype=str(dt),
                offset=offset,
                nbytes=nbytes,
                global_shape=gshape,
                index=ranges,
            )
            offset += nbytes
        total = offset + len(scalar_blob)

        t_fetch = 0.0
        t_memcpy = 0.0
        with self._write_lock:
            if self._shm is None or self._shm.size < total:
                if self._shm is not None:
                    self._shm.close()
                    self._shm.unlink()
                    self._shm = None
                self._shm = get_or_create_shm(self._shm_name, total)
            config.writing = True
            self._publish_meta(metas, config, offset, len(scalar_blob))
            from dlrover_tpu.ops.fastcopy import copy_into

            buf = self._shm.buf
            # device leaves are fetched in BATCHED chunks:
            # ``jax.device_get`` on a group issues all transfers
            # concurrently (per-leaf waits would pay one transport
            # round trip per leaf — measured 2x slower through a
            # high-latency device link), while ~256 MB chunks bound
            # the extra host RAM and let the shm memcpy of chunk k
            # overlap nothing worse than chunk k+1's issue
            CHUNK = 256 * 2**20
            chunk: list = []
            chunk_bytes = 0

            def flush(chunk):
                nonlocal t_fetch, t_memcpy
                if not chunk:
                    return
                t0 = _time.perf_counter()
                import jax

                fetched = jax.device_get([a for _, a in chunk])
                t_fetch += _time.perf_counter() - t0
                for (key, _), host in zip(chunk, fetched):
                    m = metas[key]
                    host = np.ascontiguousarray(host)
                    dst = np.frombuffer(
                        buf, dtype=np.dtype(m.dtype),
                        count=host.size, offset=m.offset,
                    ).reshape(m.shape)
                    # GIL released during the memcpy: a multi-GB
                    # snapshot must not starve heartbeat/IPC threads
                    t0 = _time.perf_counter()
                    copy_into(dst, host)
                    t_memcpy += _time.perf_counter() - t0

            for i, (key, arr) in enumerate(entries):
                if isinstance(arr, np.ndarray):
                    m = metas[key]
                    dst = np.frombuffer(
                        buf, dtype=np.dtype(m.dtype),
                        count=arr.size, offset=m.offset,
                    ).reshape(m.shape)
                    t0 = _time.perf_counter()
                    copy_into(dst, arr)
                    t_memcpy += _time.perf_counter() - t0
                else:
                    chunk.append((key, arr))
                    chunk_bytes += metas[key].nbytes
                    if chunk_bytes >= CHUNK:
                        flush(chunk)
                        chunk, chunk_bytes = [], 0
                entries[i] = (key, None)  # free eagerly
            flush(chunk)
            buf[offset:offset + len(scalar_blob)] = scalar_blob
            config.writing = False
            self._publish_meta(metas, config, offset, len(scalar_blob))
        self.last_save_phases = {
            "fetch_s": round(t_fetch, 3),
            "memcpy_s": round(t_memcpy, 3),
            "bytes": total,
        }
        # chaos hook: a corrupt_shm rule flips bytes of (or tears) the
        # snapshot that was just published, so restore/persist paths
        # must prove they reject or survive a damaged segment
        from dlrover_tpu import chaos as _chaos

        _chaos.fire("ckpt.shm_save", step=config.step, handler=self)
        logger.debug(
            "rank %s wrote %.1f MB checkpoint step %s to shm "
            "(fetch %.2fs, memcpy %.2fs)",
            self._rank, total / 2**20, config.step, t_fetch, t_memcpy,
        )

    def _publish_meta(
        self, metas: Dict[str, TensorMeta], config: CheckpointConfig,
        scalar_offset: int, scalar_nbytes: int,
    ):
        self._meta.set(
            {
                "tensors": metas,
                "config": config,
                "scalar_offset": scalar_offset,
                "scalar_nbytes": scalar_nbytes,
            }
        )

    # -- read (agent side / restore) --------------------------------------

    def metadata(self) -> Dict[str, Any]:
        return self._meta.get(default_if_absent=True)

    def get_checkpoint_config(self) -> Optional[CheckpointConfig]:
        meta = self._meta.get(default_if_absent=True)
        return meta.get("config") if meta else None

    def no_checkpoint_state(self) -> bool:
        cfg = self.get_checkpoint_config()
        return cfg is None or cfg.step <= 0

    def _attach(
        self, min_size: int = 0
    ) -> Optional[PersistentSharedMemory]:
        """Attach (cached) to the segment; when the trainer grew and
        recreated it, a cached mapping points at the old unlinked
        inode — re-attach rather than silently slicing a truncated,
        stale snapshot (``min_size`` = bytes the caller needs)."""
        if self._shm is None:
            try:
                self._shm = PersistentSharedMemory(name=self._shm_name)
            except FileNotFoundError:
                return None
        if min_size and self._shm.size < min_size:
            try:
                self._shm.close()
            except BufferError:  # a reader still holds a view
                pass
            self._shm = None
            try:
                self._shm = PersistentSharedMemory(name=self._shm_name)
            except FileNotFoundError:
                return None
            if self._shm.size < min_size:
                logger.error(
                    "shm segment %s is %d bytes but the snapshot "
                    "metadata claims %d; refusing a truncated read",
                    self._shm_name, self._shm.size, min_size,
                )
                return None
        return self._shm

    def load_flat(
        self, detach: bool = True, stats=None,
    ) -> Tuple[Optional[CheckpointConfig], Dict[str, Any], Dict[str, Any]]:
        """Read the shm snapshot as (config, flat {key: array or
        scalar}, {key: TensorMeta}) — shard entries keep their
        ``@shardN`` keys for target-sharded reassembly.

        ``detach=True`` copies every leaf out of the segment through
        the staged restore pipeline (chunked, GIL-released, parallel —
        the serial per-leaf ``arr.copy()`` this replaces paid the
        mapping's page faults single-threaded).  ``detach=False``
        returns live views into shm: valid only until the next save
        overwrites the segment, so callers must finish (or detach /
        ``device_put``-copy) before returning control — the GSPMD
        restore path feeds them straight into batched ``device_put``.
        ``stats`` is a :class:`~.restore.RestoreStats` accumulator.
        """
        import time as _time

        from dlrover_tpu.checkpoint.restore import detach_flat

        t0 = _time.perf_counter()
        meta = self._meta.get(default_if_absent=True)
        if not meta:
            return None, {}, {}
        config: CheckpointConfig = meta["config"]
        if config.writing:
            logger.warning("shm snapshot is mid-write; refusing to load")
            return None, {}, {}
        shm = self._attach(
            min_size=meta["scalar_offset"] + meta["scalar_nbytes"]
        )
        if shm is None:
            return None, {}, {}
        views = _views_from(meta["tensors"], shm.buf)
        blob = bytes(
            shm.buf[
                meta["scalar_offset"]:
                meta["scalar_offset"] + meta["scalar_nbytes"]
            ]
        )
        if stats is not None:
            stats.read_s += _time.perf_counter() - t0
            if not detach:
                stats.bytes += sum(v.nbytes for v in views.values())
        flat = detach_flat(views, stats=stats) if detach else views
        flat.update(pickle.loads(blob))
        return config, flat, meta["tensors"]

    def load_state_dict(
        self, stats=None,
    ) -> Tuple[Optional[CheckpointConfig], Any]:
        """Read the shm snapshot back into a nested dict of private
        numpy arrays (caller device_puts with its shardings).  Shard
        entries of global arrays are assembled to full host arrays
        when this process's shards cover them (always single-host)."""
        import time as _time

        config, flat, metas = self.load_flat(stats=stats)
        if config is None:
            return None, {}
        t0 = _time.perf_counter()
        flat = _assemble_flat(flat, metas)
        if stats is not None:
            stats.assemble_s += _time.perf_counter() - t0
        return config, _unflatten_to_nested(flat)

    def read_raw(self) -> Tuple[Optional[CheckpointConfig], Any, Dict]:
        """Raw snapshot + meta for the agent's persist path (no pytree
        reconstruction).  Returns a PRIVATE ``bytes`` copy: the agent
        takes it under the shard lock (one memcpy) and releases the
        lock before any storage IO, so the trainer's next snapshot is
        never blocked behind a disk/remote write (the former zero-copy
        stream-under-lock mode traded exactly that stall for one saved
        memcpy — the wrong trade; see saver._save_shard)."""
        meta = self._meta.get(default_if_absent=True)
        if not meta:
            return None, b"", {}
        config: CheckpointConfig = meta["config"]
        total = meta["scalar_offset"] + meta["scalar_nbytes"]
        shm = self._attach(min_size=total)
        if shm is None or config.writing:
            return None, b"", {}
        return config, bytes(shm.buf[:total]), meta

    def prefault(
        self, workers: Optional[int] = None,
        chunk_bytes: int = 64 * 2**20,
    ) -> int:
        """Touch every page of the snapshot so a later read runs warm.

        Page-table population is PER PROCESS: the agent's prefetch
        warms the agent, not the trainer — so the respawned trainer
        runs this itself (engine construction kicks it on a daemon
        thread) while its model build / jit trace proceeds.  Strided
        read-only touches in parallel ~chunk_bytes pieces: numpy
        releases the GIL for the reductions, so the faults overlap
        across the (bounded) pool.  Returns bytes touched (0 when no
        snapshot exists)."""
        meta = self._meta.get(default_if_absent=True)
        if not meta:
            return 0
        total = meta["scalar_offset"] + meta["scalar_nbytes"]
        shm = self._attach(min_size=total)
        if shm is None or total <= 0:
            return 0
        workers = workers if workers is not None else prefault_workers()
        flat = np.frombuffer(shm.buf, dtype=np.uint8, count=total)

        def touch(lo: int, hi: int):
            flat[lo:hi:4096].sum()

        spans = [
            (lo, min(lo + chunk_bytes, total))
            for lo in range(0, total, max(1, chunk_bytes))
        ]
        if workers <= 1 or len(spans) <= 1:
            for lo, hi in spans:
                touch(lo, hi)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="shm-prefault"
            ) as pool:
                list(pool.map(lambda s: touch(*s), spans))
        return total

    def close(self):
        if self._shm is not None:
            self._shm.close()
            self._shm = None
        self._meta.close()

    def unlink(self):
        if self._attach() is not None:
            self._shm.unlink()
            self._shm = None


def prefault_workers() -> int:
    """Thread budget for page-in prefetch/prefault work.  PINNED low
    by default: the touches deliberately overlap the trainer's
    interpreter/jax import (or its model build), and an unbounded pool
    would starve exactly the work it is hiding latency from.
    ``DLROVER_PREFETCH_WORKERS`` overrides."""
    val = os.getenv("DLROVER_PREFETCH_WORKERS", "").strip()
    if val:
        try:
            return max(1, int(val))
        except ValueError:
            pass
    return min(4, max(1, (os.cpu_count() or 2) // 2))


def _views_from(metas: Dict[str, TensorMeta], buf) -> Dict[str, np.ndarray]:
    """{key: np.frombuffer view} over a shm segment or raw/mmap blob —
    free to build; paging/copy cost is paid by whichever pipeline
    stage consumes the view."""
    views: Dict[str, np.ndarray] = {}
    for key, m in metas.items():
        views[key] = np.frombuffer(
            buf, dtype=np.dtype(m.dtype),
            count=int(np.prod(m.shape, dtype=np.int64)) if m.shape else 1,
            offset=m.offset,
        ).reshape(m.shape)
    return views


def flat_from_raw(
    meta: Dict, raw, detach: bool = True, stats=None,
) -> Tuple[Dict, Dict]:
    """(flat {key: array/scalar}, {key: TensorMeta}) from raw shm
    bytes — or an mmap view from ``storage.read_view`` — shard keys
    preserved.  ``detach=False`` returns views into ``raw`` (the
    caller keeps ``raw`` alive until it is done)."""
    from dlrover_tpu.checkpoint.restore import detach_flat

    views = _views_from(meta["tensors"], raw)
    if stats is not None and not detach:
        stats.bytes += sum(v.nbytes for v in views.values())
    flat = detach_flat(views, stats=stats) if detach else views
    blob = raw[
        meta["scalar_offset"]:meta["scalar_offset"] + meta["scalar_nbytes"]
    ]
    flat.update(pickle.loads(blob))
    return flat, meta["tensors"]


def _assemble_flat(flat: Dict[str, Any], metas: Dict[str, Any]):
    """Assemble ``@shardN`` entries into full host arrays (raises if
    the visible shards do not cover a leaf — topology changed across
    hosts; use the target-sharded restore or the orbax tier)."""
    from dlrover_tpu.checkpoint.sharded import (
        SHARD_SEP,
        assemble_shard,
        group_shard_entries,
    )

    grouped, plain = group_shard_entries(flat, metas)
    for base, entries in grouped.items():
        some_key = f"{base}{SHARD_SEP}0"
        m = metas.get(some_key)
        gshape = tuple(m.global_shape)
        full = assemble_shard(
            tuple((0, d) for d in gshape),
            np.dtype(m.dtype),
            entries,
        )
        if full is None:
            raise ValueError(
                f"shards of '{base}' do not cover its global shape "
                f"{gshape}: restore with a target state "
                f"(load_sharded) or from the orbax tier"
            )
        plain[base] = full
    return plain


def state_dict_from_raw(meta: Dict, raw, stats=None):
    """Rebuild the nested dict from raw shm bytes (storage load path);
    detach copies run through the staged restore pipeline."""
    import time as _time

    flat, metas = flat_from_raw(meta, raw, stats=stats)
    t0 = _time.perf_counter()
    flat = _assemble_flat(flat, metas)
    if stats is not None:
        stats.assemble_s += _time.perf_counter() - t0
    return _unflatten_to_nested(flat)
