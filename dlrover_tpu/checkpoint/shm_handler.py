"""Pytree <-> shared-memory serialization.

Reference: ``SharedMemoryHandler`` / ``TensorMeta``
(``dlrover/python/elastic_agent/torch/ckpt_saver.py:65,209``): a state
dict is traversed into one flat shared-memory buffer plus a meta dict
(shape/dtype/offset per leaf) published through a ``SharedDict``; the
agent process re-materializes tensors zero-copy with ``frombuffer``.

The JAX version traverses a pytree with ``jax.tree_util`` key paths.
Array leaves (jax/numpy) are device_get into the shm buffer — for a
sharded ``jax.Array`` only this host's addressable shards would be
copied by the sharded engine; this handler takes whatever ``np.asarray``
of the leaf yields.  Non-array leaves (step counters, strings, opt
hyperparams) are pickled into a trailing blob.
"""

import os
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

try:  # registers bfloat16/fp8 dtypes with numpy for np.dtype(str)
    import ml_dtypes  # noqa: F401
except ImportError:  # pragma: no cover
    pass

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.multi_process import (
    PersistentSharedMemory,
    SharedDict,
    get_or_create_shm,
)


@dataclass
class TensorMeta:
    """Placement of one array leaf inside the flat buffer
    (reference: ckpt_saver.py:65).  For a shard of a global sharded
    ``jax.Array`` (key suffixed ``@shardN``), ``global_shape`` and
    ``index`` carry the reassembly metadata (reference shard-aware
    analog: fsdp_engine.py:568)."""

    shape: Tuple[int, ...] = ()
    dtype: str = "float32"
    offset: int = 0
    nbytes: int = 0
    global_shape: Optional[Tuple[int, ...]] = None
    index: Optional[Tuple[Tuple[int, int], ...]] = None


@dataclass
class CheckpointConfig:
    """Per-snapshot metadata carried with the shm segment
    (reference: ckpt_saver.py:74)."""

    step: int = 0
    path: str = ""
    rank: int = 0
    world_size: int = 1
    # shards expected globally for the commit protocol
    global_shard_num: int = 1
    writing: bool = False
    extra: Dict[str, Any] = field(default_factory=dict)


def _flatten_state_dict(state_dict) -> Dict[str, Any]:
    """Pytree -> {"a/b/0": leaf} using jax key paths."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(state_dict)
    out = {}
    for path, leaf in flat:
        key = "/".join(_path_str(p) for p in path)
        out[key] = leaf
    return out


def _path_str(entry) -> str:
    import jax

    if isinstance(entry, jax.tree_util.DictKey):
        return str(entry.key)
    if isinstance(entry, jax.tree_util.SequenceKey):
        return str(entry.idx)
    if isinstance(entry, jax.tree_util.GetAttrKey):
        return str(entry.name)
    if isinstance(entry, jax.tree_util.FlattenedIndexKey):
        return str(entry.key)
    return str(entry)


def _unflatten_to_nested(flat: Dict[str, Any]) -> Dict[str, Any]:
    """{"a/b": v} -> {"a": {"b": v}}; integer-keyed dicts stay dicts
    (exact container types are the engine caller's concern — the state
    dict contract is string/index-keyed nesting, like the reference's
    torch state dicts)."""
    root: Dict[str, Any] = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def _extract_entries(state_dict):
    """Split a pytree into shm-layout entries: ``(entries, scalars,
    shard_info)`` where entries is ``[(key, leaf)]`` in layout order
    (numpy leaves materialized contiguous, device leaves left for the
    batched fetch), scalars the non-array leaves, and shard_info the
    reassembly metadata of ``@shardN`` entries."""
    from dlrover_tpu.checkpoint.sharded import (
        SHARD_SEP,
        is_sharded_leaf,
        local_shards,
    )

    flat = _flatten_state_dict(state_dict)
    entries = []  # (key, leaf) in shm layout order
    scalars: Dict[str, Any] = {}
    shard_info: Dict[str, Tuple[Tuple[int, ...], Tuple]] = {}
    for key, leaf in flat.items():
        if isinstance(leaf, (np.ndarray, np.generic)):
            entries.append((key, np.ascontiguousarray(leaf)))
        elif is_sharded_leaf(leaf):
            # global sharded array: only this process's addressable
            # shards go to shm, with reassembly metadata
            gshape = tuple(leaf.shape)
            for i, (ranges, data) in enumerate(local_shards(leaf)):
                skey = f"{key}{SHARD_SEP}{i}"
                entries.append((skey, data))
                shard_info[skey] = (gshape, ranges)
        elif type(leaf).__module__.startswith(("jaxlib", "jax")):
            entries.append((key, leaf))
        else:
            scalars[key] = leaf
    return entries, scalars, shard_info


def default_job_suffix() -> str:
    """Namespace shm segments per job so two jobs (or a test run next
    to a live job) on one host never collide: DLROVER_JOB_NAME if set,
    else a hash of the job's IPC socket dir (which agent and trainers
    already share)."""
    import hashlib

    from dlrover_tpu.common.multi_process import socket_dir

    name = os.getenv("DLROVER_JOB_NAME")
    if name:
        return name
    return hashlib.md5(socket_dir().encode()).hexdigest()[:8]


# -- paged base+delta shm layout (hot-save tier) ------------------------
#
# Segment anatomy (DLROVER_SHM_PAGED):
#
#   [ 0: 8]  magic  b"DLRVPG01"
#   [ 8: 9]  active directory slot (0/1) — the ATOMIC publish: a
#            single byte flips after everything the new generation
#            references is in place, so a reader or a SIGKILL
#            mid-write always lands on the previous consistent
#            snapshot
#   [12:16]  dir_cap (u32) — capacity of each directory slot
#   [16            : 16+dir_cap  ]  directory slot 0
#   [16+dir_cap    : 16+2*dir_cap]  directory slot 1
#   [data_off ...]  per-leaf ping-pong extents (A/B copy-on-write: a
#            delta save writes changed leaves to the INACTIVE side
#            and flips per-leaf `active` in the new directory), then
#            two kv arenas (base + delta blob pages bump-allocated;
#            a re-base targets the arena the live directory does NOT
#            reference)
#
# Each directory slot is [len u32 | crc32 u32 | pickled directory];
# the directory carries generation, config, per-leaf {offset, len,
# crc, gen} placement, the pickled scalar blob, and the kv page
# chain — so the segment stands alone even if the meta SharedDict
# host died with the trainer.

PAGED_MAGIC = b"DLRVPG01"
_PAGED_HDR = 16
_PAGED_ALIGN = 64


class PagedNeedBase(Exception):
    """The paged segment cannot accept a delta save (no valid epoch,
    leaf layout changed, kv arena or directory slot overflow) — the
    caller must re-export a full kv base and retry."""


def paged_enabled() -> bool:
    """``DLROVER_SHM_PAGED`` opt-in for the paged hot-save tier
    (default off: memory saves write the flat full segment)."""
    return os.environ.get(
        "DLROVER_SHM_PAGED", ""
    ).strip().lower() in ("1", "true", "yes", "on")


def shm_full_every() -> int:
    """Full-base cadence of the paged kv chain: every Nth paged save
    re-bases even without a poison, bounding both the delta replay a
    restore pays and the page directory's growth.  0 = no cadence
    (re-base only on poison/overflow).  ``DLROVER_SHM_FULL_EVERY``."""
    try:
        return max(
            0, int(os.environ.get("DLROVER_SHM_FULL_EVERY", "32"))
        )
    except ValueError:
        return 32


def save_chunk_bytes() -> int:
    """Chunk size of the save-side parallel memcpy
    (``DLROVER_SAVE_CHUNK_BYTES``; default 64 MB — the restore
    pipeline's twin)."""
    env = os.environ.get("DLROVER_SAVE_CHUNK_BYTES", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return 64 * 2**20


def _align_up(n: int, a: int = _PAGED_ALIGN) -> int:
    return (n + a - 1) // a * a


def _crc(buf) -> int:
    import zlib

    return zlib.crc32(buf) & 0xFFFFFFFF


def _as_bytes_1d(arr: np.ndarray) -> np.ndarray:
    """A contiguous array reinterpreted as flat uint8 — the compare
    unit for bit-unchanged copy-skip (float equality would miscall
    NaN-bearing leaves as changed every save)."""
    return arr.reshape(-1).view(np.uint8)


class SharedMemoryHandler:
    """Owns one shm segment + meta SharedDict for one local rank."""

    SHM_PREFIX = "dlrover_tpu_ckpt_shm"
    META_PREFIX = "ckpt_meta"

    def __init__(self, local_rank: int, host: bool = False,
                 job_name: str = ""):
        self._rank = local_rank
        job_name = job_name or default_job_suffix()
        suffix = f"{job_name}_{local_rank}" if job_name else str(local_rank)
        self._shm_name = f"{self.SHM_PREFIX}_{suffix}"
        self._meta = SharedDict(
            f"{self.META_PREFIX}_{suffix}", create=host
        )
        self._shm: Optional[PersistentSharedMemory] = None
        self._write_lock = threading.Lock()
        # writer-side copy of the last published page directory (paged
        # mode); None = unknown — the next paged save tries to adopt
        # the in-segment directory before starting a fresh epoch
        self._paged_dir: Optional[Dict[str, Any]] = None
        # phase timings of the last save (seconds): the engine logs
        # them and the bench reports them — the dominant term of a
        # flash save must be measurable, not buried (VERDICT r2)
        self.last_save_phases: Dict[str, float] = {}

    # -- write (trainer side) ---------------------------------------------

    def save_state_dict(self, state_dict, config: CheckpointConfig):
        """Serialize the pytree into shm and publish the meta dict.

        Layout (metas) is computed from array avals BEFORE any
        transfer, then device leaves are fetched in ~256 MB batched
        chunks (``jax.device_get`` issues a chunk's transfers
        concurrently — per-leaf waits pay a transport round trip per
        leaf, measured 3x slower over a high-latency device link)
        and memcpy'd chunk-by-chunk into shm, bounding extra host RAM
        to one chunk instead of a full second state copy.  The engine
        issues ``copy_to_host_async`` on the snapshot up front as a
        best-effort head start.  Note jax caches the host copy on
        each ``jax.Array`` (``_npy_value``): the async engine path
        drops its device snapshot right after this call, bounding
        that overhead to the save window.
        Reference hot path: _traverse_copy_to_shm, ckpt_saver.py:174.

        Phase timings land in ``last_save_phases`` (fetch_s = waiting
        on device->host transfers — the dominant term when the device
        is reached through a slow link; memcpy_s = shm writes).
        """
        import time as _time

        entries, scalars, shard_info = _extract_entries(state_dict)
        scalar_blob = pickle.dumps(scalars)
        # a flat write clobbers any paged epoch in this segment; the
        # next paged save must start a fresh one
        self._paged_dir = None

        # layout from shapes/dtypes only — no transfer needed yet
        metas: Dict[str, TensorMeta] = {}
        offset = 0
        for key, arr in entries:
            gshape, ranges = shard_info.get(key, (None, None))
            dt = np.dtype(arr.dtype)
            count = int(np.prod(arr.shape, dtype=np.int64)) if (
                arr.shape
            ) else 1
            nbytes = count * dt.itemsize
            metas[key] = TensorMeta(
                shape=tuple(arr.shape),
                dtype=str(dt),
                offset=offset,
                nbytes=nbytes,
                global_shape=gshape,
                index=ranges,
            )
            offset += nbytes
        total = offset + len(scalar_blob)

        t_fetch = 0.0
        t_memcpy = 0.0
        with self._write_lock:
            if self._shm is None or self._shm.size < total:
                if self._shm is not None:
                    self._shm.close()
                    self._shm.unlink()
                    self._shm = None
                self._shm = get_or_create_shm(self._shm_name, total)
            config.writing = True
            self._publish_meta(metas, config, offset, len(scalar_blob))
            from dlrover_tpu.ops.fastcopy import copy_into

            buf = self._shm.buf
            # device leaves are fetched in BATCHED chunks:
            # ``jax.device_get`` on a group issues all transfers
            # concurrently (per-leaf waits would pay one transport
            # round trip per leaf — measured 2x slower through a
            # high-latency device link), while ~256 MB chunks bound
            # the extra host RAM and let the shm memcpy of chunk k
            # overlap nothing worse than chunk k+1's issue
            CHUNK = 256 * 2**20
            chunk: list = []
            chunk_bytes = 0

            def flush(chunk):
                nonlocal t_fetch, t_memcpy
                if not chunk:
                    return
                t0 = _time.perf_counter()
                import jax

                fetched = jax.device_get([a for _, a in chunk])
                t_fetch += _time.perf_counter() - t0
                for (key, _), host in zip(chunk, fetched):
                    m = metas[key]
                    host = np.ascontiguousarray(host)
                    dst = np.frombuffer(
                        buf, dtype=np.dtype(m.dtype),
                        count=host.size, offset=m.offset,
                    ).reshape(m.shape)
                    # GIL released during the memcpy: a multi-GB
                    # snapshot must not starve heartbeat/IPC threads
                    t0 = _time.perf_counter()
                    copy_into(dst, host)
                    t_memcpy += _time.perf_counter() - t0

            for i, (key, arr) in enumerate(entries):
                if isinstance(arr, np.ndarray):
                    m = metas[key]
                    dst = np.frombuffer(
                        buf, dtype=np.dtype(m.dtype),
                        count=arr.size, offset=m.offset,
                    ).reshape(m.shape)
                    t0 = _time.perf_counter()
                    copy_into(dst, arr)
                    t_memcpy += _time.perf_counter() - t0
                else:
                    chunk.append((key, arr))
                    chunk_bytes += metas[key].nbytes
                    if chunk_bytes >= CHUNK:
                        flush(chunk)
                        chunk, chunk_bytes = [], 0
                entries[i] = (key, None)  # free eagerly
            flush(chunk)
            buf[offset:offset + len(scalar_blob)] = scalar_blob
            config.writing = False
            self._publish_meta(metas, config, offset, len(scalar_blob))
        self.last_save_phases = {
            "fetch_s": round(t_fetch, 3),
            "memcpy_s": round(t_memcpy, 3),
            "bytes": total,
        }
        # chaos hook: a corrupt_shm rule flips bytes of (or tears) the
        # snapshot that was just published, so restore/persist paths
        # must prove they reject or survive a damaged segment
        from dlrover_tpu import chaos as _chaos

        _chaos.fire("ckpt.shm_save", step=config.step, handler=self)
        logger.debug(
            "rank %s wrote %.1f MB checkpoint step %s to shm "
            "(fetch %.2fs, memcpy %.2fs)",
            self._rank, total / 2**20, config.step, t_fetch, t_memcpy,
        )

    def _publish_meta(
        self, metas: Dict[str, TensorMeta], config: CheckpointConfig,
        scalar_offset: int, scalar_nbytes: int,
    ):
        self._meta.set(
            {
                "tensors": metas,
                "config": config,
                "scalar_offset": scalar_offset,
                "scalar_nbytes": scalar_nbytes,
            }
        )

    # -- paged write (trainer side) ----------------------------------------

    def save_state_dict_paged(
        self, state_dict, config: CheckpointConfig,
        kv_payload: Optional[Tuple[str, Dict[str, Any]]] = None,
        workers: Optional[int] = None,
        chunk_bytes: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Paged hot save: write only what changed, publish with an
        atomic directory swap.

        Dense leaves are compared bit-for-bit against their active
        extent and copy-skipped when unchanged; changed leaves go to
        the leaf's INACTIVE extent (per-leaf ping-pong copy-on-write)
        through a GIL-released chunked parallel copy
        (``DLROVER_SAVE_WORKERS``).  ``kv_payload`` is the sparse
        adapter's ``("base"|"delta", state)`` — the blob lands in a
        bump-allocated kv page (a base targets the arena the live
        directory does NOT reference).  Raises :class:`PagedNeedBase`
        when a delta cannot land (no valid epoch, layout changed,
        arena/directory overflow): the caller re-exports a full base
        and retries.  Returns the phase/byte accounting dict (also
        stored in ``last_save_phases``)."""
        import struct
        import time as _time

        from dlrover_tpu.ops import fastcopy

        entries, scalars, shard_info = _extract_entries(state_dict)
        scalars_blob = pickle.dumps(scalars)
        kv_kind = kv_payload[0] if kv_payload else None
        kv_blob = (
            pickle.dumps(kv_payload[1]) if kv_payload else b""
        )
        config.writing = False  # paged publishes are atomic, never torn

        metas: Dict[str, Dict[str, Any]] = {}
        order = []
        for key, arr in entries:
            gshape, ranges = shard_info.get(key, (None, None))
            dt = np.dtype(arr.dtype)
            count = int(np.prod(arr.shape, dtype=np.int64)) if (
                arr.shape
            ) else 1
            metas[key] = {
                "shape": tuple(arr.shape), "dtype": str(dt),
                "nbytes": count * dt.itemsize,
                "global_shape": gshape, "index": ranges,
            }
            order.append(key)

        if workers is None:
            workers = fastcopy.save_workers()
        if chunk_bytes is None:
            chunk_bytes = save_chunk_bytes()

        with self._write_lock:
            d = self._paged_dir
            if d is None:
                # a respawned writer adopts the in-segment epoch so
                # its first save stays O(touched) and never clobbers
                # the snapshot a concurrent restore may still need
                d = self._read_paged_directory(verify_pages=False)
            epoch_ok = self._paged_epoch_matches(d, order, metas)
            if epoch_ok and kv_kind == "base":
                other = 1 - int(d["kv_active"])
                cap = int(d["kv_arena"][other][1])
                epoch_ok = _align_up(len(kv_blob)) <= cap
            if epoch_ok and kv_kind is None and d.get("kv_pages"):
                # the sparse plane disappeared — pages would go stale
                epoch_ok = False
            if not epoch_ok:
                if kv_kind == "delta":
                    raise PagedNeedBase(
                        "no valid paged epoch for a delta save"
                    )
                prev_gen = int(d.get("generation", 0)) if (
                    isinstance(d, dict)
                ) else 0
                d = self._paged_new_epoch(
                    order, metas, len(kv_blob), len(scalars_blob),
                    prev_gen=prev_gen,
                )
                fresh = True
            else:
                fresh = False
            buf = self._shm.buf
            gen = int(d["generation"]) + (0 if fresh else 1)
            new_leaves = {k: dict(v) for k, v in d["leaves"].items()}

            t_fetch = t_compare = t_memcpy = t_kv = 0.0
            copied = skipped = pages = 0
            futures: list = []
            pool = None
            if workers > 1:
                from concurrent.futures import ThreadPoolExecutor

                pool = ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="shm-save",
                )
            submit = pool.submit if pool is not None else None

            def handle(key, host):
                nonlocal t_compare, t_memcpy, copied, skipped, pages
                host = np.ascontiguousarray(host)
                slot = new_leaves[key]
                nbytes = slot["nbytes"]
                host_b = _as_bytes_1d(host) if nbytes else host
                if not fresh and nbytes:
                    cur_off = (
                        slot["off_a"] if slot["active"] == 0
                        else slot["off_b"]
                    )
                    t0 = _time.perf_counter()
                    cur = np.frombuffer(
                        buf, dtype=np.uint8, count=nbytes,
                        offset=cur_off,
                    )
                    same = np.array_equal(cur, host_b)
                    t_compare += _time.perf_counter() - t0
                    if same:
                        skipped += nbytes
                        return
                    side = 1 - int(slot["active"])
                else:
                    side = 0
                dst_off = slot["off_a"] if side == 0 else slot["off_b"]
                dst = np.frombuffer(
                    buf, dtype=np.uint8,
                    count=max(1, nbytes), offset=dst_off,
                )[:nbytes]
                t0 = _time.perf_counter()
                futures.extend(
                    fastcopy.copy_into_chunked(
                        dst, host_b, submit=submit,
                        chunk_bytes=chunk_bytes,
                    )
                    or []
                )
                t_memcpy += _time.perf_counter() - t0
                slot["active"] = side
                slot["gen"] = gen
                slot["crc"] = _crc(host_b)
                copied += nbytes
                pages += 1

            try:
                CHUNK = 256 * 2**20
                chunk: list = []
                pending = 0

                def flush(chunk):
                    nonlocal t_fetch
                    if not chunk:
                        return
                    t0 = _time.perf_counter()
                    import jax

                    fetched = jax.device_get([a for _, a in chunk])
                    t_fetch += _time.perf_counter() - t0
                    for (key, _), host in zip(chunk, fetched):
                        handle(key, host)

                for i, (key, arr) in enumerate(entries):
                    if isinstance(arr, np.ndarray):
                        handle(key, arr)
                    else:
                        chunk.append((key, arr))
                        pending += metas[key]["nbytes"]
                        if pending >= CHUNK:
                            flush(chunk)
                            chunk, pending = [], 0
                    entries[i] = (key, None)  # free eagerly
                flush(chunk)
                t0 = _time.perf_counter()
                for f in futures:
                    f.result()
                t_memcpy += _time.perf_counter() - t0
            finally:
                if pool is not None:
                    pool.shutdown(wait=True)

            # kv blob page (base -> the other arena; delta -> bump)
            kv_pages = list(d.get("kv_pages") or ())
            kv_active = int(d.get("kv_active", 0))
            kv_tail = int(d.get("kv_tail", 0))
            if kv_kind is not None:
                t0 = _time.perf_counter()
                if kv_kind == "base":
                    kv_active = 0 if fresh else 1 - kv_active
                    arena_off, arena_cap = d["kv_arena"][kv_active]
                    page_off = int(arena_off)
                    kv_pages = []
                else:
                    arena_off, arena_cap = d["kv_arena"][kv_active]
                    page_off = kv_tail
                    if (
                        page_off + len(kv_blob)
                        > int(arena_off) + int(arena_cap)
                    ):
                        raise PagedNeedBase(
                            "kv delta arena overflow "
                            f"({page_off - int(arena_off)}"
                            f"+{len(kv_blob)} > {arena_cap})"
                        )
                buf[page_off:page_off + len(kv_blob)] = kv_blob
                kv_pages.append({
                    "kind": kv_kind, "step": int(config.step),
                    "off": page_off, "len": len(kv_blob),
                    "crc": _crc(kv_blob), "gen": gen,
                })
                kv_tail = _align_up(page_off + len(kv_blob))
                copied += len(kv_blob)
                pages += 1
                t_kv = _time.perf_counter() - t0

            new_dir = {
                "generation": gen,
                "config": config,
                "order": order,
                "leaves": new_leaves,
                "scalars_blob": scalars_blob,
                "kv_pages": kv_pages,
                "kv_arena": d["kv_arena"],
                "kv_active": kv_active,
                "kv_tail": kv_tail,
                "data_end": d["data_end"],
                "dir_cap": d["dir_cap"],
            }
            payload = pickle.dumps(new_dir)
            if len(payload) + 8 > int(d["dir_cap"]):
                if kv_kind == "delta":
                    raise PagedNeedBase("page directory slot overflow")
                raise RuntimeError(
                    "paged directory exceeds its slot even on a "
                    f"fresh epoch ({len(payload)} > {d['dir_cap']})"
                )
            # chaos hook: a kill here lands BETWEEN the data/page
            # writes and the directory publish — the crash-consistency
            # tests prove readers still see the previous generation
            from dlrover_tpu import chaos as _chaos

            _chaos.fire(
                "ckpt.paged_write", step=config.step, handler=self,
                generation=gen, kind="base" if fresh else "delta",
            )
            t0 = _time.perf_counter()
            dir_cap = int(d["dir_cap"])
            prev_slot = None if fresh else self._paged_active_slot()
            new_header = fresh or prev_slot is None
            slot_idx = 0 if new_header else 1 - prev_slot
            slot_off = _PAGED_HDR + slot_idx * dir_cap
            buf[slot_off + 8:slot_off + 8 + len(payload)] = payload
            struct.pack_into(
                "<II", buf, slot_off, len(payload), _crc(payload)
            )
            if new_header:
                # invalidate the other slot BEFORE the magic goes in:
                # a reader must never parse pre-epoch garbage
                other_off = _PAGED_HDR + (1 - slot_idx) * dir_cap
                struct.pack_into("<II", buf, other_off, 0, 0)
                struct.pack_into("<I", buf, 12, dir_cap)
                buf[0:8] = PAGED_MAGIC
            buf[8] = slot_idx  # THE atomic publish
            self._paged_dir = new_dir
            self._meta.set({
                "paged": True,
                "tensors": {},
                "config": config,
                "generation": gen,
                "scalar_offset": int(d["data_end"]),
                "scalar_nbytes": 0,
            })
            t_publish = _time.perf_counter() - t0

        total = sum(m["nbytes"] for m in metas.values()) + len(kv_blob)
        self.last_save_phases = {
            "fetch_s": round(t_fetch, 4),
            "compare_s": round(t_compare, 4),
            "memcpy_s": round(t_memcpy, 4),
            "kv_s": round(t_kv, 4),
            "publish_s": round(t_publish, 4),
            "paged": True,
            "kind": "base" if fresh else "delta",
            "generation": gen,
            "pages_written": pages,
            "bytes": int(copied),
            "bytes_skipped": int(skipped),
            "bytes_total": int(total),
            "kv_bytes": len(kv_blob),
        }
        _chaos.fire("ckpt.shm_save", step=config.step, handler=self)
        logger.debug(
            "rank %s paged save step %s gen %s: %s, wrote %d pages "
            "%.1f MB (skipped %.1f MB of %.1f MB)",
            self._rank, config.step, gen,
            "base" if fresh else "delta", pages, copied / 2**20,
            skipped / 2**20, total / 2**20,
        )
        return dict(self.last_save_phases)

    def _paged_epoch_matches(
        self, d: Optional[Dict[str, Any]], order, metas,
    ) -> bool:
        """A directory can absorb a delta save only if the dense leaf
        layout is unchanged — same keys in the same order with the
        same shapes/dtypes (their extents are preallocated)."""
        if not isinstance(d, dict) or d.get("order") != order:
            return False
        leaves = d.get("leaves") or {}
        for key in order:
            e = leaves.get(key)
            m = metas[key]
            if (
                e is None
                or tuple(e["shape"]) != tuple(m["shape"])
                or e["dtype"] != m["dtype"]
                or int(e["nbytes"]) != int(m["nbytes"])
            ):
                return False
        return self._attach(min_size=int(d.get("data_end", 0))) is not None

    def _paged_new_epoch(
        self, order, metas, kv_len: int, scalars_len: int,
        prev_gen: int = 0,
    ) -> Dict[str, Any]:
        """Lay out a fresh epoch: directory slots, per-leaf ping-pong
        extents, two kv arenas — and size/(re)create the segment.
        Returns the epoch skeleton (generation = next to publish)."""
        leaves: Dict[str, Dict[str, Any]] = {}
        # directory capacity: a prototype pickle of the fully
        # populated directory, doubled, plus headroom for the kv page
        # chain the epoch will accumulate
        proto = {
            k: {**m, "off_a": 0, "off_b": 0, "active": 0,
                "gen": 0, "crc": 0}
            for k, m in metas.items()
        }
        proto_len = len(pickle.dumps({
            "generation": 0, "config": CheckpointConfig(),
            "order": order, "leaves": proto,
            "scalars_blob": b"\0" * scalars_len,
            "kv_pages": [], "kv_arena": ((0, 0), (0, 0)),
            "kv_active": 0, "kv_tail": 0, "data_end": 0,
            "dir_cap": 0,
        }))
        dir_cap = _align_up(2 * proto_len + 65536)
        off = _align_up(_PAGED_HDR + 2 * dir_cap)
        for key in order:
            m = metas[key]
            ext = _align_up(int(m["nbytes"]))
            leaves[key] = {
                **m, "off_a": off, "off_b": off + ext,
                "active": 0, "gen": 0, "crc": 0,
            }
            off += 2 * ext
        kv_cap = 0
        arenas = ((0, 0), (0, 0))
        if kv_len:
            kv_cap = _align_up(kv_len + max(kv_len // 2, 1 << 20))
            arenas = ((off, kv_cap), (off + kv_cap, kv_cap))
            off += 2 * kv_cap
        total = off
        if self._shm is None or self._shm.size < total:
            if self._shm is not None:
                logger.warning(
                    "paged epoch needs %d bytes > segment %d: "
                    "recreating (previous snapshot discarded)",
                    total, self._shm.size,
                )
                self._shm.close()
                self._shm.unlink()
                self._shm = None
            self._shm = get_or_create_shm(self._shm_name, total)
        return {
            "generation": prev_gen + 1,
            "config": None,
            "order": order,
            "leaves": leaves,
            "scalars_blob": b"",
            "kv_pages": [],
            "kv_arena": arenas,
            "kv_active": 0,
            "kv_tail": int(arenas[0][0]),
            "data_end": total,
            "dir_cap": dir_cap,
        }

    def _paged_active_slot(self) -> Optional[int]:
        shm = self._attach(min_size=_PAGED_HDR)
        if shm is None or bytes(shm.buf[0:8]) != PAGED_MAGIC:
            return None
        slot = shm.buf[8]
        return int(slot) if slot in (0, 1) else None

    # -- paged read --------------------------------------------------------

    def _read_paged_directory(
        self, verify_pages: bool = True,
    ) -> Optional[Dict[str, Any]]:
        """Parse the in-segment page directory.  Tries the active
        slot first; a torn slot (bad length/CRC/pickle, or pages that
        fail their CRC) falls back to the other slot — the previous
        generation.  Returns None when neither slot verifies."""
        import struct

        shm = self._attach(min_size=_PAGED_HDR)
        if shm is None or shm.size < _PAGED_HDR:
            return None
        if bytes(shm.buf[0:8]) != PAGED_MAGIC:
            return None
        active = int(shm.buf[8])
        (dir_cap,) = struct.unpack_from("<I", shm.buf, 12)
        if active not in (0, 1) or dir_cap <= 8:
            return None
        if shm.size < _PAGED_HDR + 2 * dir_cap:
            shm = self._attach(min_size=_PAGED_HDR + 2 * dir_cap)
            if shm is None or bytes(shm.buf[0:8]) != PAGED_MAGIC:
                return None
        for slot in (active, 1 - active):
            off = _PAGED_HDR + slot * dir_cap
            ln, crc = struct.unpack_from("<II", shm.buf, off)
            if not 0 < ln <= dir_cap - 8:
                continue
            payload = bytes(shm.buf[off + 8:off + 8 + ln])
            if _crc(payload) != crc:
                logger.warning(
                    "paged directory slot %d torn (crc mismatch)%s",
                    slot,
                    "; falling back to the previous generation"
                    if slot == active else "",
                )
                continue
            try:
                d = pickle.loads(payload)
            except Exception:
                continue
            if not isinstance(d, dict) or "generation" not in d:
                continue
            data_end = int(d.get("data_end", 0))
            if data_end > shm.size:
                shm = self._attach(min_size=data_end)
                if shm is None:
                    continue
            if verify_pages and not self._paged_verify(d, shm.buf):
                logger.warning(
                    "paged generation %s fails page CRC; %s",
                    d.get("generation"),
                    "falling back to the previous generation"
                    if slot == active else "refusing the snapshot",
                )
                continue
            if slot != active:
                logger.warning(
                    "paged restore fell back to previous generation "
                    "%s", d.get("generation"),
                )
            return d
        return None

    def _paged_verify(self, d: Dict[str, Any], buf) -> bool:
        """Every extent/page the directory references must match its
        recorded CRC — a half-written or clobbered generation (e.g. a
        re-epoch that overwrote pages before dying) must not restore."""
        try:
            for key in d["order"]:
                e = d["leaves"][key]
                nbytes = int(e["nbytes"])
                if not nbytes:
                    continue
                off = e["off_a"] if int(e["active"]) == 0 else e["off_b"]
                got = _crc(np.frombuffer(
                    buf, dtype=np.uint8, count=nbytes, offset=int(off)
                ))
                if got != int(e["crc"]):
                    return False
            for p in d.get("kv_pages") or ():
                blob = np.frombuffer(
                    buf, dtype=np.uint8, count=int(p["len"]),
                    offset=int(p["off"]),
                )
                if _crc(blob) != int(p["crc"]):
                    return False
        except (KeyError, TypeError, ValueError, IndexError):
            return False
        return True

    def _paged_views(
        self, d: Dict[str, Any], buf,
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, TensorMeta]]:
        """Views over each leaf's ACTIVE extent, plus flat-compatible
        TensorMetas (offset = extent offset) so every downstream
        consumer of (views, metas) works unchanged."""
        views: Dict[str, np.ndarray] = {}
        metas: Dict[str, TensorMeta] = {}
        for key in d["order"]:
            e = d["leaves"][key]
            off = int(
                e["off_a"] if int(e["active"]) == 0 else e["off_b"]
            )
            m = TensorMeta(
                shape=tuple(e["shape"]), dtype=e["dtype"],
                offset=off, nbytes=int(e["nbytes"]),
                global_shape=e.get("global_shape"),
                index=e.get("index"),
            )
            metas[key] = m
            views[key] = np.frombuffer(
                buf, dtype=np.dtype(m.dtype),
                count=int(np.prod(m.shape, dtype=np.int64))
                if m.shape else 1,
                offset=off,
            ).reshape(m.shape)
        return views, metas

    def _paged_kv_state(
        self, d: Dict[str, Any], buf,
    ) -> Optional[Dict[str, Any]]:
        """Replay the kv page chain (base + deltas) back to one full
        kv export — bit-identical to what a flat full save would have
        carried."""
        pages = d.get("kv_pages") or []
        if not pages:
            return None
        from dlrover_tpu.checkpoint.sparse import merge_kv_states

        blobs = [
            pickle.loads(bytes(
                buf[int(p["off"]):int(p["off"]) + int(p["len"])]
            ))
            for p in pages
        ]
        return merge_kv_states(blobs[0], blobs[1:])

    def _load_flat_paged(
        self, detach: bool = True, stats=None,
    ) -> Tuple[
        Optional[CheckpointConfig], Dict[str, Any], Dict[str, Any]
    ]:
        import time as _time

        from dlrover_tpu.checkpoint.restore import detach_flat
        from dlrover_tpu.checkpoint.sparse import KV_STATE_KEY

        t0 = _time.perf_counter()
        d = self._read_paged_directory(verify_pages=True)
        if d is None:
            logger.warning(
                "paged shm snapshot unreadable (torn or absent); "
                "refusing to load"
            )
            return None, {}, {}
        buf = self._shm.buf
        views, metas = self._paged_views(d, buf)
        kv = self._paged_kv_state(d, buf)
        if stats is not None:
            stats.read_s += _time.perf_counter() - t0
            if not detach:
                stats.bytes += sum(v.nbytes for v in views.values())
        flat = detach_flat(views, stats=stats) if detach else views
        flat.update(pickle.loads(d["scalars_blob"]))
        if kv is not None:
            flat.update(_flatten_state_dict({KV_STATE_KEY: kv}))
        return d["config"], flat, metas

    def _read_raw_paged(
        self,
    ) -> Tuple[Optional[CheckpointConfig], Any, Dict]:
        """Materialize the paged snapshot as FLAT raw bytes + flat
        meta — the agent's persist path (and the breakpoint save)
        consume the exact format a flat save would have produced, so
        the storage tier never learns about pages."""
        from dlrover_tpu.checkpoint.sparse import KV_STATE_KEY
        from dlrover_tpu.ops.fastcopy import copy_into

        d = self._read_paged_directory(verify_pages=True)
        if d is None:
            return None, b"", {}
        buf = self._shm.buf
        views, page_metas = self._paged_views(d, buf)
        scalars = dict(pickle.loads(d["scalars_blob"]))
        kv = self._paged_kv_state(d, buf)
        arrays: Dict[str, np.ndarray] = dict(views)
        if kv is not None:
            for k, v in _flatten_state_dict(
                {KV_STATE_KEY: kv}
            ).items():
                if isinstance(v, (np.ndarray, np.generic)):
                    arrays[k] = np.ascontiguousarray(v)
                else:
                    scalars[k] = v
        metas: Dict[str, TensorMeta] = {}
        offset = 0
        for key, arr in arrays.items():
            src = page_metas.get(key)
            dt = np.dtype(arr.dtype)
            count = int(np.prod(arr.shape, dtype=np.int64)) if (
                arr.shape
            ) else 1
            nbytes = count * dt.itemsize
            metas[key] = TensorMeta(
                shape=tuple(arr.shape), dtype=str(dt),
                offset=offset, nbytes=nbytes,
                global_shape=src.global_shape if src else None,
                index=src.index if src else None,
            )
            offset += nbytes
        blob = pickle.dumps(scalars)
        raw = bytearray(offset + len(blob))
        for key, arr in arrays.items():
            m = metas[key]
            if not m.nbytes:
                continue
            dst = np.frombuffer(
                raw, dtype=np.uint8, count=m.nbytes, offset=m.offset
            )
            copy_into(dst, _as_bytes_1d(np.ascontiguousarray(arr)))
        raw[offset:offset + len(blob)] = blob
        config: CheckpointConfig = d["config"]
        meta = {
            "tensors": metas,
            "config": config,
            "scalar_offset": offset,
            "scalar_nbytes": len(blob),
            "paged_generation": int(d["generation"]),
        }
        return config, bytes(raw), meta

    def paged_generation(self) -> int:
        """Generation of the currently readable paged snapshot (0 if
        none) — test/diagnostic surface."""
        d = self._read_paged_directory(verify_pages=False)
        return int(d["generation"]) if d else 0

    # -- read (agent side / restore) --------------------------------------

    def metadata(self) -> Dict[str, Any]:
        return self._meta.get(default_if_absent=True)

    def get_checkpoint_config(self) -> Optional[CheckpointConfig]:
        meta = self._meta.get(default_if_absent=True)
        return meta.get("config") if meta else None

    def no_checkpoint_state(self) -> bool:
        cfg = self.get_checkpoint_config()
        return cfg is None or cfg.step <= 0

    def _attach(
        self, min_size: int = 0
    ) -> Optional[PersistentSharedMemory]:
        """Attach (cached) to the segment; when the trainer grew and
        recreated it, a cached mapping points at the old unlinked
        inode — re-attach rather than silently slicing a truncated,
        stale snapshot (``min_size`` = bytes the caller needs)."""
        if self._shm is None:
            try:
                self._shm = PersistentSharedMemory(name=self._shm_name)
            except FileNotFoundError:
                return None
        if min_size and self._shm.size < min_size:
            try:
                self._shm.close()
            except BufferError:  # a reader still holds a view
                pass
            self._shm = None
            try:
                self._shm = PersistentSharedMemory(name=self._shm_name)
            except FileNotFoundError:
                return None
            if self._shm.size < min_size:
                logger.error(
                    "shm segment %s is %d bytes but the snapshot "
                    "metadata claims %d; refusing a truncated read",
                    self._shm_name, self._shm.size, min_size,
                )
                return None
        return self._shm

    def load_flat(
        self, detach: bool = True, stats=None,
    ) -> Tuple[Optional[CheckpointConfig], Dict[str, Any], Dict[str, Any]]:
        """Read the shm snapshot as (config, flat {key: array or
        scalar}, {key: TensorMeta}) — shard entries keep their
        ``@shardN`` keys for target-sharded reassembly.

        ``detach=True`` copies every leaf out of the segment through
        the staged restore pipeline (chunked, GIL-released, parallel —
        the serial per-leaf ``arr.copy()`` this replaces paid the
        mapping's page faults single-threaded).  ``detach=False``
        returns live views into shm: valid only until the next save
        overwrites the segment, so callers must finish (or detach /
        ``device_put``-copy) before returning control — the GSPMD
        restore path feeds them straight into batched ``device_put``.
        ``stats`` is a :class:`~.restore.RestoreStats` accumulator.
        """
        import time as _time

        from dlrover_tpu.checkpoint.restore import detach_flat

        t0 = _time.perf_counter()
        meta = self._meta.get(default_if_absent=True)
        if not meta:
            # the meta host may have died with the trainer; a paged
            # segment stands alone (the directory IS the metadata)
            if self._paged_active_slot() is not None:
                return self._load_flat_paged(detach=detach, stats=stats)
            return None, {}, {}
        if meta.get("paged"):
            return self._load_flat_paged(detach=detach, stats=stats)
        config: CheckpointConfig = meta["config"]
        if config.writing:
            logger.warning("shm snapshot is mid-write; refusing to load")
            return None, {}, {}
        shm = self._attach(
            min_size=meta["scalar_offset"] + meta["scalar_nbytes"]
        )
        if shm is None:
            return None, {}, {}
        views = _views_from(meta["tensors"], shm.buf)
        blob = bytes(
            shm.buf[
                meta["scalar_offset"]:
                meta["scalar_offset"] + meta["scalar_nbytes"]
            ]
        )
        if stats is not None:
            stats.read_s += _time.perf_counter() - t0
            if not detach:
                stats.bytes += sum(v.nbytes for v in views.values())
        flat = detach_flat(views, stats=stats) if detach else views
        flat.update(pickle.loads(blob))
        return config, flat, meta["tensors"]

    def load_state_dict(
        self, stats=None,
    ) -> Tuple[Optional[CheckpointConfig], Any]:
        """Read the shm snapshot back into a nested dict of private
        numpy arrays (caller device_puts with its shardings).  Shard
        entries of global arrays are assembled to full host arrays
        when this process's shards cover them (always single-host)."""
        import time as _time

        config, flat, metas = self.load_flat(stats=stats)
        if config is None:
            return None, {}
        t0 = _time.perf_counter()
        flat = _assemble_flat(flat, metas)
        if stats is not None:
            stats.assemble_s += _time.perf_counter() - t0
        return config, _unflatten_to_nested(flat)

    def read_raw(self) -> Tuple[Optional[CheckpointConfig], Any, Dict]:
        """Raw snapshot + meta for the agent's persist path (no pytree
        reconstruction).  Returns a PRIVATE ``bytes`` copy: the agent
        takes it under the shard lock (one memcpy) and releases the
        lock before any storage IO, so the trainer's next snapshot is
        never blocked behind a disk/remote write (the former zero-copy
        stream-under-lock mode traded exactly that stall for one saved
        memcpy — the wrong trade; see saver._save_shard)."""
        meta = self._meta.get(default_if_absent=True)
        if not meta:
            if self._paged_active_slot() is not None:
                return self._read_raw_paged()
            return None, b"", {}
        if meta.get("paged"):
            return self._read_raw_paged()
        config: CheckpointConfig = meta["config"]
        total = meta["scalar_offset"] + meta["scalar_nbytes"]
        shm = self._attach(min_size=total)
        if shm is None or config.writing:
            return None, b"", {}
        return config, bytes(shm.buf[:total]), meta

    def prefault(
        self, workers: Optional[int] = None,
        chunk_bytes: int = 64 * 2**20,
    ) -> int:
        """Touch every page of the snapshot so a later read runs warm.

        Page-table population is PER PROCESS: the agent's prefetch
        warms the agent, not the trainer — so the respawned trainer
        runs this itself (engine construction kicks it on a daemon
        thread) while its model build / jit trace proceeds.  Strided
        read-only touches in parallel ~chunk_bytes pieces: numpy
        releases the GIL for the reductions, so the faults overlap
        across the (bounded) pool.  Returns bytes touched (0 when no
        snapshot exists)."""
        meta = self._meta.get(default_if_absent=True)
        if not meta:
            return 0
        total = meta["scalar_offset"] + meta["scalar_nbytes"]
        shm = self._attach(min_size=total)
        if shm is None or total <= 0:
            return 0
        workers = workers if workers is not None else prefault_workers()
        flat = np.frombuffer(shm.buf, dtype=np.uint8, count=total)

        def touch(lo: int, hi: int):
            flat[lo:hi:4096].sum()

        spans = [
            (lo, min(lo + chunk_bytes, total))
            for lo in range(0, total, max(1, chunk_bytes))
        ]
        if workers <= 1 or len(spans) <= 1:
            for lo, hi in spans:
                touch(lo, hi)
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="shm-prefault"
            ) as pool:
                list(pool.map(lambda s: touch(*s), spans))
        return total

    def close(self):
        if self._shm is not None:
            self._shm.close()
            self._shm = None
        self._meta.close()

    def unlink(self):
        if self._attach() is not None:
            self._shm.unlink()
            self._shm = None


def prefault_workers() -> int:
    """Thread budget for page-in prefetch/prefault work.  PINNED low
    by default: the touches deliberately overlap the trainer's
    interpreter/jax import (or its model build), and an unbounded pool
    would starve exactly the work it is hiding latency from.
    ``DLROVER_PREFETCH_WORKERS`` overrides."""
    val = os.getenv("DLROVER_PREFETCH_WORKERS", "").strip()
    if val:
        try:
            return max(1, int(val))
        except ValueError:
            pass
    return min(4, max(1, (os.cpu_count() or 2) // 2))


def _views_from(metas: Dict[str, TensorMeta], buf) -> Dict[str, np.ndarray]:
    """{key: np.frombuffer view} over a shm segment or raw/mmap blob —
    free to build; paging/copy cost is paid by whichever pipeline
    stage consumes the view."""
    views: Dict[str, np.ndarray] = {}
    for key, m in metas.items():
        views[key] = np.frombuffer(
            buf, dtype=np.dtype(m.dtype),
            count=int(np.prod(m.shape, dtype=np.int64)) if m.shape else 1,
            offset=m.offset,
        ).reshape(m.shape)
    return views


def flat_from_raw(
    meta: Dict, raw, detach: bool = True, stats=None,
) -> Tuple[Dict, Dict]:
    """(flat {key: array/scalar}, {key: TensorMeta}) from raw shm
    bytes — or an mmap view from ``storage.read_view`` — shard keys
    preserved.  ``detach=False`` returns views into ``raw`` (the
    caller keeps ``raw`` alive until it is done)."""
    from dlrover_tpu.checkpoint.restore import detach_flat

    views = _views_from(meta["tensors"], raw)
    if stats is not None and not detach:
        stats.bytes += sum(v.nbytes for v in views.values())
    flat = detach_flat(views, stats=stats) if detach else views
    blob = raw[
        meta["scalar_offset"]:meta["scalar_offset"] + meta["scalar_nbytes"]
    ]
    flat.update(pickle.loads(blob))
    return flat, meta["tensors"]


def _assemble_flat(flat: Dict[str, Any], metas: Dict[str, Any]):
    """Assemble ``@shardN`` entries into full host arrays (raises if
    the visible shards do not cover a leaf — topology changed across
    hosts; use the target-sharded restore or the orbax tier)."""
    from dlrover_tpu.checkpoint.sharded import (
        SHARD_SEP,
        assemble_shard,
        group_shard_entries,
    )

    grouped, plain = group_shard_entries(flat, metas)
    for base, entries in grouped.items():
        some_key = f"{base}{SHARD_SEP}0"
        m = metas.get(some_key)
        gshape = tuple(m.global_shape)
        full = assemble_shard(
            tuple((0, d) for d in gshape),
            np.dtype(m.dtype),
            entries,
        )
        if full is None:
            raise ValueError(
                f"shards of '{base}' do not cover its global shape "
                f"{gshape}: restore with a target state "
                f"(load_sharded) or from the orbax tier"
            )
        plain[base] = full
    return plain


def state_dict_from_raw(meta: Dict, raw, stats=None):
    """Rebuild the nested dict from raw shm bytes (storage load path);
    detach copies run through the staged restore pipeline."""
    import time as _time

    flat, metas = flat_from_raw(meta, raw, stats=stats)
    t0 = _time.perf_counter()
    flat = _assemble_flat(flat, metas)
    if stats is not None:
        stats.assemble_s += _time.perf_counter() - t0
    return _unflatten_to_nested(flat)
