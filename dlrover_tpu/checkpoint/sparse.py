"""KvVariable state <-> flash checkpoint: the sparse adapter.

Reference: TFPlus persists hash-table embedding state through its
checkpoint system (``tfplus/kv_variable/python/training/
checkpoint_manager.py:34`` — KvVariable export ops feeding TF
checkpoints).  DLRover's whole sparse-elasticity story assumes
embedding rows, frequency counters and optimizer slots survive
scaling; this module is the TPU repo's version of that contract.

A :class:`SparseStateAdapter` registers host-resident
:class:`~dlrover_tpu.ops.kv_variable.KvVariable` tables (the
embedding table AND its optimizer's slot tables) with the
flash-checkpoint engine.  On every save the engine asks the adapter
for an :meth:`export_state` snapshot — plain numpy ``keys`` /
``values`` / ``freq`` blobs, nested under the reserved ``__kv__``
pytree key — which rides the shm segment next to the dense state and
is persisted to committed storage per rank by the unchanged agent
saver.  On restore the engine hands the blobs back and the adapter
``import_``\\ s them.

Cross-world semantics (the elastic-resize contract): the shm tier is
per-node state and is REFUSED across a world change (the dense rule);
cross-world restores read every old rank's kv shard from committed
storage and RESHARD the hash table — rows are re-partitioned by
:func:`owner_of_keys` (the same splitmix64 finalizer the C++ store
hashes with) onto the new world, and each rank imports exactly its
owned subset.  Jobs that want cross-world sparse restores must
partition training traffic with the same owner function (the
DeepFM/sparse chaos scripts do); same-world restores import each
rank's own shard verbatim, with no ownership assumption.

Telemetry: every export/import emits a ``kv_checkpoint`` event
(rows, bytes, spilled rows, tier, reshard accounting) and records
``dlrover_kv_checkpoint_seconds{stage}``.  With ``DLROVER_KV_DIGEST``
set, events additionally carry an order-independent per-table content
digest (sum mod 2**64 of per-row hashes over key+values+freq) — the
chaos invariants prove "every row, frequency count and optimizer
slot bit-identical through the cycle" from the event log alone, and
the digests are additive across disjoint shards, so exactly-once
resharding is checkable as sum-of-new-digests == sum-of-old-digests.
"""

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.ops.kv_variable import (
    DIRTY_CONSUMER_CHECKPOINT,
    DIRTY_CONSUMER_SERVING,
    DIRTY_CONSUMER_SHM,
)
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

# reserved top-level pytree key the adapter's blobs ride under; the
# engine strips it before handing the dense state back to the caller
KV_STATE_KEY = "__kv__"
KV_PREFIX = KV_STATE_KEY + "/"
# nested key holding non-table optimizer state (step counters)
SCALARS_KEY = "__scalars__"
# nested key carrying the delta-checkpoint link metadata (kind =
# base/delta, parent/base steps, the chain of steps to replay); the
# chain is a comma-joined string so it survives the pytree flatten as
# one scalar
KV_META_KEY = "__meta__"

# streaming-reshard window: the peak value-row memory any bulk sparse
# path may hold at once.  MB knob for production, ROWS override for
# tests/chaos (tiny tables need sub-MB windows to exercise chunking)
RESHARD_WINDOW_MB_ENV = "DLROVER_KV_RESHARD_WINDOW_MB"
RESHARD_WINDOW_ROWS_ENV = "DLROVER_KV_RESHARD_WINDOW_ROWS"
_DEFAULT_RESHARD_WINDOW_MB = 64.0


def reshard_window_rows(row_bytes: int) -> int:
    """Rows per streaming window for a table whose rows cost
    ``row_bytes`` (keys + values + freq)."""
    rows = os.environ.get(RESHARD_WINDOW_ROWS_ENV, "").strip()
    if rows:
        try:
            return max(1, int(rows))
        except ValueError:
            pass
    try:
        mb = float(
            os.environ.get(RESHARD_WINDOW_MB_ENV, "").strip()
            or _DEFAULT_RESHARD_WINDOW_MB
        )
    except ValueError:
        mb = _DEFAULT_RESHARD_WINDOW_MB
    return max(1, int(mb * 2**20 / max(1, row_bytes)))

_REG = get_registry()
_KV_CKPT_SECONDS = _REG.histogram(
    "dlrover_kv_checkpoint_seconds",
    "Sparse (KvVariable) checkpoint stage time "
    "(labels: stage = export / import / reshard)",
)


def _hash64(keys: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64/murmur finalizer — bit-identical to
    ``Table::hash_key`` in ``native/kv_store.cc``, so the Python-side
    ownership partition and the C++ table agree on key placement."""
    x = np.ascontiguousarray(keys, dtype=np.int64).view(np.uint64).copy()
    x ^= x >> np.uint64(33)
    x *= np.uint64(0xFF51AFD7ED558CCD)
    x ^= x >> np.uint64(33)
    x *= np.uint64(0xC4CEB9FE1A85EC53)
    x ^= x >> np.uint64(33)
    return x


def owner_of_keys(keys: np.ndarray, world_size: int) -> np.ndarray:
    """Rank that owns each key in a ``world_size`` world.  THE
    partition rule of cross-world sparse restores: reshard assigns
    every row to ``hash64(key) % world_size``, and sparse train loops
    that want elastic resizes route each key's traffic the same way."""
    if world_size <= 1:
        return np.zeros(np.asarray(keys).size, dtype=np.int64)
    return (_hash64(keys) % np.uint64(world_size)).astype(np.int64)


_FNV_PRIME = np.uint64(0x100000001B3)


def rows_digest(
    keys: np.ndarray, values: np.ndarray, freq: np.ndarray
) -> int:
    """Order-independent content digest of a row set: per-row FNV-ish
    hash over key + value bytes + frequency, summed mod 2**64.

    Two properties the chaos invariants lean on: (a) row ORDER never
    matters (export order changes across an import), (b) digests of
    DISJOINT shards add — the union's digest is the wrapped sum of
    the shard digests, so exactly-once resharding is provable from
    per-rank events alone (a lost row changes the sum; a duplicated
    row adds its hash twice)."""
    n = int(np.asarray(keys).size)
    if n == 0:
        return 0
    h = _hash64(keys)
    vb = np.ascontiguousarray(values, dtype=np.float32).reshape(n, -1)
    raw = vb.view(np.uint8).reshape(n, -1)
    pad = (-raw.shape[1]) % 8
    if pad:
        raw = np.concatenate(
            [raw, np.zeros((n, pad), dtype=np.uint8)], axis=1
        )
    cols = raw.view(np.uint64)
    with np.errstate(over="ignore"):
        for j in range(cols.shape[1]):
            h = (h ^ cols[:, j]) * _FNV_PRIME
        h = (h ^ np.ascontiguousarray(freq, dtype=np.uint64)) * _FNV_PRIME
        total = np.sum(h, dtype=np.uint64)
    return int(total)


def keys_digest(keys: np.ndarray) -> int:
    """Order-independent digest of a bare key set (deletion
    tombstones carry no values): sum mod 2**64 of the per-key
    splitmix hashes.  Same additivity contract as
    :func:`rows_digest`."""
    if np.asarray(keys).size == 0:
        return 0
    with np.errstate(over="ignore"):
        return int(np.sum(_hash64(keys), dtype=np.uint64))


def merge_kv_states(
    base: Dict[str, Any], deltas: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Replay a base + delta chain in numpy-land WITHOUT tables: the
    paged shm tier stores kv pages as pickled export blobs and a
    restore (or the agent's flat materialization) must flatten the
    chain back to one full export bit-equal to what the live tables
    would produce.  Per delta: tombstones delete first, then touched
    rows last-write-win (the exact :meth:`SparseStateAdapter.
    apply_delta` ordering).  Optimizer scalars ride whole per link —
    the newest link's copy wins."""
    names = [k for k in base.keys() if k != SCALARS_KEY]
    merged: Dict[str, Any] = {}
    for name in names:
        sub = base[name]
        keys = np.ascontiguousarray(sub["keys"], dtype=np.int64)
        values = np.ascontiguousarray(sub["values"], dtype=np.float32)
        freq = np.ascontiguousarray(sub["freq"], dtype=np.uint64)
        for d in deltas:
            dsub = d.get(name)
            if not isinstance(dsub, dict):
                continue
            dead = np.ascontiguousarray(
                dsub.get("dead", ()), dtype=np.int64
            )
            if dead.size:
                live = ~np.isin(keys, dead)
                keys, values, freq = (
                    keys[live], values[live], freq[live]
                )
            dkeys = np.ascontiguousarray(dsub["keys"], dtype=np.int64)
            if dkeys.size:
                keep = ~np.isin(keys, dkeys)
                keys = np.concatenate([keys[keep], dkeys])
                values = np.concatenate([
                    values[keep],
                    np.ascontiguousarray(
                        dsub["values"], dtype=np.float32
                    ),
                ])
                freq = np.concatenate([
                    freq[keep],
                    np.ascontiguousarray(
                        dsub["freq"], dtype=np.uint64
                    ),
                ])
        merged[name] = {"keys": keys, "values": values, "freq": freq}
    scalars = base.get(SCALARS_KEY)
    for d in deltas:
        scalars = d.get(SCALARS_KEY, scalars)
    if scalars:
        merged[SCALARS_KEY] = scalars
    return merged


def _digest_enabled() -> bool:
    return os.environ.get(
        "DLROVER_KV_DIGEST", ""
    ).strip().lower() in ("1", "true", "yes", "on")


def _enc(name: str) -> str:
    """Table names may contain '/' (slot tables are named
    '<table>/m'); the pytree path separator is also '/'.  Encode to
    keep one nesting level per table so shard extraction and event
    digests stay keyed by whole table."""
    return name.replace("/", ".")


class SparseStateAdapter:
    """Registers KvVariable tables + sparse optimizers with the flash
    checkpoint engine (``engine.register_sparse(adapter)`` /
    ``Checkpointer.register_sparse``).

    ``digest=None`` reads ``DLROVER_KV_DIGEST`` (the chaos scenarios
    arm it); digests cost one vectorized pass over the exported rows.
    """

    def __init__(self, digest: Optional[bool] = None):
        self._tables: Dict[str, Any] = {}
        self._optimizers: List[Any] = []
        self._digest = digest
        # delta flash checkpoints (None = full exports, the default):
        # every `_delta_every`th durable export is a full base, the
        # rest export only the consumer-1 dirty rows; `_ckpt_chain`
        # is the step chain a restore replays, `_ckpt_poisoned`
        # forces the next export to re-base (fresh adapter, restore,
        # or a failed/skipped save whose drained delta never became
        # durable)
        self._delta_every: Optional[int] = None
        self._ckpt_chain: List[int] = []
        self._ckpt_poisoned = True
        # paged shm tier (consumer 2): its base+delta pages live in
        # the shm segment itself, so the chain here is only a length
        # counter for the full-base cadence; poisoned forces the next
        # shm export to re-base (fresh adapter, any restore, or a
        # paged save that failed after draining the baseline)
        self._shm_chain_len = 0
        self._shm_poisoned = True

    # -- registration -------------------------------------------------------

    def register_table(self, table) -> "SparseStateAdapter":
        name = _enc(table.name)
        if name in self._tables and self._tables[name] is not table:
            raise ValueError(
                f"a different table is already registered as {name!r}"
                " — table names must be unique per adapter"
            )
        self._tables[name] = table
        return self

    def register_optimizer(self, optimizer) -> "SparseStateAdapter":
        """Register a sparse optimizer: its parameter table, every
        slot table (GroupAdam m/v, Adagrad acc, FTRL z/n, ...) and
        its step-counter scalars all become checkpoint state."""
        self.register_table(optimizer.table)
        for slot in optimizer.slot_tables().values():
            self.register_table(slot)
        if optimizer not in self._optimizers:
            self._optimizers.append(optimizer)
        return self

    @property
    def tables(self) -> Dict[str, Any]:
        return dict(self._tables)

    def digest_enabled(self) -> bool:
        return self._digest if self._digest is not None else (
            _digest_enabled()
        )

    # -- export (save path) -------------------------------------------------

    def export_state(
        self, step: Optional[int] = None, rank: Optional[int] = None,
        extra_event: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Snapshot every registered table into plain numpy blobs
        (spilled rows included — ``KvVariable.export`` covers both
        tiers) plus optimizer scalars.  The returned dict nests under
        :data:`KV_STATE_KEY` in the engine's state dict and rides the
        shm segment like any other array leaves, so the save stall
        grows only by these memcpys (the table is host RAM already;
        there is no device fetch).

        Chaos hook ``kv.spill``: an injected ``io_error`` here plays
        a spill-tier disk dying DURING the export — the adapter
        breaks every registered table's cold tier (subsequent spill
        IO fails like a dead device) and proceeds: stranded cold rows
        drop out of the export, DRAM rows persist, and the production
        write-failure breaker trips on the next training step."""
        try:
            _chaos.fire("kv.spill", step=step)
        except OSError:
            logger.error(
                "kv.spill io_error injected: breaking the spill tier "
                "of %d table(s) mid-export", len(self._tables),
            )
            for table in self._tables.values():
                table._break_spill_tier()
        t0 = time.perf_counter()
        with_digest = self.digest_enabled()
        out: Dict[str, Any] = {}
        digests: Dict[str, Dict[str, Any]] = {}
        rows = nbytes = spilled = lost = 0
        spill_disabled = False
        for name, table in self._tables.items():
            logical = len(table)
            keys, values, freq = table.export()
            out[name] = {"keys": keys, "values": values, "freq": freq}
            rows += len(keys)
            lost += max(0, logical - len(keys))
            nbytes += keys.nbytes + values.nbytes + freq.nbytes
            st = table.spill_stats()
            spilled += st["disk_rows"]
            spill_disabled = spill_disabled or st["disabled"]
            if with_digest:
                digests[name] = {
                    "rows": int(len(keys)),
                    "sum": f"{rows_digest(keys, values, freq):016x}",
                }
        scalars = {
            _enc(opt.table.name): opt.state_scalars()
            for opt in self._optimizers
            if hasattr(opt, "state_scalars")
        }
        if scalars:
            out[SCALARS_KEY] = scalars
        seconds = time.perf_counter() - t0
        _KV_CKPT_SECONDS.observe(seconds, stage="export")
        event = dict(
            stage="export", rows=int(rows), bytes=int(nbytes),
            spilled_rows=int(spilled), seconds=round(seconds, 4),
            tables=len(self._tables),
        )
        if step is not None:
            event["step"] = int(step)
        if rank is not None:
            event["rank"] = int(rank)
        if spill_disabled:
            event["spill_disabled"] = True
        if lost:
            # rows the logical table claims but the export could not
            # read (a dead spill tier) — the checkpoint is still
            # valid for everything it DOES contain
            event["lost_rows"] = int(lost)
        if digests:
            event["digests"] = digests
        if extra_event:
            event.update(extra_event)
        emit_event("kv_checkpoint", **event)
        return out

    # -- delta export (serving-plane incremental publication) ---------------

    def enable_dirty_tracking(
        self, consumer: int = DIRTY_CONSUMER_SERVING
    ) -> "SparseStateAdapter":
        """Arm dirty/dead tracking for one consumer slot on every
        registered table (the serving publisher arms the serving
        slot at construction; :meth:`enable_delta_checkpoints` arms
        the checkpoint slot — tracking is opt-in so non-publishing
        jobs pay nothing, and the two planes baseline
        independently)."""
        for table in self._tables.values():
            table.enable_dirty_tracking(consumer)
        return self

    def dirty_rows(
        self, consumer: int = DIRTY_CONSUMER_SERVING
    ) -> int:
        """Rows the consumer's next delta would carry, summed over
        tables."""
        return sum(
            t.dirty_count(consumer) for t in self._tables.values()
        )

    def export_delta(
        self, step: Optional[int] = None, rank: Optional[int] = None,
        clear: bool = True,
        consumer: int = DIRTY_CONSUMER_SERVING,
        extra_event: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Snapshot only the rows TOUCHED since the last cleared
        delta (plus deletion tombstones) — the export stall is
        O(rows touched this interval), never O(table), which is what
        lets a multi-GB continuously-trained table republish to
        serving replicas without full-table stalls (reference:
        tfplus ``checkpoint_manager.py:72`` delta checkpoints).

        ``clear`` (the publisher default) atomically drains exactly
        the exported keys, so a mutation racing the export lands in
        the NEXT delta instead of vanishing.  Flash checkpoints call
        :meth:`export_state` and never clear — the serving delta
        chain and the fault-tolerance snapshots baseline
        independently."""
        t0 = time.perf_counter()
        with_digest = self.digest_enabled()
        out: Dict[str, Any] = {}
        digests: Dict[str, Dict[str, Any]] = {}
        rows = nbytes = dead_rows = table_rows = 0
        for name, table in self._tables.items():
            # tombstones FIRST: the two exports are separate lock
            # holds, and an eviction landing between them must not
            # put a key in this delta's tombstones AFTER its row was
            # exported (apply would delete-then-reimport — a
            # resurrection).  Dead-first, the racing eviction's
            # tombstone simply waits for the next delta; dead-THEN-
            # re-touched keys legitimately appear in both lists and
            # the apply order (delete, then import) lands them alive
            # with the new value — same as the trainer.
            dead = table.export_dead(clear=clear, consumer=consumer)
            keys, values, freq = table.export_dirty(
                clear=clear, consumer=consumer
            )
            out[name] = {
                "keys": keys, "values": values, "freq": freq,
                "dead": dead,
            }
            rows += len(keys)
            dead_rows += len(dead)
            table_rows += len(table)
            nbytes += (
                keys.nbytes + values.nbytes + freq.nbytes + dead.nbytes
            )
            if with_digest:
                digests[name] = {
                    "rows": int(len(keys)),
                    "sum": f"{rows_digest(keys, values, freq):016x}",
                    "dead": int(len(dead)),
                    "dead_sum": f"{keys_digest(dead):016x}",
                }
        scalars = {
            _enc(opt.table.name): opt.state_scalars()
            for opt in self._optimizers
            if hasattr(opt, "state_scalars")
        }
        if scalars:
            out[SCALARS_KEY] = scalars
        seconds = time.perf_counter() - t0
        _KV_CKPT_SECONDS.observe(seconds, stage="export_delta")
        event = dict(
            stage="export", rows=int(rows), bytes=int(nbytes),
            seconds=round(seconds, 4), tables=len(self._tables),
            delta=True, dead_rows=int(dead_rows),
            table_rows=int(table_rows),
        )
        if step is not None:
            event["step"] = int(step)
        if rank is not None:
            event["rank"] = int(rank)
        if digests:
            event["digests"] = digests
        if extra_event:
            event.update(extra_event)
        emit_event("kv_checkpoint", **event)
        return out

    def apply_delta(
        self, state: Dict, tier: str = "", step: Optional[int] = None,
        rank: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Apply one delta onto the registered tables IN PLACE:
        tombstoned keys are deleted, touched rows imported (insert or
        overwrite) — the replica-side half of the delta chain, and
        the replay primitive the compaction-edge tests drive.  Unlike
        :meth:`import_state` this never clears: unchanged rows stay
        put."""
        t0 = time.perf_counter()
        with_digest = self.digest_enabled()
        rows = nbytes = dead_rows = 0
        digests: Dict[str, Dict[str, Any]] = {}
        for name, table in self._tables.items():
            sub = state.get(name)
            if not isinstance(sub, dict) or "keys" not in sub:
                continue
            keys = np.ascontiguousarray(sub["keys"], dtype=np.int64)
            values = np.ascontiguousarray(
                sub["values"], dtype=np.float32
            )
            freq = np.ascontiguousarray(sub["freq"], dtype=np.uint64)
            dead = np.ascontiguousarray(
                sub.get("dead", ()), dtype=np.int64
            )
            # tombstones first — LOAD-BEARING: the exporter reads
            # dead before dirty, so a key that died and was
            # re-touched between the two exports appears in both
            # lists, and delete-then-import must land it alive with
            # the re-touched value (matching the trainer's state)
            if dead.size:
                table.delete(dead)
            if keys.size:
                table.import_(keys, values, freq)
            rows += int(keys.size)
            dead_rows += int(dead.size)
            nbytes += (
                keys.nbytes + values.nbytes + freq.nbytes + dead.nbytes
            )
            if with_digest:
                digests[name] = {
                    "rows": int(keys.size),
                    "sum": f"{rows_digest(keys, values, freq):016x}",
                    "dead": int(dead.size),
                    "dead_sum": f"{keys_digest(dead):016x}",
                }
        scalars = state.get(SCALARS_KEY)
        if scalars:
            for opt in self._optimizers:
                sc = scalars.get(_enc(opt.table.name))
                if sc and hasattr(opt, "load_state_scalars"):
                    opt.load_state_scalars(sc)
        seconds = time.perf_counter() - t0
        _KV_CKPT_SECONDS.observe(seconds, stage="apply_delta")
        event = dict(
            stage="restore", rows=int(rows), bytes=int(nbytes),
            seconds=round(seconds, 4), tables=len(self._tables),
            resharded=False, delta=True, dead_rows=int(dead_rows),
        )
        if tier:
            event["tier"] = tier
        if step is not None:
            event["step"] = int(step)
        if rank is not None:
            event["rank"] = int(rank)
        if digests:
            event["digests"] = digests
        emit_event("kv_checkpoint", **event)
        return {"kv_s": round(seconds, 4), "kv_rows": int(rows)}

    # -- delta-aware flash checkpoints (hot save path) ----------------------

    def enable_delta_checkpoints(
        self, full_every: int = 8
    ) -> "SparseStateAdapter":
        """Make durable flash saves INCREMENTAL: every
        ``full_every``th export is a full base, the rest carry only
        the rows touched since the previous durable export — the
        save stall becomes O(rows touched), the PR 13 serving result
        applied to the fault-tolerance plane.  The baseline lives in
        the CHECKPOINT consumer slot, so the serving publisher's
        deltas and these never clear each other.

        Restores replay the chain (base + deltas, read from the
        committed storage step dirs named in the link metadata), so
        every link must stay on storage: run with
        ``deletion_keep_latest=0`` or ``>= full_every``.  Memory-only
        (shm) saves always export full state — the shm segment holds
        exactly one snapshot and must stand alone."""
        self._delta_every = max(1, int(full_every))
        self._ckpt_poisoned = True
        self.enable_dirty_tracking(DIRTY_CONSUMER_CHECKPOINT)
        return self

    def delta_checkpoints_enabled(self) -> bool:
        return self._delta_every is not None

    def delta_full_every(self) -> int:
        """Base cadence of the delta-checkpoint chain (0 when delta
        checkpoints are off) — the longest chain a restore replays,
        and the minimum ``deletion_keep_latest`` that keeps every
        link on storage."""
        return int(self._delta_every or 0)

    def checkpoint_chain_poison(self) -> None:
        """Force the next durable export to re-base.  Called when an
        export's save was skipped or failed AFTER the delta drained
        its baseline — those rows would otherwise silently drop out
        of the chain (same discipline as the serving publisher's
        poisoned chain)."""
        self._ckpt_poisoned = True

    def export_for_checkpoint(
        self, step: Optional[int] = None, rank: Optional[int] = None,
        durable: bool = True,
    ) -> Dict[str, Any]:
        """The engine's save-path entry: a full export unless delta
        checkpoints are enabled AND this save is durable (persisted
        to a storage step dir a restore can chain through).  Link
        metadata rides under :data:`KV_META_KEY`."""
        if self._delta_every is None or not durable:
            return self.export_state(step=step, rank=rank)
        step_i = int(step) if step is not None else 0
        # a table registered after the last base has no tracked
        # history — re-base so its rows enter the chain at all
        untracked = any(
            not t.dirty_tracking_enabled(DIRTY_CONSUMER_CHECKPOINT)
            for t in self._tables.values()
        )
        self.enable_dirty_tracking(DIRTY_CONSUMER_CHECKPOINT)
        if (
            untracked
            or self._ckpt_poisoned
            or not self._ckpt_chain
            or len(self._ckpt_chain) >= self._delta_every
        ):
            # baseline BEFORE the export (the publisher's ordering):
            # a mutation racing the two steps lands in the base AND
            # the next delta — a benign overwrite, never a silent gap
            for table in self._tables.values():
                table.clear_dirty(DIRTY_CONSUMER_CHECKPOINT)
            out = self.export_state(
                step=step, rank=rank,
                extra_event={"kind": "base",
                             "consumer": DIRTY_CONSUMER_CHECKPOINT},
            )
            out[KV_META_KEY] = {"kind": "base", "step": step_i}
            self._ckpt_chain = [step_i]
            self._ckpt_poisoned = False
            return out
        out = self.export_delta(
            step=step, rank=rank, clear=True,
            consumer=DIRTY_CONSUMER_CHECKPOINT,
            extra_event={
                "kind": "delta",
                "consumer": DIRTY_CONSUMER_CHECKPOINT,
                "base_step": int(self._ckpt_chain[0]),
                "parent_step": int(self._ckpt_chain[-1]),
                "chain_len": len(self._ckpt_chain) + 1,
            },
        )
        out[KV_META_KEY] = {
            "kind": "delta",
            "step": step_i,
            "parent": int(self._ckpt_chain[-1]),
            "base": int(self._ckpt_chain[0]),
            # comma-joined so the pytree flatten keeps it one scalar
            "chain": ",".join(str(s) for s in self._ckpt_chain),
        }
        self._ckpt_chain.append(step_i)
        return out

    # -- paged shm tier (consumer 2) ---------------------------------------

    def shm_chain_poison(self) -> None:
        """Force the next paged shm export to re-base — same
        discipline as :meth:`checkpoint_chain_poison`, for the shm
        consumer slot: a paged save that failed or was skipped AFTER
        the delta drained its baseline would otherwise silently drop
        those rows from the segment."""
        self._shm_poisoned = True

    def export_for_shm(
        self, step: Optional[int] = None, rank: Optional[int] = None,
        full_every: int = 0,
    ) -> Tuple[str, Dict[str, Any]]:
        """The paged shm tier's export entry: ``("base", state)`` on
        the first save / after any poison / every ``full_every``-th
        save, else ``("delta", state)`` holding only the consumer-2
        dirty rows.  Unlike the storage chain there is no
        :data:`KV_META_KEY` link metadata — the shm page directory
        itself records the chain."""
        untracked = any(
            not t.dirty_tracking_enabled(DIRTY_CONSUMER_SHM)
            for t in self._tables.values()
        )
        self.enable_dirty_tracking(DIRTY_CONSUMER_SHM)
        cadence = int(full_every or 0)
        if (
            untracked
            or self._shm_poisoned
            or self._shm_chain_len <= 0
            or (cadence > 0 and self._shm_chain_len >= cadence)
        ):
            # baseline BEFORE the export (the publisher's ordering):
            # a racing mutation lands in the base AND the next delta
            for table in self._tables.values():
                table.clear_dirty(DIRTY_CONSUMER_SHM)
            out = self.export_state(
                step=step, rank=rank,
                extra_event={"kind": "base",
                             "consumer": DIRTY_CONSUMER_SHM},
            )
            self._shm_chain_len = 1
            self._shm_poisoned = False
            return "base", out
        out = self.export_delta(
            step=step, rank=rank, clear=True,
            consumer=DIRTY_CONSUMER_SHM,
            extra_event={
                "kind": "delta",
                "consumer": DIRTY_CONSUMER_SHM,
                "chain_len": self._shm_chain_len + 1,
            },
        )
        self._shm_chain_len += 1
        return "delta", out

    @staticmethod
    def chain_steps(meta: Dict[str, Any]) -> List[int]:
        """The storage steps a delta link's restore must replay
        BEFORE the link itself (base first)."""
        raw = str(meta.get("chain", "") or "")
        return [int(s) for s in raw.split(",") if s.strip()]

    def import_chain(
        self, links: List[Dict], tier: str = "",
        step: Optional[int] = None, rank: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Chain replay: ``links[0]`` (a base / full export) replaces
        the tables, every later link applies as a delta (tombstones
        then rows).  Digest-equal to a full export at every link —
        the property test pins it."""
        if not links:
            return {"kv_s": 0.0, "kv_rows": 0}
        t0 = time.perf_counter()
        info = self.import_state(
            links[0], tier=tier, step=step, rank=rank
        )
        rows = int(info.get("kv_rows", 0))
        for link in links[1:]:
            d = self.apply_delta(
                link, tier=tier, step=step, rank=rank
            )
            rows += int(d.get("kv_rows", 0))
        return {
            "kv_s": round(time.perf_counter() - t0, 4),
            "kv_rows": rows,
            "kv_chain": len(links),
        }

    # -- import (restore path) ----------------------------------------------

    def _import_tables(
        self, per_table: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]],
        scalars: Optional[Dict] = None,
    ) -> Tuple[int, int, Dict[str, Dict[str, Any]]]:
        """Replace every registered table's contents; returns
        (rows, bytes, digests)."""
        # any restore invalidates the delta-checkpoint baseline: the
        # import re-marks every row dirty anyway, and a delta chained
        # onto pre-restore history would be wrong — next export bases
        self._ckpt_poisoned = True
        self._shm_poisoned = True
        with_digest = self.digest_enabled()
        rows = nbytes = 0
        digests: Dict[str, Dict[str, Any]] = {}
        for name, table in self._tables.items():
            blob = per_table.get(name)
            if blob is None:
                logger.warning(
                    "checkpoint has no kv state for table %r; leaving "
                    "it untouched", name,
                )
                continue
            keys, values, freq = blob
            table.clear()
            table.import_(keys, values, freq)
            rows += len(keys)
            nbytes += keys.nbytes + values.nbytes + freq.nbytes
            if with_digest:
                digests[name] = {
                    "rows": int(len(keys)),
                    "sum": f"{rows_digest(keys, values, freq):016x}",
                }
        if scalars:
            for opt in self._optimizers:
                sc = scalars.get(_enc(opt.table.name))
                if sc and hasattr(opt, "load_state_scalars"):
                    opt.load_state_scalars(sc)
        return rows, nbytes, digests

    @staticmethod
    def _blobs_from(state: Dict) -> Tuple[Dict, Optional[Dict]]:
        """Nested kv state dict -> ({table: (keys, values, freq)},
        scalars)."""
        per_table = {}
        for name, sub in state.items():
            if name == SCALARS_KEY or not isinstance(sub, dict):
                continue
            if "keys" not in sub:
                continue
            per_table[name] = (
                np.ascontiguousarray(sub["keys"], dtype=np.int64),
                np.ascontiguousarray(sub["values"], dtype=np.float32),
                np.ascontiguousarray(sub["freq"], dtype=np.uint64),
            )
        return per_table, state.get(SCALARS_KEY)

    def import_state(
        self, state: Dict, tier: str = "", step: Optional[int] = None,
        rank: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Same-world restore: import one rank's own kv shard
        verbatim (no ownership assumption).  Returns the info dict
        the engine folds into the restore phase breakdown."""
        t0 = time.perf_counter()
        per_table, scalars = self._blobs_from(state)
        rows, nbytes, digests = self._import_tables(per_table, scalars)
        seconds = time.perf_counter() - t0
        _KV_CKPT_SECONDS.observe(seconds, stage="import")
        event = dict(
            stage="restore", rows=int(rows), bytes=int(nbytes),
            seconds=round(seconds, 4), tables=len(per_table),
            resharded=False,
        )
        if tier:
            event["tier"] = tier
        if step is not None:
            event["step"] = int(step)
        if rank is not None:
            event["rank"] = int(rank)
        if digests:
            event["digests"] = digests
        emit_event("kv_checkpoint", **event)
        return {"kv_s": round(seconds, 4), "kv_rows": int(rows)}

    def import_shards(
        self,
        shards: Dict[int, Dict],
        world_size: int,
        rank: int,
        from_world: Optional[int] = None,
        tier: str = "storage",
        step: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Cross-world restore: RESHARD the hash table from every old
        rank's kv state.  Rows are concatenated across shards
        (deduped by key, later rank wins — a well-partitioned job
        never collides), re-partitioned by :func:`owner_of_keys` onto
        the new ``world_size``, and exactly this rank's owned subset
        replaces the table contents.  Optimizer scalars come from the
        lowest old rank.  ``shards`` maps old global rank -> nested
        kv state dict."""
        t0 = time.perf_counter()
        if from_world is None:
            from_world = len(shards)
        per_rank = {
            r: self._blobs_from(state) for r, state in sorted(
                shards.items()
            )
        }
        owned: Dict[str, Tuple] = {}
        total_rows = 0
        for name in self._tables:
            ks, vs, fs = [], [], []
            for r, (per_table, _) in per_rank.items():
                blob = per_table.get(name)
                if blob is not None:
                    ks.append(blob[0])
                    vs.append(blob[1])
                    fs.append(blob[2])
            if not ks:
                continue
            keys = np.concatenate(ks)
            dim = self._tables[name].dim
            values = np.concatenate(
                [v.reshape(-1, dim) for v in vs]
            )
            freq = np.concatenate(fs)
            # dedupe by key, keeping the LAST occurrence (highest old
            # rank) — mirrors import_'s overwrite semantics
            _, last_idx = np.unique(keys[::-1], return_index=True)
            keep = np.sort(len(keys) - 1 - last_idx)
            keys, values, freq = keys[keep], values[keep], freq[keep]
            total_rows += len(keys)
            mine = owner_of_keys(keys, world_size) == rank
            owned[name] = (keys[mine], values[mine], freq[mine])
        for name, table in self._tables.items():
            if name not in owned:
                # a registered table with no rows in ANY old shard
                # must still be CLEARED: a reshard-in-place that left
                # it untouched would keep the previous world's rows —
                # phantom duplicates of rows the key-hash partition
                # assigned to other ranks
                owned[name] = (
                    np.empty(0, np.int64),
                    np.empty((0, table.dim), np.float32),
                    np.empty(0, np.uint64),
                )
        scalars = None
        for _r, (_pt, sc) in per_rank.items():
            if sc:
                scalars = sc
                break
        rows, nbytes, digests = self._import_tables(owned, scalars)
        seconds = time.perf_counter() - t0
        _KV_CKPT_SECONDS.observe(seconds, stage="reshard")
        event = dict(
            stage="restore", rows=int(rows), bytes=int(nbytes),
            seconds=round(seconds, 4), tables=len(owned),
            resharded=True, from_world=int(from_world),
            world_size=int(world_size), total_rows=int(total_rows),
            tier=tier,
        )
        if step is not None:
            event["step"] = int(step)
        event["rank"] = int(rank)
        if digests:
            event["digests"] = digests
        emit_event("kv_checkpoint", **event)
        logger.info(
            "resharded kv restore: %d/%d row(s) owned by rank %d of "
            "world %d (from world %s, %d table(s), %.3fs)",
            rows, total_rows, rank, world_size, from_world,
            len(owned), seconds,
        )
        return {
            "kv_s": round(seconds, 4),
            "kv_rows": int(rows),
            "kv_resharded": True,
        }

    # -- streaming reshard (bounded-memory cross-world restore) -------------

    def import_shards_streaming(
        self,
        shards: Dict[int, Any],
        world_size: int,
        rank: int,
        from_world: Optional[int] = None,
        tier: str = "storage",
        step: Optional[int] = None,
        window_rows: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Cross-world reshard that never holds more than a bounded
        window of value rows in RAM: per old rank, per table, the
        source arrays (typically live mmap/shm VIEWS — only the
        window pages in) are walked in ``window_rows`` slices, each
        window vectorized through :func:`owner_of_keys`, and exactly
        this rank's owned subset imported.  Window k+1's
        partition/copy runs on the staged-restore pool while window
        k's native import holds the table lock (ctypes releases the
        GIL), so partition and import overlap.

        ``shards`` maps old rank -> nested kv state OR a LIST of
        states (a delta-checkpoint chain, base first: later links
        overwrite/tombstone earlier ones exactly as replay would).
        Ranks apply in ascending order, so duplicate keys keep the
        one-shot path's last-rank-wins overwrite semantics.

        With digests armed, the per-window import digests are summed
        additively and checked against a chunked re-export of the
        final tables — a chunk imported twice (or a row lost between
        windows) breaks the equality, so exactly-once holds at ANY
        chunking.  (Chain inputs skip the strict check: a delta
        legitimately overwrites its base's rows.)"""
        from dlrover_tpu.checkpoint.restore import StagedRestore

        t0 = time.perf_counter()
        if from_world is None:
            from_world = len(shards)
        with_digest = self.digest_enabled()
        chains: Dict[int, List[Dict]] = {
            r: (list(state) if isinstance(state, (list, tuple))
                else [state])
            for r, state in sorted(shards.items())
        }
        chained = any(len(links) > 1 for links in chains.values())
        # replace-semantics: clear every registered table up front (a
        # leftover row from the previous world would be a phantom
        # duplicate of a row the partition assigned elsewhere), then
        # pre-size for the expected owned share — geometric slab
        # growth mid-stream would otherwise realloc+memcpy the whole
        # destination repeatedly, exactly the transient the bounded
        # window exists to avoid
        for name, table in self._tables.items():
            table.clear()
            est = 0
            for links in chains.values():
                sub = links[0].get(name)
                if isinstance(sub, dict) and sub.get(
                    "keys"
                ) is not None:
                    est += int(np.asarray(sub["keys"]).shape[0])
            if est:
                table.reserve(est // max(1, world_size) + 64)
        self._ckpt_poisoned = True
        self._shm_poisoned = True

        rows = nbytes = total_rows = chunks = 0
        import_sums: Dict[str, int] = {}
        win_used: Optional[int] = None

        def _tasks():
            """(table, kind, key_slice, value_slice, freq_slice)
            windows, ranks ascending, links in chain order, dead
            before rows within a link (the apply_delta ordering)."""
            nonlocal win_used
            for old_rank, links in chains.items():
                for link in links:
                    for name, table in self._tables.items():
                        sub = link.get(name)
                        if not isinstance(sub, dict):
                            continue
                        win = window_rows or reshard_window_rows(
                            table.dim * 4 + 16
                        )
                        win_used = win
                        dead = sub.get("dead")
                        if dead is not None and len(dead):
                            for lo in range(0, len(dead), win):
                                yield (
                                    name, "dead",
                                    dead[lo:lo + win], None, None,
                                )
                        keys = sub.get("keys")
                        if keys is None:
                            continue
                        n = int(np.asarray(keys).shape[0])
                        for lo in range(0, n, win):
                            hi = min(n, lo + win)
                            yield (
                                name, "rows", keys[lo:hi],
                                sub["values"], (sub["freq"], lo, hi),
                            )

        def _prepare(task):
            """Window copy + ownership partition (pool thread, numpy
            only).  Only the window's KEY column (8 B/row) and the
            OWNED value/freq rows ever materialize private — the
            value rows are fancy-indexed straight off the (possibly
            mmap) source view, so the per-window transient is
            ~window/world_size of value bytes, not a full window
            copy."""
            name, kind, keys_v, values_v, freq_ref = task
            keys = np.ascontiguousarray(keys_v, dtype=np.int64)
            mine = owner_of_keys(keys, world_size) == rank
            if kind == "dead":
                return name, kind, keys[mine], None, None, len(keys)
            freq_v, lo, hi = freq_ref
            dim = self._tables[name].dim
            idx = lo + np.flatnonzero(mine)
            values = np.ascontiguousarray(
                np.asarray(values_v).reshape(-1, dim)[idx],
                dtype=np.float32,
            )
            freq = np.ascontiguousarray(
                np.asarray(freq_v)[idx], dtype=np.uint64
            )
            return name, kind, keys[mine], values, freq, len(keys)

        with StagedRestore() as staged:
            for prepared in staged.map_pipelined(
                _prepare, _tasks(), depth=2
            ):
                name, kind, keys, values, freq, n_in = prepared
                chunks += 1
                # chaos hook: a kill here is a worker dying
                # MID-STREAMING-RESHARD — committed storage is
                # untouched (this path only mutates in-process
                # tables), so the replacement replays the identical
                # reshard from the same shards
                _chaos.fire("kv.reshard_chunk", step=chunks)
                table = self._tables[name]
                if kind == "dead":
                    if keys.size:
                        table.delete(keys)
                    continue
                total_rows += n_in
                if keys.size:
                    table.import_(keys, values, freq)
                    rows += int(keys.size)
                    nbytes += (
                        keys.nbytes + values.nbytes + freq.nbytes
                    )
                    if with_digest and not chained:
                        import_sums[name] = (
                            import_sums.get(name, 0)
                            + rows_digest(keys, values, freq)
                        ) % (1 << 64)
                emit_event(
                    "kv_reshard_chunk",
                    table=name, chunk=chunks, rows=int(n_in),
                    owned=int(keys.size), rank=int(rank),
                    step=int(step) if step is not None else None,
                )

        digests: Dict[str, Dict[str, Any]] = {}
        if with_digest:
            win = win_used or 65536
            for name, table in self._tables.items():
                final = 0
                n_rows = 0
                for k, v, f in table.export_chunks(win):
                    final = (
                        final + rows_digest(k, v, f)
                    ) % (1 << 64)
                    n_rows += len(k)
                digests[name] = {
                    "rows": int(n_rows), "sum": f"{final:016x}",
                }
                if not chained and name in import_sums and (
                    final != import_sums[name]
                ):
                    raise RuntimeError(
                        f"streaming reshard of table {name!r} is not "
                        f"exactly-once: additive import digest "
                        f"{import_sums[name]:016x} != final table "
                        f"digest {final:016x} (a chunk was imported "
                        f"twice or a row was lost between windows)"
                    )
        # optimizer scalars from the lowest old rank's LAST link
        scalars = None
        for _r, links in chains.items():
            sc = links[-1].get(SCALARS_KEY)
            if sc:
                scalars = sc
                break
        if scalars:
            for opt in self._optimizers:
                sc = scalars.get(_enc(opt.table.name))
                if sc and hasattr(opt, "load_state_scalars"):
                    opt.load_state_scalars(sc)
        seconds = time.perf_counter() - t0
        _KV_CKPT_SECONDS.observe(seconds, stage="reshard")
        event = dict(
            stage="restore", rows=int(rows), bytes=int(nbytes),
            seconds=round(seconds, 4), tables=len(self._tables),
            resharded=True, from_world=int(from_world),
            world_size=int(world_size), total_rows=int(total_rows),
            tier=tier, streamed=True, chunks=int(chunks),
        )
        if win_used is not None:
            event["window_rows"] = int(win_used)
        if step is not None:
            event["step"] = int(step)
        event["rank"] = int(rank)
        if digests:
            event["digests"] = digests
        emit_event("kv_checkpoint", **event)
        logger.info(
            "streaming kv reshard: %d/%d row(s) owned by rank %d of "
            "world %d (from world %s, %d chunk(s) of %s row(s), "
            "%.3fs, %.1f MB/s)",
            rows, total_rows, rank, world_size, from_world, chunks,
            win_used, seconds,
            (nbytes / 2**20 / seconds) if seconds > 0 else 0.0,
        )
        return {
            "kv_s": round(seconds, 4),
            "kv_rows": int(rows),
            "kv_resharded": True,
            "kv_chunks": int(chunks),
        }

    # -- flat-key helpers (engine's load_sharded path) ----------------------

    @staticmethod
    def split_flat(flat: Dict[str, Any]) -> Tuple[Dict, Dict]:
        """Partition a flat {path: leaf} dict into (kv entries keyed
        RELATIVE to the ``__kv__/`` prefix, the rest)."""
        kv: Dict[str, Any] = {}
        rest: Dict[str, Any] = {}
        for key, val in flat.items():
            if key.startswith(KV_PREFIX):
                kv[key[len(KV_PREFIX):]] = val
            elif key == KV_STATE_KEY:
                # the whole subtree survived as one pickled scalar
                # (nothing array-valued): unwrap it
                if isinstance(val, dict):
                    for k2, v2 in val.items():
                        kv[k2] = v2
            else:
                rest[key] = val
        return kv, rest

    @staticmethod
    def nest_flat(flat: Dict[str, Any]) -> Dict[str, Any]:
        """{"emb/keys": arr, "__scalars__/emb/step": 3} -> nested."""
        root: Dict[str, Any] = {}
        for key, value in flat.items():
            parts = key.split("/")
            node = root
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = value
        return root
