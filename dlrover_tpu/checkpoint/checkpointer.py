"""User-facing flash-checkpoint API.

Reference: ``Checkpointer`` ABC + ``DdpCheckpointer``
(``dlrover/trainer/torch/flash_checkpoint/checkpointer.py:23``,
``ddp.py:25``).  One class covers the JAX cases: replicated pytrees
(DDP parity) and per-process-sharded pytrees (FSDP/GSPMD parity) —
the sharding story is a constructor flag, not a separate engine
hierarchy, because on TPU both are just pytrees of ``jax.Array``.
"""

from enum import Enum
from typing import Any, Optional, Tuple

from dlrover_tpu.checkpoint.engine import CheckpointEngine


class StorageType(Enum):
    MEMORY = 0
    DISK = 1


class Checkpointer:
    """Save/load JAX pytree checkpoints with sub-second step stall.

    Usage::

        ckpt = Checkpointer("/ckpt/dir")
        ckpt.save_checkpoint(step, {"params": params, "opt": opt_state},
                             storage_type=StorageType.DISK)
        step, state = ckpt.load_checkpoint()
    """

    def __init__(
        self,
        checkpoint_dir: str,
        replicated: bool = True,
        deletion_keep_latest: int = 0,
        **engine_kwargs,
    ):
        self.checkpoint_dir = checkpoint_dir
        self._engine = CheckpointEngine(
            checkpoint_dir,
            replicated=replicated,
            deletion_keep_latest=deletion_keep_latest,
            **engine_kwargs,
        )

    def save_checkpoint(
        self,
        step: int,
        state_dict: Any,
        path: str = "",
        storage_type: StorageType = StorageType.DISK,
    ) -> bool:
        if storage_type == StorageType.MEMORY:
            return self._engine.save_to_memory(step, state_dict, path)
        return self._engine.save_to_storage(step, state_dict, path)

    def load_checkpoint(
        self, target_state: Any = None, orbax_dir: str = "",
    ) -> Tuple[Optional[int], Any]:
        """Without ``target_state``: host-array restore (replicated /
        same-topology).  With ``target_state`` (a pytree of sharded
        jax.Arrays): every leaf is re-assembled onto the target's
        shardings — shm, then storage, then the orbax tier at
        ``orbax_dir`` (reference: fsdp_engine re-shard on load)."""
        if target_state is not None:
            return self._engine.load_sharded(
                target_state, orbax_dir=orbax_dir
            )
        return self._engine.load()

    def wait(self, timeout: float = 600.0) -> bool:
        """Block until in-flight async snapshot writes reach shared
        memory (call before process exit so the last save is
        restorable)."""
        return self._engine.wait_async(timeout=timeout)

    def close(self):
        self._engine.close()
