"""User-facing flash-checkpoint API.

Reference: ``Checkpointer`` ABC + ``DdpCheckpointer``
(``dlrover/trainer/torch/flash_checkpoint/checkpointer.py:23``,
``ddp.py:25``).  One class covers the JAX cases: replicated pytrees
(DDP parity) and per-process-sharded pytrees (FSDP/GSPMD parity) —
the sharding story is a constructor flag, not a separate engine
hierarchy, because on TPU both are just pytrees of ``jax.Array``.
"""

from enum import Enum
from typing import Any, Optional, Tuple

from dlrover_tpu.checkpoint.engine import CheckpointEngine


class StorageType(Enum):
    MEMORY = 0
    DISK = 1


class Checkpointer:
    """Save/load JAX pytree checkpoints with sub-second step stall.

    Usage::

        ckpt = Checkpointer("/ckpt/dir")
        ckpt.save_checkpoint(step, {"params": params, "opt": opt_state},
                             storage_type=StorageType.DISK)
        step, state = ckpt.load_checkpoint()
    """

    def __init__(
        self,
        checkpoint_dir: str,
        replicated: bool = True,
        deletion_keep_latest: int = 0,
        **engine_kwargs,
    ):
        self.checkpoint_dir = checkpoint_dir
        self._engine = CheckpointEngine(
            checkpoint_dir,
            replicated=replicated,
            deletion_keep_latest=deletion_keep_latest,
            **engine_kwargs,
        )

    def save_checkpoint(
        self,
        step: int,
        state_dict: Any,
        path: str = "",
        storage_type: StorageType = StorageType.DISK,
    ) -> bool:
        if storage_type == StorageType.MEMORY:
            return self._engine.save_to_memory(step, state_dict, path)
        return self._engine.save_to_storage(step, state_dict, path)

    def load_checkpoint(self) -> Tuple[Optional[int], Any]:
        return self._engine.load()

    def close(self):
        self._engine.close()
