"""User-facing flash-checkpoint API.

Reference: ``Checkpointer`` ABC + ``DdpCheckpointer``
(``dlrover/trainer/torch/flash_checkpoint/checkpointer.py:23``,
``ddp.py:25``).  One class covers the JAX cases: replicated pytrees
(DDP parity) and per-process-sharded pytrees (FSDP/GSPMD parity) —
the sharding story is a constructor flag, not a separate engine
hierarchy, because on TPU both are just pytrees of ``jax.Array``.
"""

import threading
from enum import Enum
from typing import Any, Optional, Tuple

from dlrover_tpu.checkpoint.engine import CheckpointEngine


class RestoreHandle:
    """A restore running on a background thread, so its read/assemble
    stages overlap the caller's own setup (model build, optimizer
    init, jit trace) — the respawn-overlap half of invisible recovery.
    ``result()`` joins and returns ``(step, state)`` exactly as the
    synchronous call would (bit-identical: it IS the same code on
    another thread; the overlap regression test pins this).

    Not a ``concurrent.futures`` future on purpose: executor threads
    are non-daemon, and a restore wedged on a dead storage tier must
    never block process exit in this crash-heavy path."""

    def __init__(self, fn, args=(), kwargs=None):
        self._value: Optional[tuple] = None
        self._exc: Optional[Exception] = None

        def run():
            try:
                self._value = fn(*args, **(kwargs or {}))
            except Exception as e:  # noqa: BLE001 - re-raised
                self._exc = e

        self._thread = threading.Thread(
            target=run, daemon=True, name="restore-async"
        )
        self._thread.start()

    def done(self) -> bool:
        return not self._thread.is_alive()

    def result(self, timeout: Optional[float] = None):
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            raise TimeoutError("restore still running")
        if self._exc is not None:
            raise self._exc
        return self._value


class StorageType(Enum):
    MEMORY = 0
    DISK = 1


class Checkpointer:
    """Save/load JAX pytree checkpoints with sub-second step stall.

    Usage::

        ckpt = Checkpointer("/ckpt/dir")
        ckpt.save_checkpoint(step, {"params": params, "opt": opt_state},
                             storage_type=StorageType.DISK)
        step, state = ckpt.load_checkpoint()
    """

    def __init__(
        self,
        checkpoint_dir: str,
        replicated: bool = True,
        deletion_keep_latest: int = 0,
        orbax_dir: str = "",
        orbax_every: int = 0,
        **engine_kwargs,
    ):
        """``orbax_dir`` + ``orbax_every``: additionally write every
        Nth storage save through the orbax tier — the re-shardable
        durable copy a topology change restores from (reference: the
        DCP/dist-ckpt tier next to flash saves)."""
        self.checkpoint_dir = checkpoint_dir
        self._engine = CheckpointEngine(
            checkpoint_dir,
            replicated=replicated,
            deletion_keep_latest=deletion_keep_latest,
            **engine_kwargs,
        )
        self._orbax_dir = orbax_dir
        self._orbax_every = orbax_every
        self._orbax = None
        self._orbax_waiter = None
        self._orbax_hung = False
        self._orbax_dirty = False
        self._storage_saves = 0

    def _orbax_tier(self):
        if self._orbax is None and self._orbax_dir:
            from dlrover_tpu.checkpoint.orbax_compat import (
                GlobalCheckpointer,
            )

            self._orbax = GlobalCheckpointer(self._orbax_dir)
        return self._orbax

    def register_sparse(self, adapter) -> None:
        """Attach a
        :class:`~dlrover_tpu.checkpoint.sparse.SparseStateAdapter`:
        the registered KvVariable tables (embedding + optimizer
        slots, spill tier included) ride every save under the
        reserved ``__kv__`` key and are imported — or, across a world
        change, hash-resharded from all old ranks' storage shards —
        on every restore."""
        self._engine.register_sparse(adapter)

    def save_checkpoint(
        self,
        step: int,
        state_dict: Any,
        path: str = "",
        storage_type: StorageType = StorageType.DISK,
    ) -> bool:
        if storage_type == StorageType.MEMORY:
            return self._engine.save_to_memory(step, state_dict, path)
        ok = self._engine.save_to_storage(step, state_dict, path)
        # the durable tier is independent of the flash tier: a flash
        # save skipped as busy must not starve the orbax cadence, and
        # the cadence counts SAVES (not raw step numbers, which may
        # never hit the modulo)
        self._storage_saves += 1
        if (
            self._orbax_every
            and (self._storage_saves - 1) % self._orbax_every == 0
            and self._orbax_tier() is not None
        ):
            # async inside orbax; jax.Array immutability makes the
            # concurrent snapshot safe
            self._orbax_tier().save(step, state_dict)
            self._orbax_dirty = True
        return ok

    @property
    def last_restore_phases(self):
        """Stage breakdown of the last restore (``tier``, ``read_s``,
        ``assemble_s``, ``h2d_s``, ``total_s``, ``workers``) — the
        same numbers the ``checkpoint_restore`` event carries."""
        return dict(self._engine.last_restore_phases)

    def load_checkpoint(
        self, target_state: Any = None, orbax_dir: str = "",
    ) -> Tuple[Optional[int], Any]:
        """Without ``target_state``: host-array restore (replicated /
        same-topology).  With ``target_state`` (a pytree of sharded
        jax.Arrays): every leaf is re-assembled onto the target's
        shardings — shm, then storage, then the orbax tier at
        ``orbax_dir`` (reference: fsdp_engine re-shard on load).

        Both paths run the staged restore pipeline (read → assemble →
        h2d overlapped; ``DLROVER_RESTORE_WORKERS`` sizes the pool,
        ``1`` = exact serial path)."""
        if target_state is not None:
            return self._engine.load_sharded(
                target_state, orbax_dir=orbax_dir or self._orbax_dir
            )
        step, state = self._engine.load()
        if step is None and (orbax_dir or self._orbax_dir):
            # shm + flash storage gone (node replacement): the
            # durable tier is the last resort even without a target
            # template; a per-call orbax_dir overrides the configured
            # one (mirrors the target_state branch)
            if orbax_dir and orbax_dir != self._orbax_dir:
                from dlrover_tpu.checkpoint.orbax_compat import (
                    GlobalCheckpointer,
                )

                tier = GlobalCheckpointer(orbax_dir)
                try:
                    return tier.restore()
                finally:
                    tier.close()
            tier = self._orbax_tier()
            if tier is not None:
                return tier.restore()
        return step, state

    def load_checkpoint_async(
        self, target_state: Any = None, orbax_dir: str = "",
    ) -> RestoreHandle:
        """:meth:`load_checkpoint` on a background thread: start it
        FIRST, build the model/optimizer/jitted step — and resolve
        the step through the AOT executable cache
        (``RecoveryProfiler.resolve_step`` with ``restore_busy=not
        handle.done()``) — while the read+assemble stages run, then
        ``handle.result()``; only the (device-bound) tail of the
        restore stays serial with the caller.  One restore at a time:
        do not save or load through this checkpointer until
        ``result()`` returned.

        Note the host-array path (no ``target_state``) performs no
        device transfers at all, so with enough setup work to hide
        behind, the whole restore disappears from the critical path."""
        return RestoreHandle(
            self.load_checkpoint,
            kwargs={
                "target_state": target_state, "orbax_dir": orbax_dir,
            },
        )

    def wait(self, timeout: float = 600.0) -> bool:
        """Block until in-flight async snapshot writes reach shared
        memory AND in-flight orbax tier writes complete (call before
        process exit so the last save is restorable).  The timeout
        bounds the whole call — a hung remote store cannot block a
        preemption grace period."""
        import threading
        import time as _time

        deadline = _time.monotonic() + timeout
        # split the budget only when the durable tier actually has
        # pending work — orbax then needs a real share, not a 50 ms
        # floor probe that would falsely mark a healthy store hung;
        # with nothing pending the shm drain keeps the whole budget
        orbax_pending = self._orbax is not None and (
            self._orbax_dirty or self._orbax_waiter is not None
        )
        engine_budget = (
            max(0.1, timeout * 0.7) if orbax_pending else timeout
        )
        ok = self._engine.wait_async(timeout=engine_budget)
        if orbax_pending:
            # drain any stale waiter first: it entered orbax's wait
            # BEFORE saves issued since, so only a FRESH wait that
            # completes counts as success (a stale thread finishing
            # in a race gap must not)
            stale = self._orbax_waiter
            if stale is not None and stale.is_alive():
                stale.join(
                    timeout=max(0.05, deadline - _time.monotonic())
                )
                if stale.is_alive():
                    self._orbax_hung = True
                    return False
            fresh = threading.Thread(
                target=self._orbax.wait, daemon=True
            )
            fresh.start()
            fresh.join(
                timeout=max(0.05, deadline - _time.monotonic())
            )
            timed_out = fresh.is_alive()
            self._orbax_waiter = fresh if timed_out else None
            self._orbax_hung = timed_out
            self._orbax_dirty = timed_out
            ok = ok and not timed_out
        return ok

    def close(self):
        if self._orbax is not None and not self._orbax_hung:
            # a wait() that already timed out means the store is hung;
            # re-entering the unbounded wait here would blow through
            # the preemption grace period the caller bounded
            self._orbax.wait()
            self._orbax.close()
        self._engine.close()


def restore_to_template(template, restored, device_put: bool = True):
    """Rebuild a restored checkpoint (plain nested dicts — the shm
    format flattens pytrees to string paths) onto ``template``'s tree
    structure: optax tuples/NamedTuples, flax containers, dataclasses
    all come back typed, each leaf ``device_put`` to the template
    leaf's sharding when it has one.

    The reference never needed this (torch state dicts are already
    plain dicts); JAX optimizer states are structured pytrees, so the
    restructure lives here next to the engine.

    Prefer ``load_checkpoint(target_state=...)`` when you hold a
    template with shardings — it additionally re-assembles shards
    after a topology change; this helper covers the replicated
    plain-``load_checkpoint()`` flow.
    """
    import jax

    from dlrover_tpu.checkpoint.shm_handler import _path_str

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    # BATCHED placement: one device_put over all sharded leaves and
    # one over the default-placed ones — a per-leaf asarray+put chain
    # pays one dispatch (and, through a remote device link, one round
    # trip) per leaf, which is the measured ``state_build`` residual
    # of the recovery budget
    put_default: list = []   # (leaf_index, host_value)
    put_sharded: list = []   # (leaf_index, host_value, sharding)
    for path, tleaf in flat:
        node = restored
        for p in path:
            key = _path_str(p)
            if isinstance(node, dict) and key in node:
                node = node[key]
            else:
                raise KeyError(
                    f"checkpoint is missing leaf "
                    f"'{'/'.join(_path_str(q) for q in path)}'"
                )
        val = node
        if device_put and hasattr(tleaf, "sharding"):
            sh = tleaf.sharding
            if sh is None:
                put_default.append((len(leaves), val))
            else:
                put_sharded.append((len(leaves), val, sh))
        leaves.append(val)
    if put_sharded:
        arrs = jax.device_put(
            [v for _, v, _ in put_sharded],
            [s for _, _, s in put_sharded],
        )
        for (i, _, _), arr in zip(put_sharded, arrs):
            leaves[i] = arr
    if put_default:
        arrs = jax.device_put([v for _, v in put_default])
        for (i, _), arr in zip(put_default, arrs):
            leaves[i] = arr
    return jax.tree_util.tree_unflatten(treedef, leaves)
