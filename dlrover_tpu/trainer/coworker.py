"""Coworker data service: CPU data hosts feed accelerator hosts.

Reference: ``atorch/service/coworker_data_service.py:1`` +
``atorch/data/coworker_dataset.py:1`` + the coworker process-group
creation (``atorch/distributed/distributed.py:565``) — CPU pods run
read + collate and stream ready batches over gRPC so accelerator
pods never spend step time on input work.

TPU translation, same transport as the master control plane
(:mod:`dlrover_tpu.common.comm` — framed pickles over TCP with the
restricted unpickler; numpy arrays are allowlisted):

- **data host**: :class:`CoworkerDataService` builds batches in
  worker threads into a bounded ready queue and answers
  ``next_batch`` requests; one service can feed many trainer hosts
  (each request pops the next batch — the dynamic-sharding contract
  of the reference's data service).
- **trainer host**: :class:`CoworkerDataLoader` streams batches over
  a persistent connection with lookahead (the next request is in
  flight while the current batch trains), device_puts them with the
  mesh batch sharding, and reports cumulative ``input_wait_s`` so
  the input-bound fraction of step time is measurable — the same
  contract as :class:`dlrover_tpu.trainer.shm_loader.ShmDataLoader`,
  crossing a host boundary instead of a process one.
"""

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from dlrover_tpu.common.comm import (
    MessageClient,
    MessageServer,
    RequestHandler,
)
from dlrover_tpu.common.log import default_logger as logger


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {
            k: np.stack([np.asarray(s[k]) for s in samples])
            for k in first
        }
    return np.stack([np.asarray(s) for s in samples])


class CoworkerDataService(RequestHandler):
    """Data-host side: build batches ahead of demand, serve them over
    the comm layer.

    ``read_fn(index) -> sample`` and ``collate_fn(samples) -> batch``
    run in ``num_workers`` threads (reads are IO-bound; numpy collate
    releases the GIL for the memcpy-heavy part).  ``port=0`` picks a
    free port — read it back from ``.port``.
    """

    def __init__(
        self,
        read_fn: Callable[[int], Any],
        batch_size: int,
        index_iter,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 2,
        queue_depth: int = 8,
        port: int = 0,
        host: str = "0.0.0.0",
    ):
        self.batch_size = batch_size
        self._read_fn = read_fn
        self._collate = collate_fn or _default_collate
        self._index_iter = iter(index_iter)
        self._index_lock = threading.Lock()
        self._ready: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._stop = threading.Event()
        # the server socket exists before start(): a next_batch
        # arriving in that window must wait, not see end-of-data
        self._started = threading.Event()
        # one failed batch build poisons the service for EVERY
        # consumer: a single queued ('error',) item would reach one
        # consumer while the rest saw a clean end and silently lost
        # the failed batch's samples
        self._error: Optional[str] = None
        self._served = 0
        self._build_s = 0.0
        self._workers = [
            threading.Thread(target=self._build_loop, daemon=True)
            for _ in range(max(1, num_workers))
        ]
        # responses here are whole batches: the default 8192-frame
        # retry cache would pin gigabytes, while too few entries can
        # evict an executed-but-unacked batch before its retry lands
        # (losing those samples); 256 covers many consumers' retry
        # windows at bounded memory
        self._server = MessageServer(
            port, self, host=host, cache_capacity=256
        )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "CoworkerDataService":
        self._server.start()
        for w in self._workers:
            w.start()
        self._started.set()
        return self

    def stop(self):
        self._stop.set()
        # unblock builders stuck on a full ready queue
        try:
            while True:
                self._ready.get_nowait()
        except queue.Empty:
            pass
        self._server.stop()

    @property
    def port(self) -> int:
        return self._server.port

    # -- batch building ----------------------------------------------------

    def _next_indices(self) -> Optional[List[int]]:
        with self._index_lock:
            out = []
            for _ in range(self.batch_size):
                try:
                    out.append(next(self._index_iter))
                except StopIteration:
                    break
            return out or None

    def _build_loop(self):
        while not self._stop.is_set():
            indices = self._next_indices()
            if indices is None:
                return
            t0 = time.perf_counter()
            try:
                batch = self._collate(
                    [self._read_fn(i) for i in indices]
                )
            except Exception as e:  # noqa: BLE001 - ship to trainer
                logger.error("coworker batch build failed: %s", e)
                self._error = repr(e)
                return
            self._build_s += time.perf_counter() - t0
            with self._id_lock:
                batch_id = self._next_id
                self._next_id += 1
            self._put(("batch", batch_id, batch))

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._ready.put(item, timeout=0.5)
                return
            except queue.Full:
                continue

    # -- RequestHandler ----------------------------------------------------

    def report(self, node_id, node_type, message) -> bool:
        return True

    def get(self, node_id, node_type, message):
        if message == "stats":
            return self.stats()
        if message != "next_batch":
            raise ValueError(f"unknown coworker request {message!r}")
        while True:
            if self._error is not None:
                return ("error", self._error)
            if self._stop.is_set():
                # stop() without (or before) start(): release any
                # waiting consumer instead of polling forever
                return ("end",)
            try:
                # short poll: the END answer must not cost a long
                # timeout cycle (it lands in the consumer's
                # input-wait accounting)
                item = self._ready.get(timeout=0.05)
            except queue.Empty:
                if not self._started.is_set():
                    # start() has not run yet: the workers exist but
                    # none has started — is_alive() would read as
                    # end-of-data
                    continue
                # end-of-data only when no builder can still
                # produce a batch (builders exit only after draining
                # the index iterator; one may still hold an in-flight
                # batch, so every builder thread must be gone)
                alive = any(w.is_alive() for w in self._workers)
                if (not alive and self._ready.empty()
                        and self._error is None):
                    return ("end",)
                continue
            self._served += 1 if item[0] == "batch" else 0
            return item

    def stats(self) -> Dict[str, float]:
        return {
            "served": self._served,
            "build_s": round(self._build_s, 4),
            "ready_depth": self._ready.qsize(),
        }


class CoworkerDataLoader:
    """Trainer-host side: stream batches from a coworker service.

    A fetcher thread keeps ``prefetch`` requests ahead of the
    consumer (the network round trip and the service-side build
    overlap device compute); batches are device_put with the mesh
    batch sharding and recycled double-buffered like the shm loader.
    """

    def __init__(
        self,
        addr: str,
        mesh=None,
        prefetch: int = 2,
        node_id: int = 0,
        timeout: float = 60.0,
    ):
        self._addr = addr
        self._mesh = mesh
        self._prefetch = max(1, prefetch)
        self._client = MessageClient(
            addr, node_id=node_id, node_type="coworker_consumer",
            timeout=timeout,
        )
        self._q: "queue.Queue" = queue.Queue(
            maxsize=self._prefetch
        )
        self._fetcher: Optional[threading.Thread] = None
        self._input_wait_s = 0.0
        self._batches = 0

    def _fetch_loop(self):
        while True:
            try:
                item = self._client.get("next_batch")
            except Exception as e:  # noqa: BLE001
                item = ("error", repr(e))
            self._q.put(item)
            if item[0] != "batch":
                return

    def _place(self, batch):
        import jax

        if self._mesh is None:
            return batch
        from jax.sharding import NamedSharding

        from dlrover_tpu.parallel.sharding import batch_spec

        return jax.device_put(
            batch, NamedSharding(self._mesh, batch_spec())
        )

    def __iter__(self):
        if self._fetcher is not None:
            # a second iteration would race the first fetcher on the
            # shared queue and replay its stale prefetched batches —
            # the loader is one stream; make a new one per epoch
            raise RuntimeError(
                "CoworkerDataLoader is single-use: create a new "
                "loader (new connection) for another pass"
            )
        self._fetcher = threading.Thread(
            target=self._fetch_loop, daemon=True
        )
        self._fetcher.start()
        while True:
            t0 = time.perf_counter()
            item = self._q.get()
            self._input_wait_s += time.perf_counter() - t0
            kind = item[0]
            if kind == "end":
                return
            if kind == "error":
                raise RuntimeError(
                    f"coworker data service failed: {item[1]}"
                )
            _, batch_id, batch = item
            self._batches += 1
            yield self._place(batch)

    def stats(self) -> Dict[str, float]:
        """Cumulative input-side accounting (the loader contract the
        bench's input-bound fraction reads)."""
        return {
            "input_wait_s": round(self._input_wait_s, 4),
            "batches": self._batches,
        }

    def service_stats(self) -> Dict[str, float]:
        return self._client.get("stats")
