"""Platform worker starter: env contract -> tpurun invocation.

Reference: ``dlrover/trainer/platform/starter.py:94`` +
``worker/tf_kubernetes_worker.py`` / ``tf_ray_worker.py``: scheduled
containers/actors boot through one entry that reads the platform's
env contract and launches the elastic agent.  The TPU analog turns
the ``NodeEnv`` variables the scaler injected into a ``tpurun``
command line, so a pod/actor spec only needs
``python -m dlrover_tpu.trainer.starter -- <train.py> [args...]``.
"""

import argparse
import os
import sys
from typing import List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger


def build_run_argv(
    script_and_args: List[str],
    env: Optional[dict] = None,
) -> List[str]:
    """Env contract -> tpurun argv (testable seam)."""
    env = env if env is not None else dict(os.environ)
    argv: List[str] = []
    node_num = env.get(NodeEnv.NODE_NUM, "1")
    min_nodes = env.get("DLROVER_MIN_NODES", node_num)
    max_nodes = env.get("DLROVER_MAX_NODES", node_num)
    argv += ["--nnodes", f"{min_nodes}:{max_nodes}"]
    argv += [
        "--nproc_per_node",
        env.get(NodeEnv.LOCAL_WORLD_SIZE, "1"),
    ]
    node_rank = env.get(NodeEnv.NODE_RANK, "")
    if node_rank:
        argv += ["--node_rank", node_rank]
    if env.get("DLROVER_NETWORK_CHECK", "") in ("1", "true"):
        argv += ["--network-check"]
    argv += script_and_args
    return argv


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="dlrover_tpu platform worker starter"
    )
    parser.add_argument("script_and_args", nargs=argparse.REMAINDER)
    ns = parser.parse_args(argv)
    rest = list(ns.script_and_args)
    if rest and rest[0] == "--":  # only the leading separator
        rest = rest[1:]
    if not rest:
        parser.error("training script required after --")
    master_addr = os.getenv(NodeEnv.MASTER_ADDR, "")
    logger.info(
        "starter: node %s of job %s (master %s)",
        os.getenv(NodeEnv.NODE_RANK, "?"),
        os.getenv(NodeEnv.JOB_NAME, "?"),
        master_addr or "<local>",
    )
    from dlrover_tpu.run import main as tpurun_main

    return tpurun_main(build_run_argv(rest))


if __name__ == "__main__":
    sys.exit(main())
