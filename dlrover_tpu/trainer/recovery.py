"""Recovery-phase profiler: the death→first-step budget, measured.

The invisible-recovery target (``elastic_recovery_s ≤ 2.0``) is only
reachable — and only *provable* — with the serial chain broken into
named phases, each measured where it actually runs:

- **spawn**: the agent witnesses the death → this process exists
  (kernel start time from ``/proc/self/stat``, so the measurement
  covers the fork/exec itself, not just userland);
- **import**: process start → the trainer constructed this profiler
  (interpreter + jax/flax imports — near zero under a warm fork);
- **restore**: the checkpoint restore (the engine's measured
  ``total_s``);
- **aot**: resolving the step through the AOT executable cache
  (:mod:`dlrover_tpu.common.aot_cache`) — on a HIT this is the
  deserialize+link time and the retrace phase collapses to zero; on
  a MISS it is the entry write (so incarnation N+1 hits);
- **retrace**: the first post-restore step's trace+compile, with the
  persistent compilation cache's hit/miss witnessed from the cache
  directory (:mod:`dlrover_tpu.common.compile_cache`);
- **first_step**: the remainder until the first step completes.

Each phase lands as a ``recovery_phase`` event + a
``dlrover_recovery_phase_seconds{phase}`` histogram sample, so the
chaos invariants, the timeline's recovery breakdown and bench.py all
read the same numbers.  The agent exports ``DLROVER_RECOVERY_T0``
(the wall clock at which it observed the death) into every respawned
worker's env; without it the profiler still measures import/restore/
retrace relative to process start (a first incarnation, or a cold
launch).
"""

import os
import threading
import time
from typing import Callable, Dict, Optional

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.compile_cache import (
    cache_entries,
    enable_persistent_cache,
    job_cache_dir,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

RECOVERY_T0_ENV = "DLROVER_RECOVERY_T0"

_REG = get_registry()
_PHASE_SECONDS = _REG.histogram(
    "dlrover_recovery_phase_seconds",
    "Measured death->first-step recovery budget by phase "
    "(spawn / import / restore / aot / retrace / first_step)",
)


def _proc_start_epoch() -> Optional[float]:
    """Absolute wall-clock time this process started: kernel start
    ticks (``/proc/self/stat`` field 22) against the boot epoch from
    ``/proc/uptime`` — survives exec, unlike any userland timestamp."""
    fields = env_utils.proc_stat_fields(os.getpid())
    if fields is None:
        return None
    try:
        ticks = int(fields[19])
        hz = float(os.sysconf("SC_CLK_TCK"))
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        boot_epoch = time.time() - uptime
        return boot_epoch + ticks / hz
    except (IndexError, ValueError, OSError):
        return None


class _Phase:
    __slots__ = ("_profiler", "_name", "_t0")

    def __init__(self, profiler: "RecoveryProfiler", name: str):
        self._profiler = profiler
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._profiler.record(
            self._name, time.perf_counter() - self._t0
        )
        return False


class RecoveryProfiler:
    """Construct RIGHT AFTER the heavy imports; the constructor books
    the spawn and import phases and activates the job's persistent
    compile cache in-process (covering entrypoints whose jax imported
    before the agent's env reached them)."""

    def __init__(
        self,
        restart_count: Optional[int] = None,
        node_rank: Optional[int] = None,
    ):
        self.restart_count = (
            restart_count if restart_count is not None
            else env_utils.get_restart_count()
        )
        self.node_rank = (
            node_rank if node_rank is not None
            else env_utils.get_node_rank()
        )
        self.phases: Dict[str, float] = {}
        self.cache_hit: Optional[bool] = None
        self.aot_hit: Optional[bool] = None
        self.cache_dir = enable_persistent_cache() or job_cache_dir()
        try:
            self.t0 = float(os.getenv(RECOVERY_T0_ENV, "") or 0.0)
        except ValueError:
            self.t0 = 0.0
        now = time.time()
        start = _proc_start_epoch()
        self._proc_start = start if start is not None else now
        if self.t0 > 0 and self._proc_start >= self.t0:
            self.record("spawn", self._proc_start - self.t0)
        self.record("import", max(0.0, now - self._proc_start))
        self._first_step_t0 = time.perf_counter()

    # -- recording ---------------------------------------------------------

    def record(self, phase: str, seconds: float):
        seconds = max(0.0, float(seconds))
        self.phases[phase] = round(seconds, 4)
        _PHASE_SECONDS.observe(seconds, phase=phase)
        emit_event(
            "recovery_phase",
            phase=phase,
            seconds=round(seconds, 4),
            restart_count=self.restart_count,
            node_rank=self.node_rank,
        )

    def phase(self, name: str) -> _Phase:
        """``with profiler.phase("restore"): step, state = load()``"""
        return _Phase(self, name)

    def record_restore(self, restore_phases: Dict) -> None:
        """Book the restore phase from the engine's measured
        breakdown (``Checkpointer.last_restore_phases``)."""
        total = restore_phases.get("total_s")
        if isinstance(total, (int, float)) and total > 0:
            self.record("restore", float(total))

    def resolve_step(
        self,
        fn,
        example_args,
        label: str = "train_step",
        cache_dir: Optional[str] = None,
        restore_busy: Optional[bool] = None,
    ):
        """Resolve the jitted step through the AOT executable cache,
        booking the budget phases and emitting the witnesses::

            step = prof.resolve_step(step_fn, (abstract_state, batch))
            ...
            state, metrics = step(state, batch)   # no trace on a HIT

        HIT: the ``aot`` phase is the deserialize+link time and
        ``retrace`` is recorded as 0 — tracing left the critical path.
        MISS: the lower+compile inside the resolve IS the measured
        retrace (recorded exactly as :meth:`measured_retrace` would),
        and the entry is written so incarnation N+1 hits.  Off or
        failed: returns a wrapper whose first call runs under
        :meth:`measured_retrace` — byte-for-byte today's behavior.

        ``restore_busy`` (pass ``lambda: not load_handle.done()``; a
        plain bool works too) stamps whether the async restore was
        still reading when this resolve finished — the overlap
        witness on the ``aot_cache`` event.  Call this BEFORE joining
        the restore to actually overlap."""
        from dlrover_tpu.common import aot_cache as _aot

        entries_before = cache_entries(self.cache_dir)
        t0 = time.perf_counter()
        res = _aot.resolve_step(
            fn, example_args, label=label, cache_dir=cache_dir
        )
        wall = time.perf_counter() - t0
        return self._book_resolution(
            res, wall, entries_before, restore_busy
        )

    def resolve_step_async(
        self,
        fn,
        args_builder: Callable,
        label: str = "train_step",
        cache_dir: Optional[str] = None,
        restore_busy=None,
    ) -> Callable:
        """:meth:`resolve_step` on a daemon thread, so the
        deserialize (HIT) or trace+compile (MISS) — and the abstract
        example build itself — overlap the async restore read AND the
        caller's own model/optimizer/state construction::

            join = prof.resolve_step_async(
                step_fn, lambda: (abstract_state, abstract_batch),
                restore_busy=lambda: not load_handle.done())
            ... build model, join the restore, build the state ...
            step = join()   # waits only for what did not overlap

        The ``aot`` budget phase books the JOIN WAIT — the seconds
        the critical path actually stalled, which is what the
        sub-second cycle is made of — while the ``aot_cache`` event
        keeps the thread-measured ``load_s``/``trace_s``/``save_s``
        so the true deserialize cost stays visible."""
        from dlrover_tpu.common import aot_cache as _aot

        entries_before = cache_entries(self.cache_dir)
        holder: Dict[str, object] = {}
        t0 = time.perf_counter()

        def run():
            try:
                # the builder is passed THROUGH (not called): on the
                # warm fast path the label index resolves without
                # ever building the abstract examples
                holder["res"] = _aot.resolve_step(
                    fn, args_builder, label=label, cache_dir=cache_dir
                )
            except Exception as e:  # noqa: BLE001 - never crash
                holder["res"] = _aot.Resolution(
                    fn=fn, source="off", deferred=True,
                    reason=f"async resolve failed: {e}",
                )

        thread = threading.Thread(
            target=run, daemon=True, name="aot-resolve"
        )
        thread.start()

        def join(timeout: Optional[float] = None):
            w0 = time.perf_counter()
            thread.join(timeout=timeout)
            wait = time.perf_counter() - w0
            res = holder.get("res")
            if res is None:  # timeout: trace inline, never wedge
                res = _aot.Resolution(
                    fn=fn, source="off", deferred=True,
                    reason="async resolve timed out",
                )
            wall = time.perf_counter() - t0
            return self._book_resolution(
                res, wall, entries_before, restore_busy,
                aot_phase_s=wait,
            )

        return join

    def _book_resolution(
        self,
        res,
        wall: float,
        entries_before: int,
        restore_busy=None,
        aot_phase_s: Optional[float] = None,
    ):
        """Book an :class:`aot_cache.Resolution` into the budget
        phases and emit the ``aot_cache`` + ``compile_cache``
        witnesses; returns the callable the training loop should use.
        ``aot_phase_s`` overrides the booked ``aot`` phase (the async
        path passes the join wait — the critical-path cost — while
        the event keeps the thread-measured times)."""
        from dlrover_tpu.common import aot_cache as _aot

        aot_n = _aot.aot_entries(res.dir) if res.dir else 0
        event = {
            "hit": res.hit,
            # "resolution", not "source": the event envelope's
            # source field is the emitting process's identity
            "resolution": res.source,
            "key": res.key,
            "dir": res.dir,
            "wrote": res.wrote,
            "preloaded": res.preloaded,
            "seconds": round(wall, 4),
            "load_s": round(res.load_s, 4),
            "trace_s": round(res.trace_s, 4),
            "save_s": round(res.save_s, 4),
            "entries": aot_n,
            "restart_count": self.restart_count,
            "node_rank": self.node_rank,
        }
        if aot_phase_s is not None:
            event["wait_s"] = round(aot_phase_s, 4)
        for k, v in res.extra.items():
            event[k] = round(v, 4) if isinstance(v, float) else v
        if res.reason:
            event["reason"] = res.reason
        if restore_busy is not None:
            busy = restore_busy() if callable(restore_busy) else (
                restore_busy
            )
            event["overlapped_restore"] = bool(busy)
        if res.source == "aot":
            self.aot_hit = True
            self.cache_hit = True
            self.record(
                "aot",
                res.load_s if aot_phase_s is None else aot_phase_s,
            )
            # no tracing happened anywhere: the retrace phase the
            # invariants/budget sum over is genuinely zero
            self.record("retrace", 0.0)
            emit_event("aot_cache", **event)
            self._emit_compile_cache(
                hit=True, status="aot-hit", retrace_s=0.0,
                entries_before=entries_before,
                entries_after=cache_entries(self.cache_dir),
                aot_entries=aot_n,
            )
            return res.fn
        self.aot_hit = False
        if res.source == "trace" and not res.deferred:
            # the eager lower+compile inside the resolve IS the
            # measured retrace; the entry write rides the aot phase
            self.record("retrace", res.trace_s)
            self.record("aot", res.load_s + res.save_s)
            entries_after = cache_entries(self.cache_dir)
            hit = entries_before > 0 and entries_after <= entries_before
            self.cache_hit = hit
            emit_event("aot_cache", **event)
            self._emit_compile_cache(
                hit=hit,
                status="xla-cache-hit" if hit else "cold",
                retrace_s=res.trace_s,
                entries_before=entries_before,
                entries_after=entries_after,
                aot_entries=aot_n,
            )
            return res.fn
        # off / failed resolve: keep today's semantics — the first
        # call traces under the measured_retrace bracket (still books
        # the failed load attempt so the budget stays complete)
        self.record(
            "aot",
            res.load_s if aot_phase_s is None else aot_phase_s,
        )
        emit_event("aot_cache", **event)
        inner = res.fn
        done = [False]
        profiler = self

        def first_call_measured(*args, **kwargs):
            if done[0]:
                return inner(*args, **kwargs)
            done[0] = True
            with profiler.measured_retrace() as r:
                out = inner(*args, **kwargs)
                r.block(out)
            return out

        return first_call_measured

    def _emit_compile_cache(
        self, hit, status, retrace_s, entries_before, entries_after,
        aot_entries,
    ):
        emit_event(
            "compile_cache",
            hit=hit,
            status=status,
            entries_before=entries_before,
            entries_after=entries_after,
            aot_entries=aot_entries,
            retrace_s=round(retrace_s, 4),
            dir=self.cache_dir,
            restart_count=self.restart_count,
            node_rank=self.node_rank,
        )

    def measured_retrace(self) -> "_Retrace":
        """Bracket the FIRST post-restore step::

            with profiler.measured_retrace() as r:
                state, metrics = step_fn(state, batch)
                r.block(metrics)

        The block's wall time is the retrace phase; the cache
        directory's entry count before/after witnesses the compile-
        cache hit (no new ``*-cache`` entries over a warm dir = HIT),
        emitted as a ``compile_cache`` event.  ``block`` brackets
        ``block_until_ready`` so async dispatch cannot shrink the
        measurement."""
        return _Retrace(self)

    def record_first_step(self):
        """Close the budget: remainder since the last recorded phase
        boundary (profiler construction → now, minus restore+retrace,
        which were measured inside it)."""
        elapsed = time.perf_counter() - self._first_step_t0
        inner = sum(
            self.phases.get(p, 0.0)
            for p in ("restore", "retrace", "aot")
        )
        self.record("first_step", max(0.0, elapsed - inner))
        if self.t0 > 0:
            total = time.time() - self.t0
            logger.info(
                "recovery budget (restart %s): %.2fs total — %s",
                self.restart_count, total, self.phases,
            )


class _Retrace:
    def __init__(self, profiler: RecoveryProfiler):
        self._p = profiler
        self._blocked = None

    def block(self, x):
        """Remember the step's output so ``__exit__`` can wait on it
        (retrace_s must include the compile's execution barrier)."""
        self._blocked = x
        return x

    def __enter__(self):
        self._before = cache_entries(self._p.cache_dir)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None:
            return False
        if self._blocked is not None:
            try:
                import jax

                jax.block_until_ready(self._blocked)
            except Exception:  # noqa: BLE001 - non-jax outputs
                pass
        retrace_s = time.perf_counter() - self._t0
        after = cache_entries(self._p.cache_dir)
        hit = self._before > 0 and after <= self._before
        self._p.cache_hit = hit
        self._p.record("retrace", retrace_s)
        from dlrover_tpu.common.aot_cache import aot_entries

        self._p._emit_compile_cache(
            hit=hit,
            status="xla-cache-hit" if hit else "cold",
            retrace_s=retrace_s,
            entries_before=self._before,
            entries_after=after,
            aot_entries=aot_entries(),
        )
        return False
