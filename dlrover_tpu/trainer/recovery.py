"""Recovery-phase profiler: the death→first-step budget, measured.

The invisible-recovery target (``elastic_recovery_s ≤ 2.0``) is only
reachable — and only *provable* — with the serial chain broken into
named phases, each measured where it actually runs:

- **spawn**: the agent witnesses the death → this process exists
  (kernel start time from ``/proc/self/stat``, so the measurement
  covers the fork/exec itself, not just userland);
- **import**: process start → the trainer constructed this profiler
  (interpreter + jax/flax imports — near zero under a warm fork);
- **restore**: the checkpoint restore (the engine's measured
  ``total_s``);
- **retrace**: the first post-restore step's trace+compile, with the
  persistent compilation cache's hit/miss witnessed from the cache
  directory (:mod:`dlrover_tpu.common.compile_cache`);
- **first_step**: the remainder until the first step completes.

Each phase lands as a ``recovery_phase`` event + a
``dlrover_recovery_phase_seconds{phase}`` histogram sample, so the
chaos invariants, the timeline's recovery breakdown and bench.py all
read the same numbers.  The agent exports ``DLROVER_RECOVERY_T0``
(the wall clock at which it observed the death) into every respawned
worker's env; without it the profiler still measures import/restore/
retrace relative to process start (a first incarnation, or a cold
launch).
"""

import os
import time
from typing import Dict, Optional

from dlrover_tpu.common import env_utils
from dlrover_tpu.common.compile_cache import (
    cache_entries,
    enable_persistent_cache,
    job_cache_dir,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

RECOVERY_T0_ENV = "DLROVER_RECOVERY_T0"

_REG = get_registry()
_PHASE_SECONDS = _REG.histogram(
    "dlrover_recovery_phase_seconds",
    "Measured death->first-step recovery budget by phase "
    "(spawn / import / restore / retrace / first_step)",
)


def _proc_start_epoch() -> Optional[float]:
    """Absolute wall-clock time this process started: kernel start
    ticks (``/proc/self/stat`` field 22) against the boot epoch from
    ``/proc/uptime`` — survives exec, unlike any userland timestamp."""
    fields = env_utils.proc_stat_fields(os.getpid())
    if fields is None:
        return None
    try:
        ticks = int(fields[19])
        hz = float(os.sysconf("SC_CLK_TCK"))
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        boot_epoch = time.time() - uptime
        return boot_epoch + ticks / hz
    except (IndexError, ValueError, OSError):
        return None


class _Phase:
    __slots__ = ("_profiler", "_name", "_t0")

    def __init__(self, profiler: "RecoveryProfiler", name: str):
        self._profiler = profiler
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._profiler.record(
            self._name, time.perf_counter() - self._t0
        )
        return False


class RecoveryProfiler:
    """Construct RIGHT AFTER the heavy imports; the constructor books
    the spawn and import phases and activates the job's persistent
    compile cache in-process (covering entrypoints whose jax imported
    before the agent's env reached them)."""

    def __init__(
        self,
        restart_count: Optional[int] = None,
        node_rank: Optional[int] = None,
    ):
        self.restart_count = (
            restart_count if restart_count is not None
            else env_utils.get_restart_count()
        )
        self.node_rank = (
            node_rank if node_rank is not None
            else env_utils.get_node_rank()
        )
        self.phases: Dict[str, float] = {}
        self.cache_hit: Optional[bool] = None
        self.cache_dir = enable_persistent_cache() or job_cache_dir()
        try:
            self.t0 = float(os.getenv(RECOVERY_T0_ENV, "") or 0.0)
        except ValueError:
            self.t0 = 0.0
        now = time.time()
        start = _proc_start_epoch()
        self._proc_start = start if start is not None else now
        if self.t0 > 0 and self._proc_start >= self.t0:
            self.record("spawn", self._proc_start - self.t0)
        self.record("import", max(0.0, now - self._proc_start))
        self._first_step_t0 = time.perf_counter()

    # -- recording ---------------------------------------------------------

    def record(self, phase: str, seconds: float):
        seconds = max(0.0, float(seconds))
        self.phases[phase] = round(seconds, 4)
        _PHASE_SECONDS.observe(seconds, phase=phase)
        emit_event(
            "recovery_phase",
            phase=phase,
            seconds=round(seconds, 4),
            restart_count=self.restart_count,
            node_rank=self.node_rank,
        )

    def phase(self, name: str) -> _Phase:
        """``with profiler.phase("restore"): step, state = load()``"""
        return _Phase(self, name)

    def record_restore(self, restore_phases: Dict) -> None:
        """Book the restore phase from the engine's measured
        breakdown (``Checkpointer.last_restore_phases``)."""
        total = restore_phases.get("total_s")
        if isinstance(total, (int, float)) and total > 0:
            self.record("restore", float(total))

    def measured_retrace(self) -> "_Retrace":
        """Bracket the FIRST post-restore step::

            with profiler.measured_retrace() as r:
                state, metrics = step_fn(state, batch)
                r.block(metrics)

        The block's wall time is the retrace phase; the cache
        directory's entry count before/after witnesses the compile-
        cache hit (no new ``*-cache`` entries over a warm dir = HIT),
        emitted as a ``compile_cache`` event.  ``block`` brackets
        ``block_until_ready`` so async dispatch cannot shrink the
        measurement."""
        return _Retrace(self)

    def record_first_step(self):
        """Close the budget: remainder since the last recorded phase
        boundary (profiler construction → now, minus restore+retrace,
        which were measured inside it)."""
        elapsed = time.perf_counter() - self._first_step_t0
        inner = sum(
            self.phases.get(p, 0.0) for p in ("restore", "retrace")
        )
        self.record("first_step", max(0.0, elapsed - inner))
        if self.t0 > 0:
            total = time.time() - self.t0
            logger.info(
                "recovery budget (restart %s): %.2fs total — %s",
                self.restart_count, total, self.phases,
            )


class _Retrace:
    def __init__(self, profiler: RecoveryProfiler):
        self._p = profiler
        self._blocked = None

    def block(self, x):
        """Remember the step's output so ``__exit__`` can wait on it
        (retrace_s must include the compile's execution barrier)."""
        self._blocked = x
        return x

    def __enter__(self):
        self._before = cache_entries(self._p.cache_dir)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None:
            return False
        if self._blocked is not None:
            try:
                import jax

                jax.block_until_ready(self._blocked)
            except Exception:  # noqa: BLE001 - non-jax outputs
                pass
        retrace_s = time.perf_counter() - self._t0
        after = cache_entries(self._p.cache_dir)
        hit = self._before > 0 and after <= self._before
        self._p.cache_hit = hit
        self._p.record("retrace", retrace_s)
        emit_event(
            "compile_cache",
            hit=hit,
            entries_before=self._before,
            entries_after=after,
            retrace_s=round(retrace_s, 4),
            dir=self._p.cache_dir,
            restart_count=self._p.restart_count,
            node_rank=self._p.node_rank,
        )
        return False
