"""Elastic distributed sampler with mid-epoch checkpoint/restore.

Reference: ``ElasticDistributedSampler``
(``dlrover/trainer/torch/elastic/sampler.py:25``, ``state_dict:118``):
a rank-strided sampler whose ``state_dict`` records the epoch and
consumed batches so a restarted (possibly re-sized) job resumes from
the same position — when the world size changes, the completed sample
count is preserved and the stride changes.
"""

from typing import Dict, Iterator, List, Optional

import numpy as np


class ElasticDistributedSampler:
    def __init__(
        self,
        dataset_size: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if rank >= num_replicas:
            raise ValueError(
                f"rank {rank} >= num_replicas {num_replicas}"
            )
        self.dataset_size = dataset_size
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        # samples this rank has already consumed within the epoch
        self.completed_num = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.completed_num = 0

    def _epoch_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            return rng.permutation(self.dataset_size)
        return np.arange(self.dataset_size)

    def __iter__(self) -> Iterator[int]:
        indices = self._epoch_indices()
        # global offset: completed_num counts per-rank samples, so the
        # global restart position is completed_num * num_replicas
        start = self.completed_num * self.num_replicas
        for i in range(start + self.rank, len(indices), self.num_replicas):
            self.completed_num += 1
            yield int(indices[i])

    def __len__(self) -> int:
        if self.drop_last:
            return self.dataset_size // self.num_replicas
        return (
            self.dataset_size + self.num_replicas - 1
        ) // self.num_replicas

    # -- checkpoint ---------------------------------------------------------

    def state_dict(self) -> Dict[str, int]:
        """Reference: sampler.py:118 — records global progress so a
        different world size can resume."""
        return {
            "epoch": self.epoch,
            "completed_num": self.completed_num * self.num_replicas,
        }

    def load_state_dict(self, state: Dict[str, int]):
        self.epoch = int(state.get("epoch", 0))
        global_completed = int(state.get("completed_num", 0))
        self.completed_num = global_completed // self.num_replicas
