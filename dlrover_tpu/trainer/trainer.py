"""High-level Trainer: auto-accelerate + flash checkpoint + elastic
data + metrics in one loop.

Reference: ``AtorchTrainer`` (``atorch/trainer/atorch_trainer.py:136``)
— a HuggingFace-Trainer-compatible loop built on ``auto_accelerate``
with async flash checkpointing and loss-spike detection
(``atorch/utils/loss_spike_utils.py``).  The TPU loop drives the
compiled sharded train step; saves are flash (shm now, storage async);
resume restores params and the trainer/step counters.
"""

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu.accel import Strategy, auto_accelerate
from dlrover_tpu.checkpoint.checkpointer import Checkpointer, StorageType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry.events import emit_event, set_event_source
from dlrover_tpu.telemetry.metrics import get_registry
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer, TrainState

_REG = get_registry()
_STEP_SECONDS = _REG.histogram(
    "dlrover_train_step_seconds",
    "Wall time of one (dispatch+sync) training step",
)
_LOSS_GAUGE = _REG.gauge(
    "dlrover_train_loss", "Latest training loss"
)
_LOSS_SPIKE_TOTAL = _REG.counter(
    "dlrover_loss_spike_total", "Loss spikes above the EMA threshold"
)


@dataclass
class TrainingArguments:
    """Reference: ``AtorchArguments`` (atorch/trainer/atorch_args.py)."""

    output_dir: str = "/tmp/dlrover_tpu_out"
    max_steps: int = 100
    global_batch_size: int = 8
    micro_batch_size: int = 8
    learning_rate: float = 1e-3
    logging_steps: int = 10
    save_steps: int = 50
    save_storage_steps: int = 0  # 0 = same as save_steps
    eval_steps: int = 0          # 0 = no periodic eval
    strategy: Optional[Strategy] = None
    dry_run_candidates: bool = False
    resume_from_checkpoint: bool = True
    # loss-spike detection (reference: loss_spike_utils)
    loss_spike_factor: float = 3.0
    loss_ema_beta: float = 0.98
    seed: int = 0


class Trainer:
    def __init__(
        self,
        model,
        args: TrainingArguments,
        train_data: Iterable,
        loss_fn: Callable,
        optim_factory: Optional[Callable] = None,
        eval_data: Optional[Iterable] = None,
    ):
        self.model = model
        self.args = args
        self.train_data = train_data
        self.eval_data = eval_data
        self.loss_fn = loss_fn
        self.optim_factory = optim_factory or self._default_optim
        self._accel = None
        self._checkpointer: Optional[Checkpointer] = None
        self.loss_spikes: List[Dict[str, float]] = []
        self._loss_ema: Optional[float] = None

    def _default_optim(self):
        import optax

        return optax.adamw(self.args.learning_rate)

    # -- build -------------------------------------------------------------

    def _build(self, sample_batch):
        self._accel = auto_accelerate(
            self.model,
            self.optim_factory,
            self.loss_fn,
            sample_batch,
            strategy=self.args.strategy,
            dry_run_candidates=self.args.dry_run_candidates,
            grad_accum=max(
                1,
                self.args.global_batch_size
                // self.args.micro_batch_size,
            )
            if self.args.global_batch_size
            > self.args.micro_batch_size
            else 1,
        )
        self._checkpointer = Checkpointer(self.args.output_dir)
        self._elastic = ElasticTrainer(
            global_batch_size=self.args.global_batch_size,
            micro_batch_size=self.args.micro_batch_size,
            dp_size=1,
        )

    # -- checkpoint --------------------------------------------------------

    def _try_resume(self) -> int:
        if not self.args.resume_from_checkpoint:
            return 0
        step, restored = self._checkpointer.load_checkpoint()
        if step is None:
            return 0
        params = jax.tree.map(jnp.asarray, restored["params"])
        optimizer = self.optim_factory()
        state = TrainState.create(params, optimizer)
        state = TrainState(
            params=state.params, opt_state=state.opt_state,
            step=jnp.asarray(step, jnp.int32),
        )
        self._accel.state = jax.device_put(
            state, jax.tree.map(lambda x: x.sharding, self._accel.state)
        )
        logger.info("resumed training from step %s", step)
        return int(step)

    def _save(self, step: int, to_storage: bool):
        state = self._accel.state
        self._checkpointer.save_checkpoint(
            step,
            {
                "params": state.params,
                "trainer": self._elastic.state_dict(),
            },
            storage_type=(
                StorageType.DISK if to_storage else StorageType.MEMORY
            ),
        )

    # -- loss spike --------------------------------------------------------

    def _check_loss_spike(self, step: int, loss: float):
        if self._loss_ema is None:
            self._loss_ema = loss
            return
        if loss > self.args.loss_spike_factor * self._loss_ema:
            logger.warning(
                "loss spike at step %s: %.4f (ema %.4f)",
                step, loss, self._loss_ema,
            )
            self.loss_spikes.append({"step": step, "loss": loss})
            _LOSS_SPIKE_TOTAL.inc()
            emit_event(
                "loss_spike", step=step, loss=loss,
                ema=round(self._loss_ema, 6),
                factor=self.args.loss_spike_factor,
            )
        beta = self.args.loss_ema_beta
        self._loss_ema = beta * self._loss_ema + (1 - beta) * loss

    # -- loops -------------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        set_event_source("trainer")
        data_iter = iter(self.train_data)
        first = next(data_iter)
        self._build(first)
        start_step = self._try_resume()
        self._elastic.global_step = start_step

        step = start_step
        metrics_out: Dict[str, float] = {}
        batch = first
        loss = float("nan")
        save_storage_steps = (
            self.args.save_storage_steps or self.args.save_steps
        )
        while step < self.args.max_steps:
            step_start = time.perf_counter()
            # full phase breakdown for the diagnosis layer: the
            # built-in loop previously profiled nothing, so a
            # data-starved vs h2d-bound vs compute-bound recipe was
            # indistinguishable from the step_phases event alone
            with self._elastic.profile("h2d"):
                placed = self._accel.place_batch(batch)
            with self._elastic.profile("compute") as phase:
                self._accel.state, metrics = self._accel.train_step(
                    self._accel.state, placed
                )
                phase.block(metrics)
            step += 1
            loss = float(metrics["loss"])
            # float(loss) synced the step, so this is dispatch+sync
            # wall time — the jit-compiling first step lands in the
            # top bucket, steady state in the ms range
            _STEP_SECONDS.observe(time.perf_counter() - step_start)
            _LOSS_GAUGE.set(loss)
            self._elastic.report_step(metrics)
            self._check_loss_spike(step, loss)
            if step % self.args.logging_steps == 0:
                logger.info(
                    "step %s loss %.4f grad_norm %.3f",
                    step, loss, float(metrics["grad_norm"]),
                )
            with self._elastic.profile("checkpoint"):
                if (self.args.save_steps
                        and step % self.args.save_steps == 0):
                    self._save(step, step % save_storage_steps == 0)
            if self.args.eval_steps and step % self.args.eval_steps == 0:
                metrics_out["eval_loss"] = self.evaluate()
            with self._elastic.profile("data_wait"):
                try:
                    batch = next(data_iter)
                except StopIteration:
                    data_iter = iter(self.train_data)
                    batch = next(data_iter)
        # final storage save; flush in-flight snapshots first so the
        # save cannot be skipped as busy, then flush it too so a
        # process exit right after train() cannot lose it
        if self._checkpointer is not None:
            self._checkpointer.wait()
        self._save(step, True)
        if self._checkpointer is not None:
            self._checkpointer.wait()
        metrics_out.update(
            {"final_loss": loss, "steps": step}
        )
        return metrics_out

    def evaluate(self) -> float:
        if self.eval_data is None:
            return float("nan")
        losses = []
        params = self._accel.state.params
        for batch in self.eval_data:
            placed = self._accel.place_batch(batch)
            losses.append(float(self.loss_fn(params, placed)))
        return float(np.mean(losses)) if losses else float("nan")
