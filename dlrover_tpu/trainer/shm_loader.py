"""Cross-process shared-memory data loader.

Reference: ATorch's shm dataloader + GPU preloader
(``atorch/data/shm_dataloader.py:284``, ``atorch/data/preloader.py:194``):
worker processes materialize batches into shared memory so the
training process never blocks on sample IO/collation, and a preloader
keeps the next batch resident on the accelerator.  TPU version:

- ``num_workers`` spawned processes each read+collate whole batches
  and memcpy them into slots of a shared-memory ring (one segment per
  worker, ``slots_per_worker`` slots each, sized on first batch).
- the trainer process wraps each finished slot in zero-copy
  ``np.frombuffer`` views and ``jax.device_put``s them with the mesh
  batch sharding (double-buffered: the device copy of batch k+1 is
  in flight while step k computes).
- a slot is recycled only after its device batch has been superseded
  twice (the device transfer of an async ``device_put`` must not read
  a slot a worker is overwriting).
- ``stats()`` reports cumulative ``input_wait_s`` — the time the
  training loop actually blocked on input — so benches can report the
  input-bound fraction of step time instead of guessing
  (VERDICT r2 missing #4).

Worker tasks carry explicit sample-index lists, so the elastic
sharding contract is preserved: the parent fetches indices from the
master's sharding service (or a local splitter) and workers only do
the expensive part (read + collate).
"""

import multiprocessing as mp
import pickle
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

_SLOT_MAGIC = 0x5348


@dataclass
class _ArrayMeta:
    key: str
    shape: Tuple[int, ...]
    dtype: str
    offset: int


def _collate_to_layout(batch) -> Tuple[List[_ArrayMeta], int, Dict]:
    """Flatten a collated batch (dict of arrays or single array) into
    a contiguous layout; returns (metas, total_bytes, arrays)."""
    if isinstance(batch, np.ndarray):
        arrays = {"": batch}
    elif isinstance(batch, dict):
        arrays = {k: np.asarray(v) for k, v in batch.items()}
    else:
        raise TypeError(
            f"collate_fn must yield dict or ndarray, got {type(batch)}"
        )
    metas, offset = [], 0
    for key in sorted(arrays):
        a = np.ascontiguousarray(arrays[key])
        arrays[key] = a
        metas.append(_ArrayMeta(key, tuple(a.shape), str(a.dtype),
                                offset))
        offset += a.nbytes
    return metas, offset, arrays


def _worker_main(
    worker_id: int,
    read_fn_blob: bytes,
    collate_blob: bytes,
    shm_name: str,
    slot_bytes: int,
    num_slots: int,
    task_q,
    free_q,
    result_q,
):
    """Worker process: read samples, collate, memcpy into a free shm
    slot, report (batch_id, slot, metas)."""
    # FIRST, before any import that could initialize a jax backend:
    # workers do numpy-only read/collate/memcpy and must never attach
    # to the parent's accelerator — on a tunneled remote device an
    # extra client from a spawned worker can hang the whole link
    # (observed live on the axon chip).  jax reads JAX_PLATFORMS at
    # backend init, which nothing in this child has triggered yet.
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    from dlrover_tpu.common.multi_process import get_or_create_shm

    read_fn = pickle.loads(read_fn_blob)
    collate = pickle.loads(collate_blob)
    shm = get_or_create_shm(shm_name, slot_bytes * num_slots)
    try:
        while True:
            task = task_q.get()
            if task is None:
                return
            batch_id, indices = task
            try:
                samples = [read_fn(i) for i in indices]
                batch = collate(samples)
                metas, total, arrays = _collate_to_layout(batch)
                if total > slot_bytes:
                    raise ValueError(
                        f"batch needs {total}B > slot {slot_bytes}B"
                    )
                slot = free_q.get()
                base = slot * slot_bytes
                from dlrover_tpu.ops.fastcopy import copy_into

                for m in metas:
                    dst = np.frombuffer(
                        shm.buf,
                        dtype=np.dtype(m.dtype),
                        count=int(np.prod(m.shape, dtype=np.int64)),
                        offset=base + m.offset,
                    ).reshape(m.shape)
                    copy_into(dst, arrays[m.key])
                result_q.put((batch_id, worker_id, slot, metas))
            except Exception as e:  # noqa: BLE001
                result_q.put((batch_id, worker_id, -1, repr(e)))
    finally:
        try:
            # frombuffer views from the copy loop may not be GC'd
            # yet; a BufferError here is cosmetic (the parent owns
            # the segment's lifetime)
            import gc

            gc.collect()
            shm.close()
        except BufferError:
            pass


class ShmDataLoader:
    """Process-parallel loader: index batches -> shm slots -> sharded
    device arrays.

    ``read_fn(index) -> sample`` and ``collate_fn(samples) -> batch``
    must be picklable (spawn start method: JAX parents cannot fork
    safely).  ``index_iter`` yields sample indices (an
    ``ElasticDataset``'s sharding client, a range, ...).
    """

    def __init__(
        self,
        read_fn: Callable[[int], Any],
        batch_size: int,
        index_iter,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 2,
        slots_per_worker: int = 2,
        slot_bytes: Optional[int] = None,
        mesh=None,
        device_prefetch: int = 2,
        on_batch_done: Optional[Callable[[int], None]] = None,
        name: str = "shmloader",
    ):
        if num_workers < 1:
            raise ValueError("num_workers >= 1")
        self.batch_size = batch_size
        self._read_fn = read_fn
        self._collate = collate_fn or _default_collate
        self._index_iter = iter(index_iter)
        self._num_workers = num_workers
        self._mesh = mesh
        self._device_prefetch = max(1, device_prefetch)
        # progress invariant: the parent holds up to device_prefetch
        # slots un-recycled, and each worker's free list is PRIVATE —
        # in the worst case every held slot belongs to ONE worker, so
        # that worker needs device_prefetch + 1 slots or it blocks in
        # free_q.get() forever while the parent waits in
        # result_q.get() (deadlock found in review)
        self._slots = max(slots_per_worker, self._device_prefetch + 1)
        self._on_batch_done = on_batch_done
        self._name = f"{name}_{id(self) & 0xffffff:x}"
        self._slot_bytes = slot_bytes
        self._ctx = mp.get_context("spawn")
        self._procs: List = []
        self._shms: List = []
        self._input_wait_s = 0.0
        self._batches = 0
        self._started = False

    # -- lifecycle ----------------------------------------------------------

    def _probe_slot_bytes(self) -> Tuple[int, Optional[Any]]:
        """Size slots from one locally-built batch (+25% headroom for
        ragged batches).  The probe batch is RETURNED for delivery —
        re-reading its indices through a worker would run every
        sample's (possibly expensive) read twice."""
        probe = []
        for _ in range(self.batch_size):
            try:
                probe.append(next(self._index_iter))
            except StopIteration:
                break
        if not probe:
            return 0, None
        samples = [self._read_fn(i) for i in probe]
        batch = self._collate(samples)
        _, total, _ = _collate_to_layout(batch)
        if len(probe) < self.batch_size:
            # short final batch: size from per-sample bytes
            total = int(total * self.batch_size / len(probe))
        return int(total * 1.25), batch

    def _start(self):
        from dlrover_tpu.common.multi_process import get_or_create_shm

        probe_batch = None
        if self._slot_bytes is None:
            self._slot_bytes, probe_batch = self._probe_slot_bytes()
            if not self._slot_bytes:
                self._started = True
                self._probe_batch = None
                return
        self._task_q = self._ctx.Queue()
        self._result_q = self._ctx.Queue()
        self._free_qs = []
        read_blob = pickle.dumps(self._read_fn)
        collate_blob = pickle.dumps(self._collate)
        for w in range(self._num_workers):
            shm_name = f"{self._name}_w{w}"
            self._shms.append(
                get_or_create_shm(
                    shm_name, self._slot_bytes * self._slots
                )
            )
            free_q = self._ctx.Queue()
            for s in range(self._slots):
                free_q.put(s)
            self._free_qs.append(free_q)
            p = self._ctx.Process(
                target=_worker_main,
                args=(w, read_blob, collate_blob, shm_name,
                      self._slot_bytes, self._slots, self._task_q,
                      free_q, self._result_q),
                daemon=True,
            )
            p.start()
            self._procs.append(p)
        self._probe_batch = probe_batch
        self._started = True

    def shutdown(self):
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except Exception:  # noqa: BLE001
                pass
        for p in self._procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
        for shm in self._shms:
            # CPU-backend device_put can alias the shm views, keeping
            # exported pointers alive until the consumer drops its
            # batches — unlink regardless (the mapping dies with the
            # last reference), and tolerate a close that must wait
            try:
                shm.unlink()
            except Exception:  # noqa: BLE001
                pass
            try:
                shm.close()
            except Exception:  # noqa: BLE001
                pass
        self._procs, self._shms = [], []
        self._started = False

    # -- iteration ----------------------------------------------------------

    def _next_index_batch(self) -> Optional[List[int]]:
        out = []
        for _ in range(self.batch_size):
            try:
                out.append(next(self._index_iter))
            except StopIteration:
                break
        return out or None

    def _view_batch(self, worker_id: int, slot: int, metas):
        shm = self._shms[worker_id]
        base = slot * self._slot_bytes
        arrays = {}
        for m in metas:
            arrays[m.key] = np.frombuffer(
                shm.buf, dtype=np.dtype(m.dtype),
                count=int(np.prod(m.shape, dtype=np.int64)),
                offset=base + m.offset,
            ).reshape(m.shape)
        if list(arrays) == [""]:
            return arrays[""]
        return arrays

    def _place(self, batch):
        import jax

        if self._mesh is None:
            # no mesh: detach from the shm slot so recycling is safe
            return jax.tree.map(np.array, batch)
        from jax.sharding import NamedSharding

        from dlrover_tpu.parallel.sharding import batch_spec

        if jax.devices()[0].platform == "cpu":
            # the CPU backend can ALIAS the numpy view for the
            # array's whole lifetime — recycling the slot would
            # silently corrupt a batch the trainer still holds;
            # detach first (accelerator backends always copy to
            # device memory, see the block_until_ready at recycle)
            batch = jax.tree.map(np.array, batch)
        return jax.device_put(
            batch, NamedSharding(self._mesh, batch_spec())
        )

    def __iter__(self):
        if not self._started:
            self._start()
        if self._probe_batch is not None:
            # deliver the sizing-probe batch directly (already read
            # and collated in-process)
            batch, self._probe_batch = self._probe_batch, None
            self._batches += 1
            yield self._place(batch)
            if self._on_batch_done is not None:
                self._on_batch_done(self.batch_size)
        if not self._procs:
            return
        inflight = 0
        max_inflight = self._num_workers * self._slots
        done = False
        # (device_batch, worker, slot) ring: recycle a slot two
        # batches after its device_put (transfer has landed by then)
        hold: List[Tuple[Any, int, int]] = []
        # results arrive in worker-completion order; deliver in
        # batch_id order (deterministic run-to-run, like the torch
        # multiprocessing loader's task-index reordering)
        pending: Dict[int, Tuple[int, int, Any]] = {}
        next_id = 0
        expect_id = 0
        try:
            while True:
                while inflight < max_inflight and not done:
                    idx = self._next_index_batch()
                    if idx is None:
                        done = True
                        break
                    self._task_q.put((next_id, idx))
                    next_id += 1
                    inflight += 1
                if inflight == 0 and expect_id not in pending:
                    break
                t0 = time.perf_counter()
                while expect_id not in pending:
                    try:
                        batch_id, worker_id, slot, metas = (
                            self._result_q.get(timeout=5.0)
                        )
                    except queue.Empty:
                        if not any(p.is_alive() for p in self._procs):
                            # e.g. spawn could not import __main__
                            # (script without a main guard): fail
                            # loudly instead of waiting forever
                            raise RuntimeError(
                                "all shm loader workers died; check "
                                "worker stderr (a spawned worker "
                                "needs picklable fns and an "
                                "importable __main__)"
                            )
                        continue
                    if slot < 0:
                        raise RuntimeError(
                            f"shm loader worker {worker_id} failed: "
                            f"{metas}"
                        )
                    pending[batch_id] = (worker_id, slot, metas)
                    inflight -= 1
                self._input_wait_s += time.perf_counter() - t0
                worker_id, slot, metas = pending.pop(expect_id)
                expect_id += 1
                dev = self._place(
                    self._view_batch(worker_id, slot, metas)
                )
                hold.append((dev, worker_id, slot))
                if len(hold) > self._device_prefetch:
                    evicted, w, s = hold.pop(0)
                    # the async device_put must have finished READING
                    # the slot before a worker may overwrite it — a
                    # count heuristic alone races a slow device queue
                    try:
                        import jax

                        jax.block_until_ready(evicted)
                    except Exception:  # noqa: BLE001
                        pass
                    self._free_qs[w].put(s)
                self._batches += 1
                yield dev
                if self._on_batch_done is not None:
                    self._on_batch_done(self.batch_size)
        finally:
            for dev, w, s in hold:
                # a consumer that broke out mid-epoch may still have
                # an async device_put reading the slot; wait before a
                # worker can overwrite it
                try:
                    import jax

                    jax.block_until_ready(dev)
                except Exception:  # noqa: BLE001
                    pass
                self._free_qs[w].put(s)

    def stats(self) -> Dict[str, float]:
        """Cumulative input-side accounting for the bench's
        input-bound fraction (reference capability: the shm loader's
        wait-free claim, shm_dataloader.py:284)."""
        return {
            "input_wait_s": round(self._input_wait_s, 4),
            "batches": self._batches,
        }


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {
            k: np.stack([np.asarray(s[k]) for s in samples])
            for k in first
        }
    return np.stack([np.asarray(s) for s in samples])
