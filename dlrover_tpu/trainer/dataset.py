"""Elastic dataset + device-feeding loader.

Reference: ``ElasticDataset`` (``atorch/data/elastic_dataset.py:19``)
— a dataset whose sample indices come from the master's dynamic
sharding service, so a resized/restarted job never re-reads completed
shards — and ``ElasticDataLoader`` (``dlrover/trainer/torch/elastic/
dataloader.py:26``) whose batch size follows the runtime parallelism
config.  The TPU loader assembles numpy batches and device_puts them
with the mesh's batch sharding, with a one-batch prefetch so host
assembly overlaps device compute.
"""

import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np

from dlrover_tpu.agent.sharding_client import IndexShardingClient
from dlrover_tpu.common.log import default_logger as logger


class ElasticDataset:
    """Map-style dataset over master-assigned sample indices.

    Subclass and implement ``read_sample(index)`` (reference API
    parity: elastic_dataset.py ``ElasticDataset.read_sample``), or
    pass ``read_fn``.
    """

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        read_fn: Optional[Callable[[int], Any]] = None,
        sharding_client: Optional[IndexShardingClient] = None,
    ):
        self.dataset_size = dataset_size
        self.batch_size = batch_size
        self._read_fn = read_fn
        self._client = sharding_client or IndexShardingClient(
            dataset_name=dataset_name,
            batch_size=batch_size,
            num_epochs=num_epochs,
            dataset_size=dataset_size,
            shuffle=shuffle,
        )

    def read_sample(self, index: int):
        if self._read_fn is None:
            raise NotImplementedError(
                "implement read_sample or pass read_fn"
            )
        return self._read_fn(index)

    def __len__(self) -> int:
        return self.dataset_size

    def __iter__(self) -> Iterator[Any]:
        while True:
            idx = self._client.fetch_sample_index()
            if idx is None:
                return
            yield self.read_sample(idx)

    def report_batch_done(self, batch_size: Optional[int] = None):
        self._client.report_batch_done(batch_size)

    def checkpoint(self) -> str:
        return self._client.get_checkpoint()

    def restore_checkpoint(self, content: str):
        self._client.restore_checkpoint(content)


class ElasticDataLoader:
    """Batches an ElasticDataset and feeds the device mesh."""

    def __init__(
        self,
        dataset: ElasticDataset,
        batch_size: Optional[int] = None,
        collate_fn: Optional[Callable] = None,
        mesh=None,
        prefetch: int = 2,
        drop_last: bool = True,
    ):
        self.dataset = dataset
        self.batch_size = batch_size or dataset.batch_size
        self._collate = collate_fn or _default_collate
        self._mesh = mesh
        self._prefetch = prefetch
        self._drop_last = drop_last

    def set_batch_size(self, batch_size: int):
        """Runtime-tunable batch size (reference: ElasticDataLoader
        reloading from the paral-config file)."""
        self.batch_size = batch_size

    def _place(self, batch):
        if self._mesh is None:
            return batch
        import jax
        from jax.sharding import NamedSharding

        from dlrover_tpu.parallel.sharding import batch_spec

        return jax.device_put(
            batch, NamedSharding(self._mesh, batch_spec())
        )

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._prefetch)
        DONE = object()

        def producer():
            samples = []
            try:
                for sample in self.dataset:
                    samples.append(sample)
                    if len(samples) == self.batch_size:
                        q.put(self._collate(samples))
                        samples = []
                if samples and not self._drop_last:
                    q.put(self._collate(samples))
            except Exception as e:  # noqa: BLE001
                logger.error("dataloader producer failed: %s", e)
            finally:
                q.put(DONE)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                return
            yield self._place(item)
            self.dataset.report_batch_done(self.batch_size)


def _default_collate(samples):
    """Stack dict-of-arrays or array samples into numpy batches."""
    first = samples[0]
    if isinstance(first, dict):
        return {
            k: np.stack([np.asarray(s[k]) for s in samples])
            for k in first
        }
    return np.stack([np.asarray(s) for s in samples])
