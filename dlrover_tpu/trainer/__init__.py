"""In-process training library (reference: ``dlrover/trainer/`` —
ElasticTrainer, ElasticDistributedSampler, flash-checkpoint front
ends) rebuilt around jitted JAX train steps."""

from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer, TrainState
from dlrover_tpu.trainer.sampler import ElasticDistributedSampler

__all__ = ["ElasticTrainer", "ElasticDistributedSampler", "TrainState"]
