"""Split-step sparse training pipeline (the parameter-server shape).

Reference: TFPlus trains sparse models with HOST-resident KvVariable
tables and CPU parameter servers (``tfplus/tfplus/kv_variable/ops/
kv_variable_ops.cc:37``, ``tfplus/tfplus/training/group_adam.py:28``)
— the accelerator only ever sees dense gathered embeddings.

The TPU translation has two tiers:

- ``KvVariable.jax_gather`` embeds the host gather INSIDE the jitted
  program via ``io_callback`` — elegant, but host callbacks require
  the runtime to re-enter this process mid-program, which a tunneled
  remote device physically cannot do (the call hangs; VERDICT r3
  weak #4).
- this module: the SPLIT STEP.  The gather runs host-side *before*
  the jitted device step, the C++ group optimizer runs host-side
  *after* it, and the loop is double-buffered so the host table work
  overlaps device compute instead of serializing with it:

      host:    gather(k+1)   update(k-1)      gather(k+2) ...
      device:  [------ step k ------][------ step k+1 ------]
      D2H:         [egrads k-1 streams during step k]

  Step ``k``'s embeddings therefore miss exactly one in-flight
  update (staleness 1) — the same asynchrony a CPU parameter server
  exhibits by design.  The device->host gradient fetch is started
  ASYNCHRONOUSLY right after dispatch (``copy_to_host_async``), so
  the transfer — which dominates wall time through a slow device
  link (VERDICT r4 weak #3) — streams while the next gather runs
  instead of serializing with it.  ``pipeline=False`` gives strict
  sequential semantics (gather -> step -> update) when exactness
  matters more than throughput; ``pipeline="auto"`` probes the first
  batches strictly and stays strict when the measured host fraction
  is too small for double buffering to pay (< ~0.2).
"""

import itertools
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import numpy as np


class SparseTrainPipeline:
    """Drive a hybrid host-sparse / device-dense train loop.

    Parameters
    ----------
    table:
        :class:`dlrover_tpu.ops.kv_variable.KvVariable` hosting the
        embeddings.
    sparse_optimizer:
        a group optimizer over ``table`` (GroupAdam/Adagrad/FTRL) —
        ``apply_gradients(keys, grads)`` updates only touched rows.
    device_step:
        jitted ``(state, emb, *batch_arrays) -> (state, emb_grads,
        aux)``.  ``emb`` is the dense ``[batch, fields, dim]`` gather
        result; ``emb_grads`` must be the gradient wrt ``emb``; aux is
        any pytree of scalars (loss, metrics) fetched lazily.
    pipeline:
        True (default): staleness-1 double buffering as drawn above.
        False: strict gather -> step -> update per batch.
    """

    def __init__(
        self,
        table,
        sparse_optimizer,
        device_step: Callable,
        pipeline=True,
    ):
        self.table = table
        self.sparse_optimizer = sparse_optimizer
        self.device_step = device_step
        if pipeline not in (True, False, "auto"):
            raise ValueError(f"pipeline must be bool or 'auto', "
                             f"got {pipeline!r}")
        self.pipeline = pipeline
        self.chosen_mode: Optional[str] = (
            None if pipeline == "auto"
            else ("pipelined" if pipeline else "strict")
        )
        # accounting for the bench's overlap story
        self.stats: Dict[str, float] = {
            "steps": 0,
            "gather_s": 0.0,
            "fetch_s": 0.0,   # blocking wait for device emb_grads
            "update_s": 0.0,  # pure host group-optimizer time
            "dispatch_s": 0.0,
            "wall_s": 0.0,
        }

    @staticmethod
    def _start_fetch(egrads) -> None:
        """Kick off the device->host copy without blocking: the
        transfer then streams while the host gathers the next batch
        (and while the device runs it), so the eventual blocking
        np.asarray finds the bytes already resident."""
        import jax

        def kick(x):
            fn = getattr(x, "copy_to_host_async", None)
            if fn is not None:
                fn()

        try:
            jax.tree.map(kick, egrads)
        except Exception:  # noqa: BLE001 - backend-optional fast path
            pass

    def _gather(self, sparse_ids: np.ndarray) -> np.ndarray:
        t0 = time.perf_counter()
        b, f = sparse_ids.shape
        out = self.table.gather(sparse_ids.reshape(-1)).reshape(
            b, f, self.table.dim
        )
        self.stats["gather_s"] += time.perf_counter() - t0
        return out

    def _update(self, sparse_ids: np.ndarray, emb_grads) -> None:
        t0 = time.perf_counter()
        grads = np.asarray(emb_grads)  # blocks until the step landed
        t1 = time.perf_counter()
        self.stats["fetch_s"] += t1 - t0
        b, f = sparse_ids.shape
        self.sparse_optimizer.apply_gradients(
            sparse_ids.reshape(-1),
            grads.reshape(b * f, self.table.dim),
        )
        self.stats["update_s"] += time.perf_counter() - t1

    def attach_checkpoint(self, checkpointer):
        """Wire this pipeline's sparse state into a
        :class:`~dlrover_tpu.checkpoint.checkpointer.Checkpointer`:
        builds a :class:`~dlrover_tpu.checkpoint.sparse.
        SparseStateAdapter` over the embedding table + the
        optimizer's slot tables (and step counter) and registers it
        with the flash-checkpoint engine, so every ``save_checkpoint``
        snapshots the hash tables alongside the dense state and every
        restore imports them back.  Returns the adapter.

        Checkpoint-consistent snapshots need the table quiescent at
        the save call: run the pipeline in ``strict`` mode when
        saving mid-run (the ``on_step`` callback fires with no update
        in flight), or save between :meth:`run` calls in pipelined
        mode (the trailing update is drained at return)."""
        from dlrover_tpu.checkpoint.sparse import SparseStateAdapter

        adapter = SparseStateAdapter()
        if hasattr(self.sparse_optimizer, "slot_tables"):
            adapter.register_optimizer(self.sparse_optimizer)
        else:
            adapter.register_table(self.table)
        checkpointer.register_sparse(adapter)
        return adapter

    def run(
        self,
        state,
        batches: Iterable[Tuple[np.ndarray, ...]],
        on_aux: Optional[Callable[[Any], None]] = None,
        on_step: Optional[Callable[[Any, int], None]] = None,
    ):
        """Consume ``batches`` of ``(sparse_ids, *device_arrays)``;
        returns the final dense state.  ``on_aux`` receives each
        step's (device-resident) aux pytree — fetch inside it only if
        you can afford the sync.  ``on_step(state, steps_done)`` runs
        after each step's sparse update retires — in strict mode the
        table and the dense state are exactly step-consistent there
        (the flash-checkpoint hook point); in pipelined mode one
        update is still in flight (staleness 1), so mid-run
        checkpoints should use strict mode."""
        if self.pipeline == "auto":
            # probe strictly, then commit: a tiny host fraction means
            # double buffering only adds overhead (VERDICT r4 weak #3
            # — the device fetch can dwarf the table work).  The
            # FIRST batch jit-compiles device_step, so its dispatch
            # time is seconds of XLA work that steady state never
            # pays — counting it would shrink the host fraction and
            # wrongly commit to strict; run it outside the probe
            # accounting (it still trains and still accumulates into
            # self.stats for the overlap report)
            it = iter(batches)
            warmup = list(itertools.islice(it, 1))
            state = self._run_strict(state, warmup, on_aux, on_step)
            base = {
                k: self.stats[k]
                for k in ("gather_s", "update_s", "dispatch_s",
                          "fetch_s")
            }
            probe = list(itertools.islice(it, 3))
            state = self._run_strict(state, probe, on_aux, on_step)
            host = (
                self.stats["gather_s"] - base["gather_s"]
                + self.stats["update_s"] - base["update_s"]
            )
            busy = host + \
                (self.stats["dispatch_s"] - base["dispatch_s"]) + \
                (self.stats["fetch_s"] - base["fetch_s"])
            frac = host / max(busy, 1e-9)
            self.chosen_mode = (
                "pipelined" if frac >= 0.2 else "strict"
            )
            if self.chosen_mode == "pipelined":
                return self._run_pipelined(state, it, on_aux, on_step)
            return self._run_strict(state, it, on_aux, on_step)
        if self.pipeline:
            return self._run_pipelined(state, batches, on_aux, on_step)
        return self._run_strict(state, batches, on_aux, on_step)

    def _run_strict(self, state, batches, on_aux, on_step=None):
        import jax.numpy as jnp

        t_wall = time.perf_counter()
        for sparse_ids, *rest in batches:
            emb = self._gather(sparse_ids)
            t0 = time.perf_counter()
            state, egrads, aux = self.device_step(
                state, jnp.asarray(emb), *rest
            )
            self.stats["dispatch_s"] += time.perf_counter() - t0
            self._start_fetch(egrads)
            self._update(sparse_ids, egrads)
            self.stats["steps"] += 1
            if on_aux is not None:
                on_aux(aux)
            if on_step is not None:
                on_step(state, int(self.stats["steps"]))
        self.stats["wall_s"] += time.perf_counter() - t_wall
        return state

    def _run_pipelined(self, state, batches, on_aux, on_step=None):
        import jax.numpy as jnp

        t_wall = time.perf_counter()
        it = iter(batches)
        try:
            cur = next(it)
        except StopIteration:
            self.stats["wall_s"] += time.perf_counter() - t_wall
            return state
        emb = self._gather(cur[0])
        pending: Optional[Tuple[np.ndarray, Any]] = None
        while True:
            nxt = next(it, None)
            sparse_ids, *rest = cur
            t0 = time.perf_counter()
            state, egrads, aux = self.device_step(
                state, jnp.asarray(emb), *rest
            )
            self.stats["dispatch_s"] += time.perf_counter() - t0
            # step k's gradient D2H starts NOW and streams while the
            # host gathers k+1 and the device computes — by the time
            # step k+1 retires it, the bytes are already host-side
            self._start_fetch(egrads)
            # while the device runs step k: retire step k-1's sparse
            # update (its grads streamed during our dispatch), then
            # gather step k+1's rows — the table the gather sees
            # includes every update through k-1
            if pending is not None:
                self._update(*pending)
            if nxt is not None:
                next_emb = self._gather(nxt[0])
            pending = (sparse_ids, egrads)
            self.stats["steps"] += 1
            if on_aux is not None:
                on_aux(aux)
            if on_step is not None:
                # staleness 1: this step's own sparse update is still
                # in flight — documented in :meth:`run`
                on_step(state, int(self.stats["steps"]))
            if nxt is None:
                break
            cur, emb = nxt, next_emb
        # drain the last in-flight update
        self._update(*pending)
        self.stats["wall_s"] += time.perf_counter() - t_wall
        return state

    def overlap_report(self) -> Dict[str, float]:
        """Host-work overlap accounting: in a perfect pipeline the
        wall time approaches max(device, host) instead of their sum."""
        s = dict(self.stats)
        host = s["gather_s"] + s["update_s"]
        s["host_table_s"] = round(host, 4)
        s["fetch_s"] = round(s["fetch_s"], 4)
        if s["wall_s"] > 0:
            s["host_fraction"] = round(host / s["wall_s"], 4)
        if self.chosen_mode is not None:
            s["mode"] = self.chosen_mode
        return s


def make_deepfm_device_step(model, dense_optimizer):
    """Jitted dense step for :class:`dlrover_tpu.models.deepfm.DeepFM`
    shaped for :class:`SparseTrainPipeline`: consumes the gathered
    embeddings, returns their gradient for the host group optimizer.
    Dense state is donated (updated in place on device)."""
    from functools import partial

    import jax
    import optax

    from dlrover_tpu.models.deepfm import bce_with_logits

    @partial(jax.jit, donate_argnums=0)
    def device_step(dense_state, emb, dense_x, labels):
        params, opt_state = dense_state

        def loss_fn(dp, e):
            logits = model.apply(dp, e, dense_x)
            return bce_with_logits(logits, labels)

        loss, (dgrads, egrads) = jax.value_and_grad(
            loss_fn, argnums=(0, 1)
        )(params, emb)
        updates, new_opt = dense_optimizer.update(
            dgrads, opt_state, params
        )
        new_params = optax.apply_updates(params, updates)
        return (new_params, new_opt), egrads, {"loss": loss}

    return device_step
