"""Elastic training loop utilities.

Reference: ``ElasticTrainer``
(``dlrover/trainer/torch/elastic/trainer.py``): keeps the *global*
batch size fixed as the world resizes by adjusting gradient
accumulation, counts steps, and writes a runtime-metrics file the
agent's TrainingMonitor reports to the master's SpeedMonitor.

TPU-native shape: instead of wrapping a torch optimizer, the trainer
builds one jitted train step that scans over the gradient-accumulation
microbatches inside the compiled program (``lax.scan`` — no Python
loop, one XLA program per world size) and applies the optax update.
Sharding: params/opt-state placed by partition rules, batch split over
the data axes; XLA inserts the gradient psum.
"""

import json
import os
import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.common import env_utils, jax_compat
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import dp_world_size
from dlrover_tpu.parallel.sharding import (
    PartitionRules,
    batch_spec,
    sharding_tree,
)
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_REPORTED_STEP = _REG.gauge(
    "dlrover_trainer_reported_step",
    "Latest step the trainer wrote to the agent-tailed metrics file",
)
_GRAD_ACCUM_GAUGE = _REG.gauge(
    "dlrover_trainer_grad_accum",
    "Gradient-accumulation factor keeping the global batch fixed",
)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """Minimal train state pytree (params + optax state + step)."""

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer):
        return cls(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), dtype=jnp.int32),
        )


def make_train_step(
    loss_fn: Callable,
    optimizer,
    grad_accum: int = 1,
    mesh=None,
    rules: Optional[PartitionRules] = None,
):
    """Build the jitted (state, batch) -> (state, metrics) step.

    ``loss_fn(params, batch) -> scalar``.  With ``grad_accum > 1`` the
    batch's leading dim must be ``grad_accum * micro``; the scan keeps
    the accumulation inside the compiled program.  When a mesh is
    given, in/out shardings pin state to the rule-derived placement and
    the batch to the data axes — GSPMD inserts all collectives.
    """

    def grads_of(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return loss, grads

    def step_fn(state: TrainState, batch):
        if grad_accum > 1:
            micro = jax.tree.map(
                lambda x: x.reshape(
                    (grad_accum, x.shape[0] // grad_accum) + x.shape[1:]
                ),
                batch,
            )

            def accum(carry, mb):
                loss_sum, grads_sum = carry
                loss, grads = grads_of(state.params, mb)
                return (
                    loss_sum + loss,
                    jax.tree.map(jnp.add, grads_sum, grads),
                ), None

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (loss_sum, grads), _ = jax.lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), micro
            )
            loss = loss_sum / grad_accum
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
        else:
            loss, grads = grads_of(state.params, batch)
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params
        )
        import optax

        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=0)

    rules = rules or PartitionRules()
    from jax.sharding import NamedSharding

    def jit_with_shardings(state_example):
        state_sh = sharding_tree(state_example, mesh, rules)
        batch_sh = NamedSharding(mesh, batch_spec())
        return jax.jit(
            step_fn,
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, None),
            donate_argnums=0,
        )

    return step_fn, jit_with_shardings


class ElasticTrainer:
    """Step/epoch accounting with a fixed global batch across resizes
    (reference: trainer.py GradientState + _ElasticOptimizer)."""

    def __init__(
        self,
        global_batch_size: int,
        micro_batch_size: int,
        dp_size: Optional[int] = None,
        metrics_path: Optional[str] = None,
    ):
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.dp_size = dp_size or env_utils.get_world_size()
        if global_batch_size % (micro_batch_size * self.dp_size):
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"micro {micro_batch_size} x dp {self.dp_size}"
            )
        self.grad_accum = global_batch_size // (
            micro_batch_size * self.dp_size
        )
        self.global_step = 0
        self._metrics_path = metrics_path or os.getenv(
            "DLROVER_METRICS_FILE",
            os.path.join("/tmp", f"dlrover_metrics_{os.getuid()}.json"),
        )
        self._epoch = 0
        self._restart_count = env_utils.get_restart_count()
        _GRAD_ACCUM_GAUGE.set(self.grad_accum)
        logger.info(
            "elastic trainer: global_batch=%s micro=%s dp=%s accum=%s",
            global_batch_size, micro_batch_size, self.dp_size,
            self.grad_accum,
        )

    @property
    def local_batch_size(self) -> int:
        """Samples this data-parallel rank consumes per step."""
        return self.micro_batch_size * self.grad_accum

    def report_step(self, metrics: Optional[Dict[str, float]] = None):
        """Advance the step counter and write the metrics file the
        agent monitor tails (reference: trainer.py report to file +
        monitor/training.py)."""
        self.global_step += 1
        _REPORTED_STEP.set(self.global_step)
        # per-step training event: this is what lets the chaos
        # invariant checkers compute "steps lost across a fault" from
        # the event log alone (no-op unless an event log is configured)
        emit_event(
            "train_step",
            step=self.global_step,
            restart_count=self._restart_count,
            # which node stepped: multi-agent chaos invariants decide
            # per-node progress from the event log alone
            node_rank=env_utils.get_node_rank(),
        )
        # chaos hook AFTER the event: a kill rule at step N must leave
        # step N's completion in the log before the process dies; a
        # slow rule stretches the observable step time (straggler)
        _chaos.fire("trainer.step", step=self.global_step)
        record = {
            "global_step": self.global_step,
            "timestamp": time.time(),
            "epoch": self._epoch,
        }
        if metrics:
            record.update(
                {
                    k: float(v)
                    for k, v in metrics.items()
                    if jnp.isscalar(v) or getattr(v, "ndim", 1) == 0
                }
            )
        tmp = self._metrics_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self._metrics_path)
        except OSError as e:
            logger.debug("metrics file write failed: %s", e)

    def set_epoch(self, epoch: int):
        self._epoch = epoch

    def state_dict(self) -> Dict[str, int]:
        return {"global_step": self.global_step, "epoch": self._epoch}

    def load_state_dict(self, state: Dict[str, int]):
        self.global_step = int(state.get("global_step", 0))
        self._epoch = int(state.get("epoch", 0))


def init_jax_distributed():
    """Initialize multi-host JAX from the agent's env contract
    (reference analog: dist.init_process_group with MASTER_ADDR/PORT
    set by the agent, training.py:430-447)."""
    coordinator = env_utils.get_coordinator_addr()
    num_processes = int(
        os.getenv("DLROVER_NUM_PROCESSES", "1")
    )
    if not coordinator or num_processes <= 1:
        return False
    process_id = int(os.getenv("DLROVER_PROCESS_ID", "0"))
    jax_compat.ensure_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: process %s/%s via %s",
        process_id, num_processes, coordinator,
    )
    return True
