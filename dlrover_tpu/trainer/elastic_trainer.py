"""Elastic training loop utilities.

Reference: ``ElasticTrainer``
(``dlrover/trainer/torch/elastic/trainer.py``): keeps the *global*
batch size fixed as the world resizes by adjusting gradient
accumulation, counts steps, and writes a runtime-metrics file the
agent's TrainingMonitor reports to the master's SpeedMonitor.

TPU-native shape: instead of wrapping a torch optimizer, the trainer
builds one jitted train step that scans over the gradient-accumulation
microbatches inside the compiled program (``lax.scan`` — no Python
loop, one XLA program per world size) and applies the optax update.
Sharding: params/opt-state placed by partition rules, batch split over
the data axes; XLA inserts the gradient psum.
"""

import json
import os
import time
from contextlib import contextmanager
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.common import env_utils, jax_compat
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.mesh import dp_world_size
from dlrover_tpu.parallel.sharding import (
    PartitionRules,
    batch_spec,
    sharding_tree,
)
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_REPORTED_STEP = _REG.gauge(
    "dlrover_trainer_reported_step",
    "Latest step the trainer wrote to the agent-tailed metrics file",
)
_GRAD_ACCUM_GAUGE = _REG.gauge(
    "dlrover_trainer_grad_accum",
    "Gradient-accumulation factor keeping the global batch fixed",
)
_STEP_PHASE_SECONDS = _REG.histogram(
    "dlrover_step_phase_seconds",
    "Per-step wall time by phase (data_wait / h2d / compute / "
    "checkpoint / report / other)",
)


class StepPhaseProfiler:
    """Always-on phase breakdown of one training step.

    The diagnosis layer needs to tell a *data-starved* trainer (input
    pipeline dominates) from a *slow* one (compute dominates) from a
    *hung* one (nothing progresses), which requires real per-phase
    durations — a bare step time cannot distinguish them.  Cost per
    phase is two ``perf_counter`` reads and a dict add (~1 µs), so
    this stays on in production; the event emission is a no-op unless
    an event log is configured.

    The canonical phases are ``data_wait`` (blocking on the input
    pipeline), ``h2d`` (host-to-device transfer), ``compute`` (the
    jitted step — bracket with :meth:`PhaseHandle.block` so async
    dispatch doesn't leak compute time into the next data wait),
    ``checkpoint`` and ``report``; arbitrary names are accepted.
    Un-profiled remainder of the step lands in ``other``.
    """

    KNOWN_PHASES = (
        "data_wait", "h2d", "compute", "checkpoint", "report",
    )

    def __init__(self):
        self._acc: Dict[str, float] = {}
        self._step_started = time.perf_counter()

    @contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        handle = PhaseHandle()
        try:
            yield handle
        finally:
            if handle.pending is not None:
                try:
                    jax.block_until_ready(handle.pending)
                except Exception:  # noqa: BLE001 - profiling must
                    pass  # never break the step it measures
            dt = time.perf_counter() - start
            self._acc[name] = self._acc.get(name, 0.0) + dt

    def add(self, name: str, seconds: float):
        """Record an externally-timed phase (e.g. the checkpoint
        engine's own stall measurement)."""
        self._acc[name] = self._acc.get(name, 0.0) + float(seconds)

    def finish_step(self) -> Dict[str, float]:
        """Close the step: returns ``{phase: seconds, ...,
        "total_s", "other_s"}`` and resets for the next step."""
        now = time.perf_counter()
        total = max(0.0, now - self._step_started)
        phases = {k: round(v, 6) for k, v in self._acc.items()}
        profiled = sum(self._acc.values())
        phases["total_s"] = round(total, 6)
        phases["other_s"] = round(max(0.0, total - profiled), 6)
        self._acc.clear()
        self._step_started = now
        return phases


class PhaseHandle:
    """Yielded by :meth:`StepPhaseProfiler.phase`; ``block(x)`` marks
    ``x`` to be ``jax.block_until_ready``-ed before the phase closes,
    so the recorded duration covers the device work, not just the
    async dispatch."""

    __slots__ = ("pending",)

    def __init__(self):
        self.pending = None

    def block(self, x):
        self.pending = x
        return x


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    """Minimal train state pytree (params + optax state + step)."""

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer, opt_state=None, step=None):
        """``opt_state``/``step`` default to a fresh optimizer init —
        pass restored slots to DEFER the eager init entirely (a
        restore that already supplies the moments must not pay
        ``optimizer.init`` just to overwrite it)."""
        return cls(
            params=params,
            opt_state=(
                optimizer.init(params) if opt_state is None
                else opt_state
            ),
            step=(
                jnp.zeros((), dtype=jnp.int32) if step is None
                else step
            ),
        )


def restore_train_state(optimizer, restored) -> TrainState:
    """Typed :class:`TrainState` from a restored nested dict with the
    recovery ``state_build`` residual shaved off: the optimizer is
    never re-initialized (the restore supplies params AND slots) and
    every leaf conversion rides ONE batched ``device_put`` instead of
    a per-leaf ``jnp.asarray`` chain (each of which dispatches its
    own transfer — ~0.3 s of the measured recovery budget at toy
    scale, worse at real scale).

    The typed optax containers are rebuilt by tracing
    ``TrainState.create`` over the restored params' avals — no model
    code runs and nothing touches a device during the trace."""
    from dlrover_tpu.checkpoint.checkpointer import (
        restore_to_template,
    )

    abs_params = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        restored["params"],
    )
    template = jax.eval_shape(
        lambda p: TrainState.create(p, optimizer), abs_params
    )
    return restore_to_template(template, restored)


def make_train_step(
    loss_fn: Callable,
    optimizer,
    grad_accum: int = 1,
    mesh=None,
    rules: Optional[PartitionRules] = None,
):
    """Build the jitted (state, batch) -> (state, metrics) step.

    ``loss_fn(params, batch) -> scalar``.  With ``grad_accum > 1`` the
    batch's leading dim must be ``grad_accum * micro``; the scan keeps
    the accumulation inside the compiled program.  When a mesh is
    given, in/out shardings pin state to the rule-derived placement and
    the batch to the data axes — GSPMD inserts all collectives.
    """

    def grads_of(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        return loss, grads

    def step_fn(state: TrainState, batch):
        if grad_accum > 1:
            micro = jax.tree.map(
                lambda x: x.reshape(
                    (grad_accum, x.shape[0] // grad_accum) + x.shape[1:]
                ),
                batch,
            )

            def accum(carry, mb):
                loss_sum, grads_sum = carry
                loss, grads = grads_of(state.params, mb)
                return (
                    loss_sum + loss,
                    jax.tree.map(jnp.add, grads_sum, grads),
                ), None

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (loss_sum, grads), _ = jax.lax.scan(
                accum, (jnp.zeros((), jnp.float32), zeros), micro
            )
            loss = loss_sum / grad_accum
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
        else:
            loss, grads = grads_of(state.params, batch)
        updates, new_opt = optimizer.update(
            grads, state.opt_state, state.params
        )
        import optax

        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1
        )
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=0)

    rules = rules or PartitionRules()
    from jax.sharding import NamedSharding

    def jit_with_shardings(state_example):
        state_sh = sharding_tree(state_example, mesh, rules)
        batch_sh = NamedSharding(mesh, batch_spec())
        return jax.jit(
            step_fn,
            in_shardings=(state_sh, batch_sh),
            out_shardings=(state_sh, None),
            donate_argnums=0,
        )

    return step_fn, jit_with_shardings


def abstract_like(tree):
    """``ShapeDtypeStruct`` twin of a pytree — the zero-cost abstract
    example :func:`resolve_train_step` lowers against, buildable from
    restored params or an ``eval_shape`` of the init, so the AOT
    resolve can run BEFORE the restore joins."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            jnp.shape(x), jnp.result_type(x)
        ),
        tree,
    )


def resolve_train_step(
    step_fn,
    example_state,
    example_batch,
    profiler=None,
    label: str = "train_step",
    restore_busy=None,
):
    """Resolve the jitted train step through the AOT executable cache
    before the first step: a warm incarnation DESERIALIZES the
    compiled executable instead of re-tracing (the PR 10 budget's
    dominant term), a cold one traces once and writes the entry so
    the next incarnation hits.  With a
    :class:`~dlrover_tpu.trainer.recovery.RecoveryProfiler` the
    resolve books the ``aot``/``retrace`` budget phases and emits the
    ``aot_cache``/``compile_cache`` witnesses; without one it still
    returns a ready step (plain :func:`aot_cache.resolve_step`).
    Examples may be concrete arrays or :func:`abstract_like` trees.
    Always safe: any cache problem falls back to tracing."""
    args = (example_state, example_batch)
    if profiler is not None:
        return profiler.resolve_step(
            step_fn, args, label=label, restore_busy=restore_busy
        )
    from dlrover_tpu.common import aot_cache

    return aot_cache.resolve_step(step_fn, args, label=label).fn


def resolve_train_step_async(
    step_fn,
    example_builder: Callable,
    profiler,
    label: str = "train_step",
    restore_busy=None,
) -> Callable:
    """:func:`resolve_train_step` on a daemon thread — the recovery
    posture.  ``example_builder`` is a zero-arg callable returning
    ``(abstract_state, abstract_batch)`` (so even the ``eval_shape``
    cost overlaps); the returned ``join()`` yields the step and books
    the ``aot`` phase as the join wait — the seconds the critical
    path actually stalled, which on a warm cache rounds to zero
    because the deserialize hid behind the restore read and the
    model/state build."""
    return profiler.resolve_step_async(
        step_fn,
        example_builder,
        label=label,
        restore_busy=restore_busy,
    )


class ElasticTrainer:
    """Step/epoch accounting with a fixed global batch across resizes
    (reference: trainer.py GradientState + _ElasticOptimizer)."""

    def __init__(
        self,
        global_batch_size: int,
        micro_batch_size: int,
        dp_size: Optional[int] = None,
        metrics_path: Optional[str] = None,
    ):
        self.global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size
        self.dp_size = dp_size or env_utils.get_world_size()
        if global_batch_size % (micro_batch_size * self.dp_size):
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"micro {micro_batch_size} x dp {self.dp_size}"
            )
        self.grad_accum = global_batch_size // (
            micro_batch_size * self.dp_size
        )
        self.global_step = 0
        self._metrics_path = metrics_path or os.getenv(
            "DLROVER_METRICS_FILE",
            os.path.join("/tmp", f"dlrover_metrics_{os.getuid()}.json"),
        )
        self._epoch = 0
        self._restart_count = env_utils.get_restart_count()
        # always-on step-phase profiler: report_step() closes the
        # current step's breakdown and ships it (event + histogram +
        # metrics-file record for the agent's collectors)
        self.profiler = StepPhaseProfiler()
        self.last_step_phases: Dict[str, float] = {}
        _GRAD_ACCUM_GAUGE.set(self.grad_accum)
        logger.info(
            "elastic trainer: global_batch=%s micro=%s dp=%s accum=%s",
            global_batch_size, micro_batch_size, self.dp_size,
            self.grad_accum,
        )

    @property
    def local_batch_size(self) -> int:
        """Samples this data-parallel rank consumes per step."""
        return self.micro_batch_size * self.grad_accum

    def profile(self, name: str):
        """``with trainer.profile("data_wait"): batch = next(it)`` —
        see :class:`StepPhaseProfiler`.  For the compute phase,
        ``with trainer.profile("compute") as p: state, m = step(...);
        p.block(m)`` brackets the device work with
        ``block_until_ready``."""
        return self.profiler.phase(name)

    def report_step(self, metrics: Optional[Dict[str, float]] = None):
        """Advance the step counter and write the metrics file the
        agent monitor tails (reference: trainer.py report to file +
        monitor/training.py)."""
        report_start = time.perf_counter()
        self.global_step += 1
        _REPORTED_STEP.set(self.global_step)
        # per-step training event: this is what lets the chaos
        # invariant checkers compute "steps lost across a fault" from
        # the event log alone (no-op unless an event log is configured)
        step_event = {
            "step": self.global_step,
            "restart_count": self._restart_count,
            # which node stepped: multi-agent chaos invariants decide
            # per-node progress from the event log alone
            "node_rank": env_utils.get_node_rank(),
        }
        if metrics and "loss" in metrics:
            # the elastic-resize loss-trajectory invariant compares
            # same-step losses across incarnations and world sizes —
            # a resharded restore that mangled the params shows up
            # as a divergence here, decided from the log alone
            try:
                step_event["loss"] = float(metrics["loss"])
            except (TypeError, ValueError):
                pass
        emit_event("train_step", **step_event)
        # chaos hook AFTER the event: a kill rule at step N must leave
        # step N's completion in the log before the process dies; a
        # slow rule stretches the observable step time (straggler)
        _chaos.fire("trainer.step", step=self.global_step)
        # close the step's phase breakdown: everything since the last
        # report (minus profiled phases) is "other"; the report path
        # itself (event + chaos hook) is booked as "report"
        self.profiler.add(
            "report", time.perf_counter() - report_start
        )
        phases = self.profiler.finish_step()
        self.last_step_phases = phases
        for name, seconds in phases.items():
            if name == "total_s":
                continue
            _STEP_PHASE_SECONDS.observe(
                seconds,
                phase="other" if name == "other_s" else name,
            )
        # dict-build instead of kwargs so a user phase named "step"
        # can never collide with the envelope fields
        emit_event("step_phases", **{
            **phases,
            "step": self.global_step,
            "node_rank": env_utils.get_node_rank(),
        })
        record = {
            "global_step": self.global_step,
            "timestamp": time.time(),
            "epoch": self._epoch,
            # the agent's StepPhaseCollector ships these to the
            # master's diagnosis chain (data-starved detection)
            "phases": phases,
        }
        if metrics:
            record.update(
                {
                    k: float(v)
                    for k, v in metrics.items()
                    if jnp.isscalar(v) or getattr(v, "ndim", 1) == 0
                }
            )
        tmp = self._metrics_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self._metrics_path)
        except OSError as e:
            logger.debug("metrics file write failed: %s", e)

    def set_epoch(self, epoch: int):
        self._epoch = epoch

    def state_dict(self) -> Dict[str, int]:
        return {"global_step": self.global_step, "epoch": self._epoch}

    def load_state_dict(self, state: Dict[str, int]):
        self.global_step = int(state.get("global_step", 0))
        self._epoch = int(state.get("epoch", 0))


def init_jax_distributed():
    """Initialize multi-host JAX from the agent's env contract
    (reference analog: dist.init_process_group with MASTER_ADDR/PORT
    set by the agent, training.py:430-447)."""
    coordinator = env_utils.get_coordinator_addr()
    num_processes = int(
        os.getenv("DLROVER_NUM_PROCESSES", "1")
    )
    if not coordinator or num_processes <= 1:
        return False
    process_id = int(os.getenv("DLROVER_PROCESS_ID", "0"))
    jax_compat.ensure_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed initialized: process %s/%s via %s",
        process_id, num_processes, coordinator,
    )
    return True
