"""Fleet runner: hundreds of synthetic agents vs one real master.

The master is the PRODUCTION object — a journal-backed
:class:`~dlrover_tpu.master.master.JobMaster` with its servicer,
rendezvous managers, task manager, speed monitor and (optionally)
Brain datastore — served over the real socket transport.  Only the
agents are synthetic.  The runner:

- ramps :class:`~dlrover_tpu.fleet.synthetic_agent.SyntheticAgent`
  counts up/down while a
  :class:`~dlrover_tpu.fleet.scoreboard.Scoreboard` watches;
- drives the master-side maintenance the run loop would do (SLO
  check, resize poll, Brain ingest) at harness cadence — same code
  paths, observable timing;
- performs the **SLO-green capacity search**: step the agent count
  until a windowed SLO rule breaches, back off one step, confirm the
  level holds green, and report the max sustained agents with the
  per-verb p99 at that capacity (emitted as a ``fleet_capacity``
  event and surfaced as the ``fleet_control_plane`` bench section);
- sweeps ``DLROVER_JOURNAL_FSYNC_WINDOW_S`` under fixed load to size
  the journal group-commit window from measured append p99.
"""

import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.fleet.scoreboard import Scoreboard
from dlrover_tpu.fleet.synthetic_agent import (
    AgentProfile,
    SyntheticAgent,
)
from dlrover_tpu.telemetry.events import emit_event

# the sweep's measured answer on the CI box (see the
# fleet_control_plane bench section): 0.05 s batches the fsync storm
# without letting a power cut eat more than 50 ms of non-terminal
# records (SIGKILL still loses nothing; DURABLE_KINDS always fsync).
# StateJournal's own default stays 0 — full per-append durability —
# so arming the window is an explicit, informed choice.
INFORMED_FSYNC_WINDOW_S = 0.05


class FleetRunner:
    """Owns one real master + a ramping population of synthetic
    agents + the scoreboard watching both."""

    def __init__(
        self,
        max_nodes: int = 512,
        profile: Optional[AgentProfile] = None,
        workdir: Optional[str] = None,
        journal: bool = True,
        fsync_window_s: Optional[float] = None,
        piggyback: bool = False,
        scoreboard_interval_s: float = 1.0,
        rules=None,
        brain_db: str = "",
        master_factory: Optional[Callable] = None,
        pack_size: int = 0,
    ):
        """``piggyback`` arms ``DLROVER_STEP_PIGGYBACK`` for every
        agent the runner creates (process-wide env — the before/after
        comparison runs two runners, not two modes in one).
        ``fsync_window_s`` sets the master journal's group-commit
        window (None = journal default, i.e. per-append fsync).
        ``master_factory`` overrides master construction for tests.
        ``pack_size`` > 0 hosts agents in SUBPROCESS packs of up to
        that many instead of in-process threads: at hundreds of
        agents the threads would fight the master for the GIL and
        the scoreboard would measure the harness, not the control
        plane."""
        self.max_nodes = int(max_nodes)
        self.profile = profile or AgentProfile()
        self._own_workdir = workdir is None
        self.workdir = workdir or tempfile.mkdtemp(
            prefix="dlrover_fleet_"
        )
        self._env_backup: Dict[str, Optional[str]] = {}
        self._set_env(
            "DLROVER_STEP_PIGGYBACK", "1" if piggyback else ""
        )
        # the harness hammers reconnects on purpose: keep client
        # retry envelopes tight so refused requests surface as error
        # counts, not multi-second stalls
        self._set_env("DLROVER_RPC_RETRIES", "3")
        self._set_env("DLROVER_RPC_BACKOFF_BASE", "0.05")
        self._set_env("DLROVER_RPC_BACKOFF_MAX", "0.5")
        self._set_env("DLROVER_MASTER_RESYNC_TIMEOUT", "5")
        if fsync_window_s is not None:
            self._set_env(
                "DLROVER_JOURNAL_FSYNC_WINDOW_S",
                str(fsync_window_s),
            )
        if brain_db:
            self._set_env("DLROVER_BRAIN_DB", brain_db)
        journal_dir = (
            os.path.join(self.workdir, "journal") if journal else None
        )
        if master_factory is not None:
            self.master = master_factory(journal_dir)
        else:
            from dlrover_tpu.master.master import JobMaster

            self.master = JobMaster(
                port=0,
                node_num=self.max_nodes,
                job_name="fleet",
                journal_dir=journal_dir,
                min_node_num=1,
            )
        # rounds re-form on a short timeout instead of waiting for
        # max_nodes: a ramping fleet keeps producing
        # rendezvous_complete rounds the way elastic churn would
        for mngr in self.master.rdzv_managers.values():
            mngr.update_rdzv_params(
                min_nodes=1,
                max_nodes=self.max_nodes,
                waiting_timeout=2.0,
            )
        self.master.prepare()
        self.addr = f"127.0.0.1:{self.master.port}"
        self.agents: List[SyntheticAgent] = []
        self.pack_size = max(0, int(pack_size))
        # pack mode: [{proc, count, stats_path}]
        self._packs: List[Dict] = []
        self._pack_seq = 0
        self.scoreboard = Scoreboard(
            interval_s=scoreboard_interval_s,
            rules=rules,
            agents_fn=lambda: (
                len(self.agents) + self._pack_counts()
            ),
        )
        self._next_node_id = 0
        self._dataset_registered = False
        self._stopped = False

    # -- env hygiene -------------------------------------------------------

    def _set_env(self, key: str, value: str):
        if key not in self._env_backup:
            self._env_backup[key] = os.environ.get(key)
        if value == "":
            os.environ.pop(key, None)
        else:
            os.environ[key] = value

    def _restore_env(self):
        for key, old in self._env_backup.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old
        self._env_backup = {}

    # -- population --------------------------------------------------------

    def _register_dataset(self):
        if self._dataset_registered:
            return
        boot = SyntheticAgent(
            self.addr, node_id=10_000_000, profile=self.profile
        )
        boot.client.report_dataset_shard_params(
            batch_size=1,
            num_epochs=1_000_000,
            dataset_size=4096,
            shuffle=False,
            num_minibatches_per_shard=1,
            dataset_name=self.profile.dataset,
        )
        boot.client.close()
        self._dataset_registered = True

    # -- subprocess packs --------------------------------------------------

    def _pack_counts(self) -> int:
        # prune packs that died unexpectedly (spawn failure, OOM):
        # counting phantom agents would let a capacity probe claim a
        # level no real load ever exercised
        dead = [
            p for p in self._packs
            if p["proc"].poll() is not None
        ]
        for pack in dead:
            logger.warning(
                "agent pack (%d agents) died unexpectedly (rc=%s); "
                "pruned", pack["count"], pack["proc"].returncode,
            )
            self._packs.remove(pack)
        return sum(p["count"] for p in self._packs)

    def _spawn_pack(self, count: int, timeout_s: float = 30.0) -> bool:
        pack_id = self._pack_seq
        self._pack_seq += 1
        stats_path = os.path.join(
            self.workdir, f"pack_{pack_id}.json"
        )
        start_id = self._next_node_id
        self._next_node_id += count
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "dlrover_tpu.fleet.agent_pack",
                "--addr", self.addr,
                "--start-id", str(start_id),
                "--count", str(count),
                "--stats", stats_path,
                "--profile", json.dumps(
                    dataclasses.asdict(self.profile)
                ),
            ],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        pack = {
            "proc": proc, "count": count, "stats_path": stats_path,
        }
        self._packs.append(pack)
        # wait until the pack reports its agents started: a level
        # probe must not begin while a pack is still importing
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            doc = self._read_pack_stats(stats_path)
            if doc and doc.get("ready"):
                return True
            if proc.poll() is not None:
                # never count a stillborn pack toward the population
                logger.warning(
                    "agent pack %s died at start (rc=%s)",
                    pack_id, proc.returncode,
                )
                self._packs.remove(pack)
                return False
            time.sleep(0.1)
        logger.warning("agent pack %s slow to start", pack_id)
        return True

    @staticmethod
    def _read_pack_stats(path: str) -> Optional[Dict]:
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _stop_pack(self, pack: Dict, timeout_s: float = 8.0):
        proc = pack["proc"]
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)

    def _ramp_packs(self, n: int):
        # shrink by whole packs (their final stats files keep the
        # cumulative op accounting), then top back up with a pack
        # sized to the exact deficit — the population always matches
        # the requested level, even when n is not a pack multiple
        while self._pack_counts() > n and self._packs:
            pack = self._packs.pop()
            self._stop_pack(pack)
        while self._pack_counts() < n:
            deficit = n - self._pack_counts()
            if not self._spawn_pack(min(self.pack_size, deficit)):
                break  # spawn failing repeatedly: do not spin

    def ramp_to(self, n: int, stagger_s: float = 0.01):
        """Grow or shrink the live agent population to ``n``.
        Starts are staggered (``stagger_s`` between agents; packs
        stagger internally) so a level change models a rolling
        deployment, not a thundering herd of simultaneous joins —
        the steady-state window is what the capacity search
        judges."""
        n = max(0, min(int(n), self.max_nodes))
        self._register_dataset()
        if self.pack_size > 0:
            self._ramp_packs(n)
            return
        while len(self.agents) > n:
            agent = self.agents.pop()
            agent.stop(join_timeout=2.0)
        started = []
        while len(self.agents) + len(started) < n:
            agent = SyntheticAgent(
                self.addr,
                node_id=self._next_node_id,
                profile=self.profile,
            )
            self._next_node_id += 1
            agent.start()
            started.append(agent)
            if stagger_s > 0:
                time.sleep(stagger_s)
        self.agents.extend(started)

    def _master_maintenance(self):
        """What the master run loop does every poll, at harness
        cadence: SLO evaluation, resize decisions, Brain ingest."""
        try:
            self.master.slo_checker.check()
        except Exception:  # noqa: BLE001
            logger.exception("fleet: SLO check failed")
        try:
            self.master.resize_coordinator.poll()
        except Exception:  # noqa: BLE001
            logger.exception("fleet: resize poll failed")
        try:
            self.master.maybe_brain_ingest()
        except Exception:  # noqa: BLE001
            logger.exception("fleet: brain ingest failed")

    def run_load(
        self, agents: int, duration_s: float,
        settle_s: float = 0.5,
    ) -> Dict:
        """Hold ``agents`` for ``duration_s`` and return the
        scoreboard summary over that window only."""
        self.ramp_to(agents)
        time.sleep(max(0.0, settle_s))
        self.scoreboard.reset_window()
        n_before = len(self.scoreboard.samples)
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            step = min(
                self.scoreboard.interval_s,
                max(0.05, deadline - time.monotonic()),
            )
            time.sleep(step)
            self.scoreboard.sample()
            self._master_maintenance()
        return self.scoreboard.summary(
            last_n=len(self.scoreboard.samples) - n_before
        )

    # -- capacity search ---------------------------------------------------

    def capacity_search(
        self,
        start: int = 25,
        step: int = 25,
        max_agents: Optional[int] = None,
        window_s: float = 4.0,
        settle_s: float = 1.0,
        deadline_s: float = 300.0,
        confirm: bool = True,
    ) -> Dict:
        """SLO-green capacity search: step the agent count until a
        windowed rule breaches, back off one step, confirm green,
        report the max sustained agents + per-verb p99 at capacity.

        A level is *green* when its whole window produced no
        windowed-quantile breach AND agent-side errors stayed under
        1% of ops (a master that answers fast by refusing work is
        not green)."""
        t0 = time.monotonic()
        max_agents = min(
            max_agents or self.max_nodes, self.max_nodes
        )
        levels: List[Dict] = []
        last_green: Optional[Dict] = None
        breached: Optional[Dict] = None
        n = start
        while n <= max_agents:
            remaining = deadline_s - (time.monotonic() - t0)
            if remaining < window_s + settle_s:
                logger.warning(
                    "fleet capacity search: deadline reached at "
                    "%d agents", n,
                )
                break
            level = self._probe_level(n, window_s, settle_s)
            levels.append(level)
            if level["green"]:
                last_green = level
                n += step
            else:
                breached = level
                break
        if confirm and breached is not None:
            # back off and hold: "green on the way up" could be a
            # warmup artifact — capacity is the level that holds
            # green AFTER the breach backed us off.  A failed
            # confirm keeps stepping DOWN (never re-promotes a
            # ramp-up green it could not reproduce)
            n_conf = last_green["agents"] if last_green else 0
            last_green = None
            while n_conf >= max(1, start):
                if (
                    deadline_s - (time.monotonic() - t0)
                    < window_s + settle_s
                ):
                    break
                lvl = self._probe_level(n_conf, window_s, settle_s)
                lvl["confirm"] = True
                levels.append(lvl)
                if lvl["green"]:
                    last_green = lvl
                    break
                n_conf -= step
        result = {
            "max_sustained_agents": (
                last_green["agents"] if last_green else 0
            ),
            "p99_at_capacity_ms": (
                last_green["worst_p99_ms"] if last_green else {}
            ),
            "rps_at_capacity": (
                last_green["mean_rps"] if last_green else 0.0
            ),
            "first_breach": (
                {
                    "agents": breached["agents"],
                    "breaches": breached["breaches"],
                }
                if breached else None
            ),
            "levels": [
                {
                    k: lvl[k] for k in (
                        "agents", "green", "mean_rps",
                        "error_ratio", "breach_count",
                    )
                }
                for lvl in levels
            ],
            "search_s": round(time.monotonic() - t0, 1),
        }
        emit_event(
            "fleet_capacity",
            max_sustained_agents=result["max_sustained_agents"],
            rps_at_capacity=result["rps_at_capacity"],
            levels=len(levels),
            search_s=result["search_s"],
            first_breach_agents=(
                breached["agents"] if breached else -1
            ),
        )
        return result

    def _probe_level(
        self, n: int, window_s: float, settle_s: float
    ) -> Dict:
        """Hold ``n`` agents and judge the level over ONE window
        spanning the whole hold (the scoreboard's per-second samples
        keep flowing for fleet_report, but a 1 s window cannot clear
        min_count for low-rate verbs — the probe window can)."""
        self.ramp_to(n)
        time.sleep(max(0.0, settle_s))
        ops_before, errs_before = self._fleet_ops()
        self.scoreboard.reset_window()
        self.scoreboard.begin_probe()
        deadline = time.monotonic() + window_s
        while time.monotonic() < deadline:
            step = min(
                self.scoreboard.interval_s,
                max(0.05, deadline - time.monotonic()),
            )
            time.sleep(step)
            self.scoreboard.sample()
            self._master_maintenance()
        probe = self.scoreboard.end_probe()
        ops_after, errs_after = self._fleet_ops()
        d_ops = max(1, ops_after - ops_before)
        d_errs = max(0, errs_after - errs_before)
        error_ratio = d_errs / (d_ops + d_errs)
        breach_count = len(probe["breaches"])
        green = breach_count == 0 and error_ratio < 0.01
        level = {
            "agents": n,
            "green": green,
            "mean_rps": round(probe["ops"] / window_s, 2),
            "worst_p99_ms": probe["worst_p99_ms"],
            "error_ratio": round(error_ratio, 4),
            "breach_count": breach_count,
            "breaches": probe["breaches"][:5],
        }
        logger.info(
            "fleet level %d agents: %s (rps=%.0f, errors=%.2f%%, "
            "breaches=%d)",
            n, "GREEN" if green else "BREACH",
            level["mean_rps"], error_ratio * 100, breach_count,
        )
        return level

    def _fleet_ops(self):
        ops = sum(a.stats.total_ops for a in self.agents)
        errs = sum(a.stats.total_errors for a in self.agents)
        for doc in self._all_pack_stats():
            ops += sum(doc.get("ops", {}).values())
            errs += sum(doc.get("errors", {}).values())
        return ops, errs

    def _all_pack_stats(self) -> List[Dict]:
        """Latest stats of every pack EVER spawned (stopped packs'
        final files included — op totals are cumulative, so deltas
        across a level stay correct through ramp-downs)."""
        out = []
        seen = set()
        for pack in self._packs:
            seen.add(pack["stats_path"])
            doc = self._read_pack_stats(pack["stats_path"])
            if doc:
                out.append(doc)
        # stopped packs left their final stats on disk
        try:
            for name in os.listdir(self.workdir):
                if not (
                    name.startswith("pack_")
                    and name.endswith(".json")
                ):
                    continue
                path = os.path.join(self.workdir, name)
                if path in seen:
                    continue
                doc = self._read_pack_stats(path)
                if doc:
                    out.append(doc)
        except OSError:
            pass
        return out

    # -- teardown ----------------------------------------------------------

    def stats(self) -> Dict:
        ops: Dict[str, int] = {}
        errs: Dict[str, int] = {}
        resyncs = 0
        for a in self.agents:
            for verb, c in a.stats.ops.items():
                ops[verb] = ops.get(verb, 0) + c
            for verb, c in a.stats.errors.items():
                errs[verb] = errs.get(verb, 0) + c
            resyncs += a.stats.resyncs
        for doc in self._all_pack_stats():
            for verb, c in doc.get("ops", {}).items():
                ops[verb] = ops.get(verb, 0) + c
            for verb, c in doc.get("errors", {}).items():
                errs[verb] = errs.get(verb, 0) + c
            resyncs += doc.get("resyncs", 0)
        return {"ops": ops, "errors": errs, "resyncs": resyncs}

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self.scoreboard.stop(final_sample=False)
        for agent in self.agents:
            agent._stop.set()
        for agent in self.agents:
            agent.stop(join_timeout=2.0)
        self.agents = []
        for pack in self._packs:
            self._stop_pack(pack)
        self._packs = []
        try:
            self.master.stop()
        except Exception:  # noqa: BLE001
            logger.exception("fleet master stop failed")
        self._restore_env()
        if self._own_workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)


def sweep_fsync_window(
    windows: Sequence[float] = (0.0, 0.01, 0.05, 0.25),
    agents: int = 50,
    duration_s: float = 4.0,
    profile: Optional[AgentProfile] = None,
    max_nodes: int = 512,
    pack_size: int = 0,
) -> Dict:
    """Size ``DLROVER_JOURNAL_FSYNC_WINDOW_S`` under fleet load: one
    fresh journal-backed master per window value, identical agent
    load, measured journal append p99 (the windowed
    ``dlrover_master_journal_fsync_seconds`` view).  Returns per-
    window numbers and the smallest window achieving within 20% of
    the best p99 — more batching than that buys latency nothing and
    only widens the power-cut exposure."""
    results: List[Dict] = []
    for w in windows:
        runner = FleetRunner(
            max_nodes=max_nodes,
            profile=profile,
            fsync_window_s=w,
            pack_size=pack_size,
        )
        try:
            summary = runner.run_load(agents, duration_s)
            results.append({
                "window_s": w,
                "append_p99_ms": summary.get(
                    "journal_append_p99_ms", 0.0
                ),
                "lock_wait_p99_ms": summary.get(
                    "journal_lock_wait_p99_ms", 0.0
                ),
                "mean_rps": summary.get("mean_rps", 0.0),
            })
        finally:
            runner.stop()
    measured = [
        r for r in results if r["append_p99_ms"] > 0
    ] or results
    best = min(r["append_p99_ms"] for r in measured)
    chosen = measured[0]["window_s"]
    for r in measured:
        if r["append_p99_ms"] <= best * 1.2:
            chosen = r["window_s"]
            break
    return {
        "windows": results,
        "chosen_window_s": chosen,
        "informed_default_s": INFORMED_FSYNC_WINDOW_S,
    }
