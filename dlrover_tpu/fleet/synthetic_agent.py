"""Synthetic elastic agent: the production verb mix without a trainer.

One :class:`SyntheticAgent` is one simulated node driving a REAL
:class:`~dlrover_tpu.agent.master_client.MasterClient` (the full
transport: framed pickles, retries, response cache, session resync) —
not a mock and not raw sockets, so what the scoreboard measures is
what production agents would pay.  The verb mix mirrors what an
elastic agent + its trainer put on the wire:

- ``join_rendezvous`` once at start (and again after a forced
  reconnect when the fault mix says so);
- ``HeartbeatRequest`` on the heartbeat cadence (liveness + the
  master's action channel);
- ``GlobalStepRecord`` on the step cadence — or piggybacked onto
  heartbeats when ``DLROVER_STEP_PIGGYBACK`` is armed (the measured
  fan-in fix);
- shard lease/ack (``GetShardTaskRequest`` /
  ``ReportTaskResultRequest``) on the shard cadence;
- KV set/add barriers on the kv cadence;
- fault mix: with ``reconnect_prob`` per tick the agent drops its TCP
  connection and replays the session-resync handshake — the
  master-crash-recovery path under load.

Cadences are jittered (uniform ±``jitter`` fraction) so a fleet of
agents does not phase-lock into request stampedes the way identical
timers would.
"""

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import default_logger as logger

# default dataset every fleet agent leases shards from (the runner
# registers it once with an effectively inexhaustible epoch count)
FLEET_DATASET = "fleet-shards"


@dataclass
class AgentProfile:
    """Cadence + fault mix of one synthetic agent (seconds)."""

    heartbeat_interval: float = 1.0
    step_interval: float = 0.5
    shard_interval: float = 2.0
    kv_interval: float = 4.0
    # uniform jitter as a fraction of each interval (0.3 = ±30%)
    jitter: float = 0.3
    # per-tick probability of a forced TCP drop + session resync
    reconnect_prob: float = 0.0
    dataset: str = FLEET_DATASET

    def jittered(self, interval: float, rng: random.Random) -> float:
        if self.jitter <= 0:
            return interval
        return interval * (
            1.0 + rng.uniform(-self.jitter, self.jitter)
        )


@dataclass
class AgentStats:
    """Per-agent op/error accounting the runner aggregates."""

    ops: Dict[str, int] = field(default_factory=dict)
    errors: Dict[str, int] = field(default_factory=dict)
    resyncs: int = 0
    actions_seen: int = 0
    last_step: int = 0

    def op(self, verb: str):
        self.ops[verb] = self.ops.get(verb, 0) + 1

    def err(self, verb: str):
        self.errors[verb] = self.errors.get(verb, 0) + 1

    @property
    def total_ops(self) -> int:
        return sum(self.ops.values())

    @property
    def total_errors(self) -> int:
        return sum(self.errors.values())


class SyntheticAgent:
    """One simulated node's control-plane life, on its own thread."""

    def __init__(
        self,
        master_addr: str,
        node_id: int,
        profile: Optional[AgentProfile] = None,
        seed: Optional[int] = None,
    ):
        self.node_id = int(node_id)
        self.profile = profile or AgentProfile()
        self.stats = AgentStats()
        self._rng = random.Random(
            seed if seed is not None else node_id
        )
        # a real client per agent: node_rank/local_world_size pinned
        # explicitly (hundreds of clients share one process env)
        self.client = MasterClient(
            master_addr,
            node_id=self.node_id,
            node_type="worker",
            node_rank=self.node_id,
            local_world_size=1,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(
            target=self._run,
            name=f"fleet-agent-{self.node_id}",
            daemon=True,
        )
        self._thread.start()

    def stop(self, join_timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- verb helpers ------------------------------------------------------

    def _call(self, verb: str, fn, *args, **kwargs):
        """One counted op; errors are tallied, never fatal — a load
        generator that dies on the first refused request measures
        nothing."""
        if self._stop.is_set():
            return None
        try:
            out = fn(*args, **kwargs)
            self.stats.op(verb)
            return out
        except Exception as e:  # noqa: BLE001 - tally and march on
            self.stats.err(verb)
            logger.debug(
                "fleet agent %s %s failed: %s", self.node_id, verb, e
            )
            return None

    def _join(self):
        self._call(
            "join",
            self.client.join_rendezvous,
            self.node_id,
            1,
            RendezvousName.ELASTIC_TRAINING,
            node_ip="127.0.0.1",
        )

    def _heartbeat(self):
        action = self._call(
            "heartbeat", self.client.report_heartbeat
        )
        if action:
            self.stats.actions_seen += 1

    def _report_step(self):
        self._step += 1
        self.stats.last_step = self._step
        self._call(
            "step", self.client.report_global_step, self._step
        )

    def _shard_cycle(self):
        task = self._call(
            "shard_get", self.client.get_task, self.profile.dataset
        )
        task_id = getattr(task, "task_id", -1)
        if task is None or task_id < 0:
            return
        self._call(
            "shard_ack",
            self.client.report_task_result,
            self.profile.dataset,
            task_id,
            True,
        )

    def _kv_cycle(self):
        # distinct namespaces: barrier counters must never collide
        # with opaque blob sets on the same key
        if self._rng.random() < 0.5:
            self._call(
                "kv", self.client.kv_store_add,
                f"fleet/ctr/{self.node_id % 16}", 1,
            )
        else:
            self._call(
                "kv", self.client.kv_store_set,
                f"fleet/blob/{self.node_id % 16}", b"x",
            )

    def force_reconnect(self):
        """Fault mix: drop the TCP connection mid-session and replay
        the session-resync handshake — what a master respawn (or a
        broken middlebox) makes every real agent do."""
        try:
            self.client._client.close()
        except Exception:  # noqa: BLE001
            pass
        errs_before = self.stats.errors.get("resync", 0)
        self._call("resync", self.client.session_resync)
        if self.stats.errors.get("resync", 0) == errs_before:
            self.stats.resyncs += 1

    # -- main loop ---------------------------------------------------------

    def _run(self):
        p = self.profile
        self._join()
        now = time.monotonic()
        due = {
            "heartbeat": now + p.jittered(
                p.heartbeat_interval * self._rng.random() + 1e-3,
                self._rng,
            ),
            "step": now + p.jittered(
                p.step_interval * self._rng.random() + 1e-3,
                self._rng,
            ),
            "shard": now + p.jittered(
                p.shard_interval * self._rng.random() + 1e-3,
                self._rng,
            ),
            "kv": now + p.jittered(
                p.kv_interval * self._rng.random() + 1e-3, self._rng
            ),
        }
        intervals = {
            "heartbeat": p.heartbeat_interval,
            "step": p.step_interval,
            "shard": p.shard_interval,
            "kv": p.kv_interval,
        }
        actions = {
            "heartbeat": self._heartbeat,
            "step": self._report_step,
            "shard": self._shard_cycle,
            "kv": self._kv_cycle,
        }
        while not self._stop.is_set():
            now = time.monotonic()
            for name, when in due.items():
                if self._stop.is_set():
                    break
                if now >= when:
                    actions[name]()
                    due[name] = now + p.jittered(
                        intervals[name], self._rng
                    )
            if (
                p.reconnect_prob > 0
                and not self._stop.is_set()
                and self._rng.random() < p.reconnect_prob
            ):
                self.force_reconnect()
            next_due = min(due.values())
            delay = max(0.0, next_due - time.monotonic())
            self._stop.wait(min(delay, 0.25))
        # close() drains any coalesced step itself; a second explicit
        # flush here would pay the retry envelope twice when the
        # master is already gone at teardown
        self.client.close()
