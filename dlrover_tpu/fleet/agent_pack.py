"""Subprocess host for a pack of synthetic agents.

In-process agent threads are fine for a smoke test, but at hundreds
of agents they fight the MASTER for the GIL — the scoreboard ends up
measuring the harness, not the control plane.  Pack mode moves the
agents out: the runner spawns a few of these processes, each hosting
``--count`` agent threads, and reads their op/error accounting from
the atomically-rewritten ``--stats`` JSON file.  A pack runs until
SIGTERM/SIGINT (or until orphaned) and drains its agents cleanly.

Runnable standalone against any master::

    python -m dlrover_tpu.fleet.agent_pack \
        --addr 127.0.0.1:12345 --start-id 0 --count 50 \
        --stats /tmp/pack0.json
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

from dlrover_tpu.fleet.synthetic_agent import (
    AgentProfile,
    SyntheticAgent,
)


def _write_stats(path: str, agents, ready: bool):
    ops = {}
    errors = {}
    resyncs = 0
    for a in agents:
        for verb, c in a.stats.ops.items():
            ops[verb] = ops.get(verb, 0) + c
        for verb, c in a.stats.errors.items():
            errors[verb] = errors.get(verb, 0) + c
        resyncs += a.stats.resyncs
    doc = {
        "agents": len(agents),
        "ready": ready,
        "ops": ops,
        "errors": errors,
        "resyncs": resyncs,
        "pid": os.getpid(),
        "ts": time.time(),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="host a pack of synthetic fleet agents"
    )
    parser.add_argument("--addr", required=True)
    parser.add_argument("--start-id", type=int, required=True)
    parser.add_argument("--count", type=int, required=True)
    parser.add_argument("--stats", required=True)
    parser.add_argument(
        "--profile", default="{}",
        help="AgentProfile fields as JSON",
    )
    parser.add_argument("--stagger-s", type=float, default=0.005)
    parser.add_argument(
        "--stats-interval-s", type=float, default=0.5
    )
    args = parser.parse_args(argv)

    profile = AgentProfile(**json.loads(args.profile))
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_a: stop.set())

    agents = []
    for i in range(args.count):
        agent = SyntheticAgent(
            args.addr, node_id=args.start_id + i, profile=profile
        )
        agent.start()
        agents.append(agent)
        if args.stagger_s > 0:
            time.sleep(args.stagger_s)
    _write_stats(args.stats, agents, ready=True)

    while not stop.wait(args.stats_interval_s):
        if os.getppid() == 1:
            break  # orphaned: the runner died without cleanup
        try:
            _write_stats(args.stats, agents, ready=True)
        except OSError:
            pass
    for agent in agents:
        agent._stop.set()
    for agent in agents:
        agent.stop(join_timeout=2.0)
    try:
        _write_stats(args.stats, agents, ready=False)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
