"""Fleet observatory: synthetic-agent load harness + control-plane
scoreboard + SLO-green capacity search.

The reference system's master is a cluster-scale hub serving hundreds
of elastic agents; everything this repo measured before this package
ran at 1-3 nodes.  The harness closes that gap without hardware:

- :class:`~dlrover_tpu.fleet.synthetic_agent.SyntheticAgent` drives a
  REAL :class:`~dlrover_tpu.agent.master_client.MasterClient` through
  the production verb mix (rendezvous join, heartbeats, step/speed
  reports, shard lease/ack, KV barriers, session resync after forced
  reconnects) with configurable cadence, jitter and fault mix;
- :class:`~dlrover_tpu.fleet.runner.FleetRunner` ramps hundreds of
  them against ONE real journal-backed master in-process and performs
  the SLO-green capacity search (max sustained agents);
- :class:`~dlrover_tpu.fleet.scoreboard.Scoreboard` watches the
  control plane while they run: windowed per-verb latency quantiles
  over ``dlrover_rpc_seconds``, servicer in-flight, connection
  fan-in, journal append lock-wait and fsync-batch depth — emitted as
  periodic ``fleet_report`` events that feed the timeline/report
  pipeline.
"""

from dlrover_tpu.fleet.runner import FleetRunner
from dlrover_tpu.fleet.scoreboard import Scoreboard
from dlrover_tpu.fleet.synthetic_agent import (
    AgentProfile,
    SyntheticAgent,
)

__all__ = [
    "AgentProfile",
    "FleetRunner",
    "Scoreboard",
    "SyntheticAgent",
]
