"""Synthetic lookup load against the serving-fleet router.

Sibling of :class:`~dlrover_tpu.fleet.synthetic_agent.SyntheticAgent`
for the serving plane: one :class:`SyntheticLookupAgent` is one
simulated user-traffic stream driving REAL
:class:`~dlrover_tpu.common.comm.MessageClient` lookups through the
:class:`~dlrover_tpu.serving.router.LookupRouter` — the full routed
path (framed pickles, key-consistent owner choice, fallback, drain
shifts), not a mock.  The harness counts per-outcome results and
client-visible failures, which is exactly the material the
zero-failed-lookup chaos invariant and the ``serving_fleet`` bench
section assert.

Client retries are part of the model: a stream's transport retries a
dropped/unanswered request against the (re)spawned router with the
same request id — so a router kill/respawn under live load shows up
as latency, never as a failed lookup, unless the retry envelope is
exhausted.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from dlrover_tpu.common.comm import MessageClient
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.serving.messages import LookupRequest


@dataclass
class LookupStats:
    """Per-stream accounting the harness aggregates."""

    lookups: int = 0
    rows: int = 0
    failures: int = 0
    outcomes: Dict[str, int] = field(default_factory=dict)
    latencies_s: List[float] = field(default_factory=list)
    max_generation: int = -1
    generation_regressions: int = 0
    # consecutive lookups may land on different replicas mid-catch-up;
    # the router admits members within ``stale_slack`` generations of
    # the floor, so per-stream skew up to the slack is by design — a
    # REGRESSION is only a step beyond it
    generation_slack: int = 1
    _last_generation: int = -1

    def record(self, outcome: str, generation: int, dt: float,
               rows: int):
        self.lookups += 1
        self.rows += rows
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        self.latencies_s.append(dt)
        if generation >= 0:
            if generation < self._last_generation - \
                    self.generation_slack:
                self.generation_regressions += 1
            self._last_generation = max(
                self._last_generation, generation
            )
            if generation > self.max_generation:
                self.max_generation = generation


class SyntheticLookupAgent:
    """One lookup stream on its own thread."""

    def __init__(
        self,
        router_addr: str,
        stream_id: int,
        batch: int = 256,
        key_space: int = 4000,
        qps: float = 0.0,
        timeout_s: float = 30.0,
        retries: int = 8,
        seed: Optional[int] = None,
    ):
        self.stream_id = int(stream_id)
        self.stats = LookupStats()
        self._batch = batch
        self._key_space = key_space
        self._min_interval = 1.0 / qps if qps > 0 else 0.0
        self._rng = np.random.default_rng(
            seed if seed is not None else stream_id
        )
        # patient transport: a router kill/respawn mid-run must be
        # absorbed by the envelope, not surfaced as a failed lookup
        self.client = MessageClient(
            router_addr, node_id=self.stream_id,
            node_type="lookup-load", timeout=timeout_s,
            retries=retries, backoff_base=0.1, backoff_max=1.0,
            resync_timeout=0.0,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"lookup-load{self.stream_id}",
        )
        self._thread.start()

    def stop(self, join_timeout: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
        self.client.close()

    def lookup_once(self) -> str:
        keys = self._rng.integers(
            0, self._key_space, self._batch
        ).astype(np.int64)
        t0 = time.perf_counter()
        try:
            resp = self.client.get(LookupRequest(
                keys=keys, shard_key=int(keys[0]),
            ))
            dt = time.perf_counter() - t0
            outcome = getattr(resp, "outcome", "ok")
            gen = int(getattr(resp, "generation", -1))
            self.stats.record(outcome, gen, dt, self._batch)
            return outcome
        except Exception:  # noqa: BLE001 - client-visible failure
            self.stats.failures += 1
            self.stats.record(
                "client_error", -1, time.perf_counter() - t0, 0
            )
            logger.warning(
                "lookup stream %d failed a request",
                self.stream_id, exc_info=True,
            )
            return "client_error"

    def _run(self):
        while not self._stop.is_set():
            self.lookup_once()
            if self._min_interval:
                time.sleep(self._min_interval)


class LookupLoadHarness:
    """N concurrent streams + aggregate accounting."""

    def __init__(
        self,
        router_addr: str,
        streams: int = 4,
        batch: int = 256,
        key_space: int = 4000,
        qps_per_stream: float = 0.0,
        timeout_s: float = 30.0,
        retries: int = 8,
        seed: int = 0,
    ):
        self.agents = [
            SyntheticLookupAgent(
                router_addr, i, batch=batch, key_space=key_space,
                qps=qps_per_stream, timeout_s=timeout_s,
                retries=retries, seed=seed * 1000 + i,
            )
            for i in range(streams)
        ]

    def start(self):
        self._t0 = time.perf_counter()
        for a in self.agents:
            a.start()

    def stop(self):
        for a in self.agents:
            a._stop.set()
        for a in self.agents:
            a.stop()
        self._elapsed = time.perf_counter() - self._t0

    def run_for(self, seconds: float):
        self.start()
        time.sleep(seconds)
        self.stop()
        return self.summary()

    def summary(self) -> Dict:
        lookups = sum(a.stats.lookups for a in self.agents)
        failures = sum(a.stats.failures for a in self.agents)
        regressions = sum(
            a.stats.generation_regressions for a in self.agents
        )
        outcomes: Dict[str, int] = {}
        lat: List[float] = []
        for a in self.agents:
            for k, v in a.stats.outcomes.items():
                outcomes[k] = outcomes.get(k, 0) + v
            lat.extend(a.stats.latencies_s)
        out = {
            "streams": len(self.agents),
            "lookups": lookups,
            "failed": failures,
            "generation_regressions": regressions,
            "outcomes": outcomes,
            "max_generation": max(
                (a.stats.max_generation for a in self.agents),
                default=-1,
            ),
        }
        if lat:
            arr = np.sort(np.asarray(lat))
            out["p50_ms"] = round(
                float(arr[int(0.50 * (len(arr) - 1))]) * 1e3, 3
            )
            out["p99_ms"] = round(
                float(arr[int(0.99 * (len(arr) - 1))]) * 1e3, 3
            )
        wall = getattr(self, "_elapsed", 0.0)
        out["wall_s"] = round(wall, 3)
        out["qps"] = round(lookups / wall, 1) if wall > 0 else 0.0
        return out
