"""Live control-plane scoreboard over the telemetry registry.

The registry's histograms are *cumulative* — fine for a Prometheus
scrape, useless for a capacity search, where early healthy samples
would dilute a breach at the current agent count forever.  The
scoreboard therefore works in **windows**: every sample it diffs each
``dlrover_rpc_seconds{verb}`` series' bucket counts against the
previous sample and estimates quantiles from the delta alone, so a
p99 always describes *the load level being tested right now*.

Each sample also reads the fan-in instrumentation this PR added —
``dlrover_master_connections`` (accepted/active/peak),
``dlrover_rpc_inflight`` per verb, the journal's append lock-wait
split, its batched-fsync depth under ``DLROVER_JOURNAL_FSYNC_WINDOW_S``
and the mirror queue — and emits a ``fleet_report`` event, the
timeline/report pipeline's view of the run.
"""

import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry import metrics as _metrics
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.slo import (
    HistogramWindow,
    SloRule,
    estimate_quantile,
    rules_from_env,
)

RPC_METRIC = "dlrover_rpc_seconds"

# verbs reported inline in fleet_report events, most-traffic first;
# the rest are folded into the aggregate numbers so a wide verb mix
# cannot bloat the event log
MAX_VERBS_PER_REPORT = 8


def _collect_histogram(registry, name: str):
    metric = registry.get(name)
    if not isinstance(metric, _metrics.Histogram):
        return []
    return metric.collect()


def _gauge_map(registry, name: str) -> Dict[str, float]:
    metric = registry.get(name)
    if not isinstance(metric, _metrics.Gauge):
        return {}
    out = {}
    for labels, value in metric.collect():
        key = ",".join(
            v for _, v in sorted(labels.items())
        ) or "_"
        out[key] = float(value)
    return out


# windowed-delta tracking moved to telemetry.slo.HistogramWindow so
# the serving replica/router stats share the exact implementation;
# the old private name stays as an alias for in-tree callers
_VerbWindow = HistogramWindow


class Scoreboard:
    """Samples the registry on a cadence; keeps windowed per-verb
    views; emits ``fleet_report`` events."""

    def __init__(
        self,
        registry: Optional[_metrics.MetricsRegistry] = None,
        interval_s: float = 1.0,
        rules: Optional[List[SloRule]] = None,
        min_count: int = 10,
        agents_fn=None,
        emit_reports: bool = True,
    ):
        """``agents_fn``: zero-arg callable returning the live agent
        count (the runner wires its own); ``rules``: SLO rules the
        windowed breach check evaluates (default: the same
        ``DLROVER_RPC_SLO`` rules the master's checker uses)."""
        self.registry = registry or _metrics.get_registry()
        self.interval_s = max(0.05, float(interval_s))
        self.rules = rules if rules is not None else rules_from_env()
        self.min_count = int(min_count)
        self._agents_fn = agents_fn or (lambda: 0)
        self._emit_reports = emit_reports
        self._rpc_window = _VerbWindow()
        self._journal_window = _VerbWindow()
        self._lock_window = _VerbWindow()
        self._server_window = _VerbWindow()
        self._last_sample_ts = 0.0
        self.samples: List[Dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ----------------------------------------------------------

    def reset_window(self):
        """Drop accumulated deltas: the next sample measures only
        what happens after this call."""
        self._rpc_window.reset(
            _collect_histogram(self.registry, RPC_METRIC)
        )
        self._journal_window.reset(_collect_histogram(
            self.registry, "dlrover_master_journal_fsync_seconds"
        ))
        self._lock_window.reset(_collect_histogram(
            self.registry, "dlrover_master_journal_lock_wait_seconds"
        ))
        self._server_window.reset(_collect_histogram(
            self.registry, "dlrover_rpc_server_seconds"
        ))
        self._last_sample_ts = time.monotonic()

    def _window_quantiles(self, window: Dict[Tuple, Dict]) -> Dict:
        verbs: Dict[str, Dict] = {}
        for entry in window.values():
            verb = entry["labels"].get("verb", "_")
            if entry["count"] <= 0:
                continue
            verbs[verb] = {
                "count": entry["count"],
                "mean_ms": round(
                    entry["sum_s"] / entry["count"] * 1000.0, 3
                ),
                "p50_ms": round(estimate_quantile(
                    entry["bounds"], entry["counts"], 0.50
                ) * 1000.0, 3),
                "p99_ms": round(estimate_quantile(
                    entry["bounds"], entry["counts"], 0.99
                ) * 1000.0, 3),
                "_bounds": entry["bounds"],
                "_counts": entry["counts"],
            }
        return verbs

    def sample(self) -> Dict:
        """One scoreboard observation window; appended to
        :attr:`samples` and (optionally) emitted as a
        ``fleet_report`` event."""
        now = time.monotonic()
        window_s = (
            now - self._last_sample_ts
            if self._last_sample_ts else self.interval_s
        )
        self._last_sample_ts = now
        verbs = self._window_quantiles(self._rpc_window.deltas(
            _collect_histogram(self.registry, RPC_METRIC)
        ))
        total_count = sum(v["count"] for v in verbs.values())
        rps = total_count / window_s if window_s > 0 else 0.0
        breaches = self._windowed_breaches(verbs)

        journal = self._window_quantiles(self._journal_window.deltas(
            _collect_histogram(
                self.registry,
                "dlrover_master_journal_fsync_seconds",
            )
        )).get("_", {})
        lock_wait = self._window_quantiles(self._lock_window.deltas(
            _collect_histogram(
                self.registry,
                "dlrover_master_journal_lock_wait_seconds",
            )
        )).get("_", {})
        server = self._window_quantiles(self._server_window.deltas(
            _collect_histogram(
                self.registry, "dlrover_rpc_server_seconds"
            )
        ))

        conns = _gauge_map(
            self.registry, "dlrover_master_connections"
        )
        inflight = _gauge_map(self.registry, "dlrover_rpc_inflight")
        pending_fsync = _gauge_map(
            self.registry, "dlrover_master_journal_pending_fsync"
        ).get("_", 0.0)
        mirror_queue = _gauge_map(
            self.registry, "dlrover_master_journal_mirror_queue"
        ).get("_", 0.0)

        sample = {
            "ts": time.time(),
            "window_s": round(window_s, 3),
            "agents": int(self._agents_fn()),
            "rps": round(rps, 2),
            "ops": total_count,
            "verbs": {
                v: {
                    k: val for k, val in d.items()
                    if not k.startswith("_")
                }
                for v, d in verbs.items()
            },
            "server_verbs": {
                v: {
                    k: val for k, val in d.items()
                    if not k.startswith("_")
                }
                for v, d in server.items()
            },
            "breaches": [
                {
                    "verb": b[0], "quantile": b[1],
                    "observed_s": round(b[2], 6),
                    "threshold_s": b[3], "count": b[4],
                }
                for b in breaches
            ],
            "connections": conns,
            "inflight_total": round(
                sum(inflight.values()), 1
            ),
            "journal_append_p99_ms": journal.get("p99_ms", 0.0),
            "journal_append_count": journal.get("count", 0),
            "journal_lock_wait_p99_ms": lock_wait.get(
                "p99_ms", 0.0
            ),
            "journal_pending_fsync": pending_fsync,
            "journal_mirror_queue": mirror_queue,
        }
        self.samples.append(sample)
        if self._emit_reports:
            self._emit_report(sample)
        return sample

    # -- level-wide probe window (capacity search) -------------------------
    #
    # the per-sample windows are ~1 s: right for live fleet_report
    # cadence, too small to judge a low-rate verb's p99 (a 3-request
    # window never clears min_count).  A capacity probe therefore
    # opens ONE window spanning the whole level and judges that.

    def begin_probe(self):
        self._probe = _VerbWindow()
        self._probe.reset(
            _collect_histogram(self.registry, RPC_METRIC)
        )

    def end_probe(self) -> Dict:
        """Quantiles + SLO verdict over everything since
        :meth:`begin_probe`."""
        verbs = self._window_quantiles(self._probe.deltas(
            _collect_histogram(self.registry, RPC_METRIC)
        ))
        breaches = self._windowed_breaches(verbs)
        return {
            "verbs": {
                v: {
                    k: val for k, val in d.items()
                    if not k.startswith("_")
                }
                for v, d in verbs.items()
            },
            "ops": sum(d["count"] for d in verbs.values()),
            "worst_p99_ms": {
                v: d["p99_ms"] for v, d in sorted(verbs.items())
            },
            "breaches": [
                {
                    "verb": b[0], "quantile": b[1],
                    "observed_s": round(b[2], 6),
                    "threshold_s": b[3], "count": b[4],
                }
                for b in breaches
            ],
        }

    def _windowed_breaches(
        self, verbs: Dict[str, Dict]
    ) -> List[Tuple[str, str, float, float, int]]:
        """(verb, quantile_label, observed_s, threshold_s, count)
        for every rule the CURRENT window breaches.  min_count gates
        exactly like the master's checker: a two-request window
        proves nothing."""
        out = []
        for verb, d in verbs.items():
            if d["count"] < self.min_count:
                continue
            for rule in self.rules:
                if not rule.matches(verb):
                    continue
                observed = estimate_quantile(
                    d["_bounds"], d["_counts"], rule.quantile
                )
                if observed > rule.threshold_s:
                    out.append((
                        verb, rule.quantile_label, observed,
                        rule.threshold_s, d["count"],
                    ))
        return out

    def _emit_report(self, sample: Dict):
        verbs = sample["verbs"]
        top = dict(sorted(
            verbs.items(),
            key=lambda kv: -kv[1]["count"],
        )[:MAX_VERBS_PER_REPORT])
        emit_event(
            "fleet_report",
            agents=sample["agents"],
            rps=sample["rps"],
            window_s=sample["window_s"],
            ops=sample["ops"],
            verbs=top,
            breaches=len(sample["breaches"]),
            conns_active=sample["connections"].get("active", 0.0),
            conns_peak=sample["connections"].get("peak", 0.0),
            inflight=sample["inflight_total"],
            journal_append_p99_ms=sample["journal_append_p99_ms"],
            journal_lock_wait_p99_ms=(
                sample["journal_lock_wait_p99_ms"]
            ),
            journal_pending_fsync=sample["journal_pending_fsync"],
            journal_mirror_queue=sample["journal_mirror_queue"],
        )

    # -- summary -----------------------------------------------------------

    def summary(self, last_n: Optional[int] = None) -> Dict:
        """Aggregate view over the last ``last_n`` samples (all by
        default): worst windowed p99 per verb, peak rps, breach
        count — what the bench section and the smoke test read."""
        samples = (
            self.samples[-last_n:] if last_n else list(self.samples)
        )
        if not samples:
            return {"samples": 0}
        worst: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        for s in samples:
            for verb, d in s["verbs"].items():
                worst[verb] = max(
                    worst.get(verb, 0.0), d["p99_ms"]
                )
                counts[verb] = counts.get(verb, 0) + d["count"]
        return {
            "samples": len(samples),
            "agents": samples[-1]["agents"],
            "peak_rps": max(s["rps"] for s in samples),
            "mean_rps": round(
                sum(s["rps"] for s in samples) / len(samples), 2
            ),
            "worst_p99_ms": {
                v: round(p, 3) for v, p in sorted(worst.items())
            },
            "verb_counts": counts,
            "breaches": sum(len(s["breaches"]) for s in samples),
            "conns_peak": max(
                s["connections"].get("peak", 0.0) for s in samples
            ),
            "journal_append_p99_ms": max(
                s["journal_append_p99_ms"] for s in samples
            ),
            "journal_lock_wait_p99_ms": max(
                s["journal_lock_wait_p99_ms"] for s in samples
            ),
        }

    # -- background sampling ----------------------------------------------

    def start(self):
        if self._thread is not None:
            return
        self.reset_window()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-scoreboard", daemon=True
        )
        self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - observation must not
                logger.exception("scoreboard sample failed")  # kill

    def stop(self, final_sample: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_sample:
            try:
                self.sample()
            except Exception:  # noqa: BLE001
                logger.exception("final scoreboard sample failed")
