"""FP8 (e4m3) matmul path with dynamic per-tensor scaling.

Reference: ATorch's fp8 support patches TransformerEngine modules in
(``atorch/auto/opt_lib/`` Fp8Optimization + ``utils/patch_te.py``).
The TPU equivalent needs no external library: inputs are scaled to the
e4m3 representable range, cast, and contracted with fp32 accumulation
— XLA lowers fp8 dots natively on hardware that has fp8 MXU paths
(v5p+/Trillium) and via upcast elsewhere, so the same program runs
everywhere while halving matmul operand bandwidth where it counts.
"""

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

E4M3_MAX = 448.0


def quantize_fp8(
    x: jax.Array, dtype=jnp.float8_e4m3fn
) -> tuple:
    """Per-tensor dynamic scaling to the e4m3 range; returns
    (fp8 values, fp32 inverse-applied scale)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.maximum(amax, 1e-12) / E4M3_MAX
    return (x.astype(jnp.float32) / scale).astype(dtype), scale


def fp8_dot(a: jax.Array, b: jax.Array) -> jax.Array:
    """a @ b with both operands dynamically quantized to e4m3 and an
    fp32 accumulator; result fp32 * (scale_a * scale_b)."""
    aq, sa = quantize_fp8(a)
    bq, sb = quantize_fp8(b)
    out = jax.lax.dot_general(
        aq, bq,
        (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out * (sa * sb)


class Fp8Dense(nn.Module):
    """Drop-in ``nn.Dense`` whose matmul runs through the fp8 path
    (params stay in ``param_dtype``; only the contraction operands are
    cast, the straight-through estimator handles the backward)."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (x.shape[-1], self.features),
            self.param_dtype,
        )
        flat = x.reshape(-1, x.shape[-1])
        out = _ste_fp8_dot(flat, kernel.astype(jnp.float32))
        out = out.reshape(x.shape[:-1] + (self.features,))
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros,
                (self.features,), self.param_dtype,
            )
            out = out + bias
        return out.astype(self.dtype)


@jax.custom_vjp
def _ste_fp8_dot(a, b):
    return fp8_dot(a, b)


def _ste_fwd(a, b):
    return fp8_dot(a, b), (a, b)


def _ste_bwd(res, g):
    # straight-through: backward uses the full-precision operands
    # (standard fp8 training recipe — quantization error is treated
    # as forward noise)
    a, b = res
    g = g.astype(jnp.float32)
    da = g @ b.T
    db = a.astype(jnp.float32).T @ g
    return da.astype(a.dtype), db.astype(b.dtype)


_ste_fp8_dot.defvjp(_ste_fwd, _ste_bwd)
