"""Block-wise int8 quantization kernels (Pallas) for optimizer state.

Reference: ATorch's CUDA quantization kernels powering the low-bit
optimizer family (``atorch/atorch/ops/csrc/quantization/{quantize,
dequantize,quantization_optimizer}.cu``, ~4.6k LoC; SURVEY.md §2.7).
TPU equivalent: symmetric absmax int8 with one fp32 scale per block of
``block_size`` elements.  All kernels are **gridded** over row tiles so
VMEM usage is bounded regardless of tensor size (a 124M-param leaf is
~500 MB in fp32 — far beyond the ~16 MB VMEM budget of one ungridded
call).  ``fused_qadam_step`` is the TPU analog of the reference's
``quantization_optimizer.cu``: dequant -> Adam math -> requant in one
VMEM round trip per tile, so the moments never materialize in HBM at
fp32.  Used by :mod:`dlrover_tpu.optim.low_bit`.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK = 2048  # elements per scale block (multiple of 128 lanes)
ROW_TILE = 128        # rows per grid step: tile fp32 bytes = 128*2048*4 = 1 MB


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad_rows(tiles: jax.Array, row_tile: int) -> Tuple[jax.Array, int]:
    rows = tiles.shape[0]
    padded = -(-rows // row_tile) * row_tile
    if padded != rows:
        tiles = jnp.pad(tiles, ((0, padded - rows), (0, 0)))
    return tiles, rows


def _quant_kernel(x_ref, q_ref, scale_ref, *, qmax: float = 127.0):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)  # [rows, 1]
    scale = jnp.maximum(absmax / qmax, 1e-12)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(jnp.int8)
    q_ref[:] = q
    scale_ref[:] = scale


def _dequant_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[:]


def _row_spec(block: int):
    return pl.BlockSpec((ROW_TILE, block), lambda i: (i, 0))


def _scale_spec():
    return pl.BlockSpec((ROW_TILE, 1), lambda i: (i, 0))


@functools.partial(jax.jit, static_argnums=(1, 2))
def _quantize_tiles(
    tiles: jax.Array, block_size: int, qmax: float = 127.0
):
    padded, rows = _pad_rows(tiles, ROW_TILE)
    grid = padded.shape[0] // ROW_TILE
    q, scales = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax),
        grid=(grid,),
        in_specs=[_row_spec(block_size)],
        out_specs=[_row_spec(block_size), _scale_spec()],
        out_shape=[
            jax.ShapeDtypeStruct(padded.shape, jnp.int8),
            jax.ShapeDtypeStruct((padded.shape[0], 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(padded)
    return q[:rows], scales[:rows]


def to_block_tiles(
    x: jax.Array, block_size: int, dtype=jnp.float32
) -> jax.Array:
    """Flatten + zero-pad ``x`` to the [rows, block_size] layout every
    kernel here operates on.  ``dtype=None`` keeps ``x``'s dtype —
    bf16 tiles halve the HBM traffic of a billion-param optimizer
    pass, and the kernels upcast to f32 internally anyway."""
    dtype = dtype or x.dtype
    flat = x.reshape(-1).astype(dtype)
    rows = -(-flat.size // block_size)
    pad = rows * block_size - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    return flat.reshape(rows, block_size)


def quantize_blockwise(
    x: jax.Array, block_size: int = DEFAULT_BLOCK,
    qmax: float = 127.0,
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """Flatten + pad to [rows, block_size]; returns (int8 values,
    fp32 scales [rows, 1], original shape)."""
    shape = x.shape
    tiles = to_block_tiles(x, block_size)
    q, scales = _quantize_tiles(tiles, block_size, qmax)
    return q, scales, shape


# -- 4-bit (packed nibbles) --------------------------------------------------


def quantize_blockwise_4bit(
    x: jax.Array, block_size: int = DEFAULT_BLOCK
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """int4 blockwise: symmetric absmax over +-7, two values packed
    per byte — 8x less optimizer HBM than fp32 (reference: the 4-bit
    low-bit optimizer family, atorch/optimizers/low_bit/).
    Returns (packed [rows, block/2], scales [rows, 1], shape)."""
    q, scales, shape = quantize_blockwise(x, block_size, qmax=7.0)
    biased = (q + 7).astype(jnp.uint8)  # nibbles in [0, 14]
    packed = biased[:, 0::2] | (biased[:, 1::2] << 4)
    return packed, scales, shape


def dequantize_blockwise_4bit(
    packed: jax.Array, scales: jax.Array, shape: Tuple[int, ...],
) -> jax.Array:
    lo = (packed & 0xF).astype(jnp.int32) - 7
    hi = ((packed >> 4) & 0xF).astype(jnp.int32) - 7
    rows, half = packed.shape
    q = jnp.stack([lo, hi], axis=-1).reshape(rows, half * 2)
    return dequantize_blockwise(q.astype(jnp.int8), scales, shape)


def quantize_blockwise_4bit_sqrt(
    x: jax.Array, block_size: int = DEFAULT_BLOCK
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """Unsigned 4-bit in the sqrt domain — the right map for Adam's
    second moment (non-negative, sqrt-consumed): 15 levels over
    [0, sqrt(absmax)] give far better effective resolution where the
    optimizer reads it (reference: the nonlinear quantization maps of
    the low-bit family)."""
    shape = x.shape
    tiles = to_block_tiles(x, block_size)
    y = jnp.sqrt(jnp.maximum(tiles, 0.0))
    absmax = jnp.max(y, axis=-1, keepdims=True)
    scales = jnp.maximum(absmax / 15.0, 1e-12)
    q = jnp.clip(jnp.round(y / scales), 0, 15).astype(jnp.uint8)
    packed = q[:, 0::2] | (q[:, 1::2] << 4)
    return packed, scales, shape


def dequantize_blockwise_4bit_sqrt(
    packed: jax.Array, scales: jax.Array, shape: Tuple[int, ...],
) -> jax.Array:
    lo = (packed & 0xF).astype(jnp.float32)
    hi = ((packed >> 4) & 0xF).astype(jnp.float32)
    rows, half = packed.shape
    y = jnp.stack([lo, hi], axis=-1).reshape(rows, half * 2) * scales
    n = 1
    for s in shape:
        n *= s
    return (y * y).reshape(-1)[:n].reshape(shape)


@jax.jit
def _dequantize_tiles(q: jax.Array, scales: jax.Array) -> jax.Array:
    block = q.shape[1]
    q_p, rows = _pad_rows(q, ROW_TILE)
    s_p, _ = _pad_rows(scales, ROW_TILE)
    grid = q_p.shape[0] // ROW_TILE
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(grid,),
        in_specs=[_row_spec(block), _scale_spec()],
        out_specs=_row_spec(block),
        out_shape=jax.ShapeDtypeStruct(q_p.shape, jnp.float32),
        interpret=_interpret(),
    )(q_p, s_p)
    return out[:rows]


def dequantize_blockwise(
    q: jax.Array, scales: jax.Array, shape: Tuple[int, ...]
) -> jax.Array:
    out = _dequantize_tiles(q, scales)
    n = 1
    for s in shape:
        n *= s
    return out.reshape(-1)[:n].reshape(shape)


# -- fused quantized-optimizer step -----------------------------------------


def _qadam_kernel(
    hyp_ref, g_ref, p_ref, qmu_ref, mus_ref, qnu_ref, nus_ref,
    upd_ref, qmu_out, mus_out, qnu_out, nus_out,
    *, b1: float, b2: float, eps: float, lr: float, wd: float,
):
    """One VMEM pass: dequant moments, Adam math, requant, emit update.

    ``hyp`` carries the traced bias corrections [bc1, bc2] (they depend
    on the step count); the python-float hyperparams are baked in.
    """
    g = g_ref[:].astype(jnp.float32)
    p = p_ref[:].astype(jnp.float32)
    mu = qmu_ref[:].astype(jnp.float32) * mus_ref[:]
    # nu is stored in the SQRT domain: nu = (q * scale)^2.  Linear
    # int8 storage is unstable — a coordinate with
    # absmax/127 < |g| < absmax/11 keeps mu != 0 while its nu
    # quantizes to 0, so m_hat/(sqrt(0)+eps) explodes.  In the sqrt
    # domain the mu and nu cutoffs coincide (both at |g| ~
    # rowmax/127): wherever nu rounds to zero, mu does too and the
    # update is a benign zero.  (Same reasoning as the 4-bit
    # variant's quantize_blockwise_4bit_sqrt.)
    nu_sqrt_prev = qnu_ref[:].astype(jnp.float32) * nus_ref[:]
    nu = b2 * nu_sqrt_prev * nu_sqrt_prev + (1.0 - b2) * g * g
    mu = b1 * mu + (1.0 - b1) * g
    bc1 = hyp_ref[0, 0]
    bc2 = hyp_ref[0, 1]
    m_hat = mu / bc1
    v_hat = nu / bc2
    upd_ref[:] = (
        -lr * (m_hat / (jnp.sqrt(v_hat) + eps) + wd * p)
    ).astype(upd_ref.dtype)
    mu_absmax = jnp.max(jnp.abs(mu), axis=-1, keepdims=True)
    mu_scale = jnp.maximum(mu_absmax / 127.0, 1e-12)
    qmu_out[:] = jnp.clip(
        jnp.round(mu / mu_scale), -127, 127
    ).astype(jnp.int8)
    mus_out[:] = mu_scale
    nu_sqrt = jnp.sqrt(nu)
    nu_scale = jnp.maximum(
        jnp.max(nu_sqrt, axis=-1, keepdims=True) / 127.0, 1e-12
    )
    qnu_out[:] = jnp.clip(
        jnp.round(nu_sqrt / nu_scale), 0, 127
    ).astype(jnp.int8)
    nus_out[:] = nu_scale


@functools.partial(
    jax.jit, static_argnames=("b1", "b2", "eps", "lr", "wd")
)
def fused_qadam_step(
    g_tiles: jax.Array,     # f32 [rows, block]
    p_tiles: jax.Array,     # f32 [rows, block]
    q_mu: jax.Array,        # int8 [rows, block]
    mu_scales: jax.Array,   # f32 [rows, 1]
    q_nu: jax.Array,
    nu_scales: jax.Array,
    bias_corr: jax.Array,   # f32 [1, 2] = [1-b1^t, 1-b2^t]
    *,
    b1: float, b2: float, eps: float, lr: float, wd: float,
):
    """Returns (upd_tiles, q_mu', mu_scales', q_nu', nu_scales')."""
    block = g_tiles.shape[1]
    g_p, rows = _pad_rows(g_tiles, ROW_TILE)
    p_p, _ = _pad_rows(p_tiles, ROW_TILE)
    qmu_p, _ = _pad_rows(q_mu, ROW_TILE)
    mus_p, _ = _pad_rows(mu_scales, ROW_TILE)
    qnu_p, _ = _pad_rows(q_nu, ROW_TILE)
    nus_p, _ = _pad_rows(nu_scales, ROW_TILE)
    grid = g_p.shape[0] // ROW_TILE
    padded_rows = g_p.shape[0]
    hyp_spec = pl.BlockSpec((1, 2), lambda i: (0, 0))
    kernel = functools.partial(
        _qadam_kernel, b1=b1, b2=b2, eps=eps, lr=lr, wd=wd
    )
    upd, qmu2, mus2, qnu2, nus2 = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            hyp_spec,
            _row_spec(block), _row_spec(block),
            _row_spec(block), _scale_spec(),
            _row_spec(block), _scale_spec(),
        ],
        out_specs=[
            _row_spec(block),
            _row_spec(block), _scale_spec(),
            _row_spec(block), _scale_spec(),
        ],
        out_shape=[
            # update emitted in the gradient's dtype: bf16 tiles
            # halve the write+read-back traffic and the params it
            # lands on are bf16 anyway (math stays f32 in-kernel)
            jax.ShapeDtypeStruct(
                (padded_rows, block), g_tiles.dtype
            ),
            jax.ShapeDtypeStruct((padded_rows, block), jnp.int8),
            jax.ShapeDtypeStruct((padded_rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((padded_rows, block), jnp.int8),
            jax.ShapeDtypeStruct((padded_rows, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(bias_corr, g_p, p_p, qmu_p, mus_p, qnu_p, nus_p)
    return (
        upd[:rows], qmu2[:rows], mus2[:rows], qnu2[:rows], nus2[:rows]
    )
