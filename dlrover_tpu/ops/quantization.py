"""Block-wise int8 quantization kernels (Pallas) for optimizer state.

Reference: ATorch's CUDA quantization kernels powering the low-bit
optimizer family (``atorch/atorch/ops/csrc/quantization/{quantize,
dequantize,quantization_optimizer}.cu``, ~4.6k LoC; SURVEY.md §2.7).
TPU equivalent: symmetric absmax int8 with one fp32 scale per block of
``block_size`` elements, as Pallas kernels (interpret mode on CPU).
Used by :mod:`dlrover_tpu.optim.low_bit` to store Adam moments in 1/4
the HBM.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 2048  # elements per scale block (multiple of 128 lanes)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _quant_kernel(x_ref, q_ref, scale_ref):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)  # [rows, 1]
    scale = jnp.maximum(absmax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    q_ref[:] = q
    scale_ref[:] = scale


def _dequant_kernel(q_ref, scale_ref, out_ref):
    out_ref[:] = q_ref[:].astype(jnp.float32) * scale_ref[:]


def quantize_blockwise(
    x: jax.Array, block_size: int = DEFAULT_BLOCK
) -> Tuple[jax.Array, jax.Array, Tuple[int, ...]]:
    """Flatten + pad to [rows, block_size]; returns (int8 values,
    fp32 scales [rows, 1], original shape)."""
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.size
    rows = -(-n // block_size)
    pad = rows * block_size - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    tiles = flat.reshape(rows, block_size)

    q, scales = pl.pallas_call(
        _quant_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((rows, block_size), jnp.int8),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(tiles)
    return q, scales, shape


def dequantize_blockwise(
    q: jax.Array, scales: jax.Array, shape: Tuple[int, ...]
) -> jax.Array:
    out = pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, jnp.float32),
        interpret=_interpret(),
    )(q, scales)
    n = 1
    for s in shape:
        n *= s
    return out.reshape(-1)[:n].reshape(shape)
