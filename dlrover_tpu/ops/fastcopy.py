"""GIL-free bulk copies for the checkpoint hot path.

ctypes foreign calls release the GIL, so routing the flat
array->shm memcpy through the tiny native helper keeps the trainer's
other threads (heartbeats, IPC replies, monitors) responsive while a
multi-GB snapshot streams — the reference gets this for free from
torch's C++ copy (ckpt_saver.py:174); numpy's ``copyto`` holds the
GIL the whole time.  Falls back to numpy when the toolchain is
unavailable.
"""

import ctypes
from typing import Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        from dlrover_tpu.native import build_library

        lib = ctypes.CDLL(build_library("fastcopy"))
        lib.dlrover_fastcopy.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dlrover_fastcopy.restype = ctypes.c_size_t
        _lib = lib
    except Exception as e:  # noqa: BLE001 - no toolchain etc.
        logger.info("fastcopy unavailable (%s); using numpy", e)
        _lib = None
    return _lib


def copy_into(dst: np.ndarray, src: np.ndarray) -> None:
    """dst[...] = src with the GIL released during the transfer.

    Both must be C-contiguous with identical dtype/size (the
    checkpoint path guarantees this); falls back to ``np.copyto``.
    """
    lib = _load()
    if (
        lib is None
        or not dst.flags["C_CONTIGUOUS"]
        or not src.flags["C_CONTIGUOUS"]
        or dst.dtype != src.dtype
        or dst.size != src.size
    ):
        np.copyto(dst, src)
        return
    lib.dlrover_fastcopy(
        dst.ctypes.data, src.ctypes.data, dst.nbytes
    )
