"""GIL-free bulk copies for the checkpoint hot path.

ctypes foreign calls release the GIL, so routing the flat
array->shm memcpy through the tiny native helper keeps the trainer's
other threads (heartbeats, IPC replies, monitors) responsive while a
multi-GB snapshot streams — the reference gets this for free from
torch's C++ copy (ckpt_saver.py:174); numpy's ``copyto`` holds the
GIL the whole time.  Falls back to numpy when the toolchain is
unavailable.
"""

import ctypes
import os
from typing import Optional

import numpy as np

from dlrover_tpu.common.log import default_logger as logger


def save_workers() -> int:
    """Thread count for the save-side chunked parallel memcpy
    (``DLROVER_SAVE_WORKERS``; the twin of the restore pipeline's
    ``DLROVER_RESTORE_WORKERS``).  1 means exact serial copies.
    Default sizes like the restore pool: half the cores, capped."""
    env = os.environ.get("DLROVER_SAVE_WORKERS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return min(8, max(2, (os.cpu_count() or 2) // 2))

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        from dlrover_tpu.native import build_library

        lib = ctypes.CDLL(build_library("fastcopy"))
        lib.dlrover_fastcopy.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.dlrover_fastcopy.restype = ctypes.c_size_t
        _lib = lib
    except Exception as e:  # noqa: BLE001 - no toolchain etc.
        logger.info("fastcopy unavailable (%s); using numpy", e)
        _lib = None
    return _lib


def copy_into(dst: np.ndarray, src: np.ndarray) -> None:
    """dst[...] = src with the GIL released during the transfer.

    Both must be C-contiguous with identical dtype/size (the
    checkpoint path guarantees this); falls back to ``np.copyto``.
    """
    lib = _load()
    if (
        lib is None
        or not dst.flags["C_CONTIGUOUS"]
        or not src.flags["C_CONTIGUOUS"]
        or dst.dtype != src.dtype
        or dst.size != src.size
    ):
        np.copyto(dst, src)
        return
    lib.dlrover_fastcopy(
        dst.ctypes.data, src.ctypes.data, dst.nbytes
    )


def copy_into_chunked(
    dst: np.ndarray,
    src: np.ndarray,
    submit=None,
    chunk_bytes: int = 64 * 2**20,
):
    """``dst[...] = src`` split into ~``chunk_bytes`` contiguous
    pieces.  Each piece is dispatched through ``submit(fn, *args)``
    (a thread-pool submit — the GIL-released :func:`copy_into` makes
    the pieces genuinely concurrent, page faults included) or run
    inline when ``submit`` is None; returns whatever ``submit``
    returned per piece so the caller can drain.  The restore pipeline
    uses this to parallelize the detach of one large leaf, where a
    single serial memcpy against a cold shm mapping is fault-bound.
    """
    if not (
        dst.flags["C_CONTIGUOUS"] and src.flags["C_CONTIGUOUS"]
    ):
        # reshape(-1) of a non-contiguous array is a COPY — chunk
        # writes would land in a temporary and dst stay untouched
        np.copyto(dst, src)
        return []
    d1, s1 = dst.reshape(-1), src.reshape(-1)
    if d1.size == 0:
        return []
    step = max(1, chunk_bytes // max(1, d1.dtype.itemsize))
    out = []
    for lo in range(0, d1.size, step):
        if submit is None:
            copy_into(d1[lo:lo + step], s1[lo:lo + step])
        else:
            out.append(submit(copy_into, d1[lo:lo + step], s1[lo:lo + step]))
    return out
