"""KvVariable: dynamic-capacity sparse embedding table (ctypes over
the C++ store) with a JAX bridge.

Reference API surface: TFPlus ``KvVariable`` ops
(``tfplus/tfplus/kv_variable/ops/kv_variable_ops.cc`` — gather/
gather-or-insert/gather-or-zeros, scatter add/sub/mul, import/export,
frequency) and the sparse group optimizers
(``tfplus/tfplus/training/{group_adam,adagrad,group_ftrl}.py``).

Design: the table lives in host memory (C++,
:mod:`dlrover_tpu.native`); training embeds a ``gather`` into the
jitted program via ``jax.pure_callback`` so the dense [n, dim] lookup
result flows onto the TPU, while gradients come back to the host and
the C++ group optimizer updates only the touched keys.
"""

import ctypes
from typing import Optional, Tuple

import numpy as np

from dlrover_tpu.native import build_library
from dlrover_tpu.telemetry.metrics import get_registry

_REG = get_registry()
_SPILL_FAILURES_GAUGE = _REG.gauge(
    "dlrover_kv_spill_write_failures",
    "Cumulative failed spill-tier writes (disk full / IO error)",
)
_SPILL_DISABLED_GAUGE = _REG.gauge(
    "dlrover_kv_spill_disabled",
    "1 when repeated spill-write failures tripped the cold tier off",
)
_SPILL_DISK_ROWS_GAUGE = _REG.gauge(
    "dlrover_kv_spill_disk_rows", "Rows resident in the cold tier"
)

_lib = None

# Dirty-baseline consumer slots: the serving publisher, the delta
# flash checkpointer and the paged shm tier drain deltas on
# independent cadences — each owns its own dirty/dead baseline on the
# C++ table so no plane can clear rows out of another's next delta.
DIRTY_CONSUMER_SERVING = 0
DIRTY_CONSUMER_CHECKPOINT = 1
DIRTY_CONSUMER_SHM = 2


def _load():
    global _lib
    if _lib is None:
        path = build_library("kv_store")
        lib = ctypes.CDLL(path)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.kv_create.restype = ctypes.c_void_p
        lib.kv_create.argtypes = [
            ctypes.c_int, ctypes.c_long, ctypes.c_ulong,
        ]
        lib.kv_destroy.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_long
        lib.kv_size.argtypes = [ctypes.c_void_p]
        lib.kv_dim.restype = ctypes.c_int
        lib.kv_dim.argtypes = [ctypes.c_void_p]
        lib.kv_gather.argtypes = [
            ctypes.c_void_p, i64p, ctypes.c_long, f32p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.kv_insert.argtypes = [
            ctypes.c_void_p, i64p, f32p, ctypes.c_long,
        ]
        lib.kv_scatter.argtypes = [
            ctypes.c_void_p, i64p, f32p, ctypes.c_long, ctypes.c_int,
        ]
        lib.kv_export.restype = ctypes.c_long
        lib.kv_export.argtypes = [
            ctypes.c_void_p, i64p, f32p, u64p, ctypes.c_long,
        ]
        lib.kv_export_freq.restype = ctypes.c_long
        lib.kv_export_freq.argtypes = [
            ctypes.c_void_p, u64p, ctypes.c_long,
        ]
        lib.kv_import.argtypes = [
            ctypes.c_void_p, i64p, f32p, u64p, ctypes.c_long,
        ]
        lib.kv_frequency.argtypes = [
            ctypes.c_void_p, i64p, ctypes.c_long, u64p,
        ]
        lib.kv_evict_below.restype = ctypes.c_long
        lib.kv_evict_below.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.kv_spill_enable.restype = ctypes.c_int
        lib.kv_spill_enable.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
        ]
        lib.kv_spill_stats.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long),
        ]
        lib.kv_apply_group_adam.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            i64p, f32p, ctypes.c_long,
            ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_long,
        ]
        lib.kv_apply_group_adagrad.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, i64p, f32p,
            ctypes.c_long, ctypes.c_float, ctypes.c_float,
            ctypes.c_float,
        ]
        lib.kv_apply_group_ftrl.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            i64p, f32p, ctypes.c_long, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float,
        ]
        lib.kv_clear.argtypes = [ctypes.c_void_p]
        lib.kv_reserve.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.kv_spill_break.argtypes = [ctypes.c_void_p]
        lib.kv_dirty_enable_c.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.kv_dirty_enabled_c.restype = ctypes.c_int
        lib.kv_dirty_enabled_c.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.kv_dirty_count_c.restype = ctypes.c_long
        lib.kv_dirty_count_c.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.kv_dead_count_c.restype = ctypes.c_long
        lib.kv_dead_count_c.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.kv_export_dirty_c.restype = ctypes.c_long
        lib.kv_export_dirty_c.argtypes = [
            ctypes.c_void_p, i64p, f32p, u64p, ctypes.c_long,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.kv_export_dead_c.restype = ctypes.c_long
        lib.kv_export_dead_c.argtypes = [
            ctypes.c_void_p, i64p, ctypes.c_long, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.kv_clear_dirty_c.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.kv_export_cursor_new.restype = ctypes.c_void_p
        lib.kv_export_cursor_new.argtypes = [ctypes.c_void_p]
        lib.kv_export_cursor_remaining.restype = ctypes.c_long
        lib.kv_export_cursor_remaining.argtypes = [ctypes.c_void_p]
        lib.kv_export_cursor_free.argtypes = [ctypes.c_void_p]
        lib.kv_export_chunk.restype = ctypes.c_long
        lib.kv_export_chunk.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, i64p, f32p, u64p,
            ctypes.c_long,
        ]
        lib.kv_delete.restype = ctypes.c_long
        lib.kv_delete.argtypes = [ctypes.c_void_p, i64p, ctypes.c_long]
        lib.kv_apply_sparse_sgd.argtypes = [
            ctypes.c_void_p, i64p, f32p, ctypes.c_long, ctypes.c_float,
        ]
        lib.kv_apply_sparse_adam.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            i64p, f32p, ctypes.c_long,
            ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_long,
        ]
        lib.kv_apply_rectified_adam.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            i64p, f32p, ctypes.c_long,
            ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_long,
        ]
        _lib = lib
    return _lib


def _i64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f32(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


class KvVariable:
    """Host-side sparse embedding table."""

    def __init__(self, dim: int, initial_capacity: int = 1024,
                 seed: int = 0, name: str = "kv"):
        self._lib = _load()
        self.dim = dim
        self.name = name
        self._handle = ctypes.c_void_p(
            self._lib.kv_create(dim, initial_capacity, seed)
        )

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.kv_destroy(self._handle)
                self._handle = None
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def __len__(self) -> int:
        return int(self._lib.kv_size(self._handle))

    def gather(
        self, keys: np.ndarray, insert_missing: bool = True,
        random_init: bool = True, count_freq: bool = True,
    ) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        out = np.empty((keys.size, self.dim), dtype=np.float32)
        self._lib.kv_gather(
            self._handle, _i64(keys), keys.size, _f32(out),
            int(insert_missing), int(random_init), int(count_freq),
        )
        return out

    def gather_or_zeros(self, keys: np.ndarray) -> np.ndarray:
        return self.gather(keys, insert_missing=False,
                           random_init=False, count_freq=False)

    def insert(self, keys: np.ndarray, values: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        values = np.ascontiguousarray(values, dtype=np.float32)
        self._lib.kv_insert(
            self._handle, _i64(keys), _f32(values), keys.size
        )

    def scatter_add(self, keys, values):
        self._scatter(keys, values, 0)

    def scatter_sub(self, keys, values):
        self._scatter(keys, values, 1)

    def scatter_mul(self, keys, values):
        self._scatter(keys, values, 2)

    def _scatter(self, keys, values, op: int):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        values = np.ascontiguousarray(values, dtype=np.float32)
        self._lib.kv_scatter(
            self._handle, _i64(keys), _f32(values), keys.size, op
        )

    def enable_spill(self, path: str, max_dram_rows: int) -> None:
        """Turn on the hybrid two-tier storage (reference: tfplus
        hybrid_embedding/table_manager.h): DRAM keeps at most
        ``max_dram_rows`` hot rows; frequency-cold rows spill to the
        record file at ``path`` and are transparently promoted back
        on gather miss.  Gather/scatter/optimizer semantics are
        unchanged — only residence moves."""
        rc = self._lib.kv_spill_enable(
            self._handle, path.encode(), max_dram_rows
        )
        if rc == -2:
            raise ValueError(
                "spill already enabled with a different path; "
                "re-calling with the SAME path adjusts the DRAM "
                "budget, replacing the tier would orphan the "
                "disk-resident rows"
            )
        if rc != 0:
            raise OSError(f"cannot open spill file {path!r}")

    def spill_stats(self) -> dict:
        out = (ctypes.c_long * 6)()
        self._lib.kv_spill_stats(self._handle, out)
        stats = {
            "disk_rows": int(out[0]),
            "spills": int(out[1]),
            "promotions": int(out[2]),
            "dram_rows": int(out[3]),
            "write_failures": int(out[4]),
            "disabled": bool(out[5]),
        }
        # write-through to the telemetry registry so the master
        # endpoint / agent textfile surface the failure breaker
        # without a separate polling path
        _SPILL_FAILURES_GAUGE.set(
            stats["write_failures"], table=self.name
        )
        _SPILL_DISABLED_GAUGE.set(
            1.0 if stats["disabled"] else 0.0, table=self.name
        )
        _SPILL_DISK_ROWS_GAUGE.set(stats["disk_rows"], table=self.name)
        return stats

    def frequency(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        out = np.zeros(keys.size, dtype=np.uint64)
        self._lib.kv_frequency(
            self._handle, _i64(keys), keys.size, _u64(out)
        )
        return out

    def evict_below(self, min_freq: int) -> int:
        return int(
            self._lib.kv_evict_below(self._handle, min_freq)
        )

    def evict_to_capacity(self, max_rows: int) -> int:
        """Frequency-ordered overflow policy: evict coldest rows until
        ~``max_rows`` remain (reference: the kv-variable
        frequency/overflow policies, tfplus
        kv_variable_ops.cc:37 / kernels/kv_variable.h:89).

        Ties at the threshold are kept WHOLE: evicting a frequency
        class is all-or-nothing, so the cutoff backs off until at
        least one row survives — the table may stay over budget when
        a tie class straddles it, but learned state is never wiped
        (an all-equal-frequency table, e.g. epoch one, evicts
        nothing).  Only the frequency column is exported for the
        threshold computation."""
        if len(self) <= max_rows:
            return 0
        freq = self.export_freq()
        # size the threshold math from the exported snapshot, not the
        # pre-export row count — a concurrent jitted gather can grow
        # or shrink the table between the two calls
        n = len(freq)
        if n <= max_rows:
            return 0
        order = np.sort(freq)
        cutoff = int(order[n - max_rows - 1]) + 1
        # rows surviving this cutoff; back off while it would wipe
        # the table (tie class at the top)
        keep = 0
        while cutoff > 0:
            keep = n - int(np.searchsorted(order, cutoff, "left"))
            if keep > 0:
                break
            cutoff -= 1
        if cutoff <= 0 or keep == n:
            return 0  # nothing evictable without losing a whole class
        return self.evict_below(cutoff)

    def export_freq(self) -> np.ndarray:
        """Frequency column only — no key/value materialization (an
        eviction decision on a big table must not allocate the whole
        embedding matrix)."""
        n = len(self)
        freq = np.empty(n, dtype=np.uint64)
        got = self._lib.kv_export_freq(self._handle, _u64(freq), n)
        return freq[:got]

    def export(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        n = len(self)
        keys = np.empty(n, dtype=np.int64)
        values = np.empty((n, self.dim), dtype=np.float32)
        freq = np.empty(n, dtype=np.uint64)
        got = self._lib.kv_export(
            self._handle, _i64(keys), _f32(values), _u64(freq), n
        )
        return keys[:got], values[:got], freq[:got]

    # -- chunked bulk transfer (O(window) value memory) ---------------------

    def export_chunks(self, max_rows: int):
        """Generator of ``(keys, values, freq)`` windows covering the
        whole logical table (both tiers) without ever materializing
        more than ``max_rows`` value rows at once — the bulk-export
        primitive of streaming reshard and chunked checkpoint paths.

        The native cursor snapshots only the KEY column at the first
        call (8 B/row — the same O(rows) footprint class as
        :meth:`export_freq`) and stays valid across spill residence
        moves between chunks; spilled rows are read in place, keys
        evicted after the snapshot are skipped.  Each yielded window
        is a fresh private array set — callers may hold or mutate it
        freely."""
        max_rows = max(1, int(max_rows))
        cursor = ctypes.c_void_p(
            self._lib.kv_export_cursor_new(self._handle)
        )
        try:
            while True:
                keys = np.empty(max_rows, dtype=np.int64)
                values = np.empty(
                    (max_rows, self.dim), dtype=np.float32
                )
                freq = np.empty(max_rows, dtype=np.uint64)
                got = int(self._lib.kv_export_chunk(
                    self._handle, cursor, _i64(keys), _f32(values),
                    _u64(freq), max_rows,
                ))
                if got <= 0:
                    break
                out = (keys[:got], values[:got], freq[:got])
                # drop the generator's own refs BEFORE yielding: a
                # caller that releases the window promptly then pays
                # for ONE live window during the next chunk's
                # allocation, not two (the streamed writers' RSS
                # bound leans on this)
                keys = values = freq = None
                yield out
                out = None
                if got < max_rows and not int(
                    self._lib.kv_export_cursor_remaining(cursor)
                ):
                    break
        finally:
            self._lib.kv_export_cursor_free(cursor)

    def import_chunked(
        self, keys, values, freq=None, max_rows: int = 65536,
    ) -> int:
        """Windowed :meth:`import_`: slices of at most ``max_rows``
        rows go through the native import one window at a time, so a
        caller streaming from mmap-backed views never forces the
        whole blob contiguous in RAM at once (each window is the only
        private copy).  The spill pass runs per window with the usual
        10% hysteresis, so DRAM stays bounded DURING the import, not
        just after it.  Returns rows imported."""
        keys = np.asarray(keys)
        n = int(keys.shape[0])
        max_rows = max(1, int(max_rows))
        for lo in range(0, n, max_rows):
            hi = min(n, lo + max_rows)
            self.import_(
                keys[lo:hi],
                np.asarray(values)[lo:hi],
                None if freq is None else np.asarray(freq)[lo:hi],
            )
        return n

    def reserve(self, n: int) -> None:
        """Pre-size the hash table and slab for ~``n`` more rows so a
        chunked import pays no mid-stream rehash storms."""
        self._lib.kv_reserve(self._handle, int(n))

    # -- dirty-row delta surface (per-consumer incremental export) ----------

    def enable_dirty_tracking(
        self, consumer: int = DIRTY_CONSUMER_SERVING
    ) -> None:
        """Arm dirty/dead tracking for one consumer slot (the serving
        publisher arms :data:`DIRTY_CONSUMER_SERVING`, the delta
        flash checkpointer :data:`DIRTY_CONSUMER_CHECKPOINT` — the
        two planes baseline independently).  OPT-IN: untracked jobs
        pay nothing on the optimizer hot path and accumulate no set
        overhead.  Mutations before arming are not tracked — baseline
        with a full snapshot (the first publish/export is always a
        base)."""
        self._lib.kv_dirty_enable_c(self._handle, int(consumer))

    def dirty_tracking_enabled(
        self, consumer: int = DIRTY_CONSUMER_SERVING
    ) -> bool:
        return bool(
            self._lib.kv_dirty_enabled_c(self._handle, int(consumer))
        )

    def dirty_count(
        self, consumer: int = DIRTY_CONSUMER_SERVING
    ) -> int:
        """Rows touched (value or frequency) since this consumer's
        last cleared delta export — the next delta's size, and the
        bound on its export stall (O(rows touched), never
        O(table))."""
        return int(
            self._lib.kv_dirty_count_c(self._handle, int(consumer))
        )

    def dead_count(
        self, consumer: int = DIRTY_CONSUMER_SERVING
    ) -> int:
        """Deletion tombstones (evicted keys) accumulated since this
        consumer's last cleared delta export."""
        return int(
            self._lib.kv_dead_count_c(self._handle, int(consumer))
        )

    def export_dirty(
        self, clear: bool = False,
        consumer: int = DIRTY_CONSUMER_SERVING,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Export only the rows touched since this consumer's last
        cleared delta (spill-tier rows read in place, no promotion).
        With ``clear``, exactly the exported keys leave the dirty set
        atomically with the export — a concurrent mutation stays
        dirty for the NEXT delta instead of silently vanishing."""
        chunks = []
        while True:
            n = self.dirty_count(consumer)
            if n == 0:
                break
            keys = np.empty(n, dtype=np.int64)
            values = np.empty((n, self.dim), dtype=np.float32)
            freq = np.empty(n, dtype=np.uint64)
            got = self._lib.kv_export_dirty_c(
                self._handle, _i64(keys), _f32(values), _u64(freq),
                n, int(clear), int(consumer),
            )
            chunks.append((keys[:got], values[:got], freq[:got]))
            # without clear, one pass covers the snapshot; with
            # clear, loop until the set drains (mutations racing the
            # export can top it back up — they belong to this delta
            # only if we catch them, the next one otherwise)
            if not clear or self.dirty_count(consumer) == 0:
                break
        if not chunks:
            return (
                np.empty(0, np.int64),
                np.empty((0, self.dim), np.float32),
                np.empty(0, np.uint64),
            )
        if len(chunks) == 1:
            return chunks[0]
        return (
            np.concatenate([c[0] for c in chunks]),
            np.concatenate([c[1] for c in chunks]),
            np.concatenate([c[2] for c in chunks]),
        )

    def export_dead(
        self, clear: bool = False,
        consumer: int = DIRTY_CONSUMER_SERVING,
    ) -> np.ndarray:
        """The delta's deletion tombstones."""
        n = self.dead_count(consumer)
        keys = np.empty(n, dtype=np.int64)
        got = self._lib.kv_export_dead_c(
            self._handle, _i64(keys), n, int(clear), int(consumer)
        )
        return keys[:got]

    def clear_dirty(self, consumer: int = DIRTY_CONSUMER_SERVING):
        """Reset this consumer's delta sets (a full-snapshot export
        baselines its next delta).  Other consumers' baselines are
        untouched — the two planes never clear each other."""
        self._lib.kv_clear_dirty_c(self._handle, int(consumer))

    def delete(self, keys) -> int:
        """Remove specific keys from either tier (delta tombstone
        apply on a serving replica); returns how many existed."""
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        if keys.size == 0:
            return 0
        return int(
            self._lib.kv_delete(self._handle, _i64(keys), keys.size)
        )

    def import_(self, keys, values, freq=None):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        values = np.ascontiguousarray(values, dtype=np.float32)
        freq_arr = (
            np.ascontiguousarray(freq, dtype=np.uint64)
            if freq is not None
            else np.zeros(keys.size, dtype=np.uint64)
        )
        self._lib.kv_import(
            self._handle, _i64(keys), _f32(values), _u64(freq_arr),
            keys.size,
        )

    def clear(self):
        """Drop every row on both tiers.  Checkpoint import REPLACES
        table state (a resharded restore must hold exactly the owned
        subset — leftover rows from a previous world would be phantom
        duplicates of rows the key-hash partition assigned to another
        rank)."""
        self._lib.kv_clear(self._handle)

    def _break_spill_tier(self):
        """Fault-injection hook (chaos ``io_error`` on the spill
        tier): make the cold tier's backing device fail like a dead
        disk — subsequent spill writes error out (tripping the
        production write-failure breaker), stranded cold records read
        back short and are skipped by export.  DRAM rows are
        untouched."""
        self._lib.kv_spill_break(self._handle)

    # -- JAX bridge --------------------------------------------------------

    def jax_gather(self, keys, insert_missing: bool = True):
        """Embed a host gather inside a jitted program; output is a
        dense [n, dim] f32 array on device.

        Platform note: host callbacks require the runtime to call
        back into THIS process mid-program.  A tunneled remote
        device (device server on the far side of a network link)
        cannot — the call hangs.  There, run the gather host-side
        and ``device_put`` the dense batch instead (the embedding
        lookup is host-resident by design, like the reference's CPU
        parameter-server tables).

        The default gather mutates the table (inserts missing rows and
        bumps frequency counters), so it runs through
        ``io_callback(ordered=True)`` — XLA is free to cache, dedupe or
        drop *pure* callbacks, which would lose or double-apply the
        inserts.  With ``insert_missing=False`` the gather is
        side-effect-free (``gather_or_zeros``) and uses
        ``pure_callback`` so it stays compatible with vmap/caching.
        """
        import jax
        import jax.numpy as jnp
        from jax.experimental import io_callback

        keys_shape = keys.shape
        flat = keys.reshape(-1)
        out_shape = jax.ShapeDtypeStruct(
            (flat.shape[0], self.dim), jnp.float32
        )

        if insert_missing:
            def host_fn(k):
                return self.gather(np.asarray(k))

            out = io_callback(host_fn, out_shape, flat, ordered=True)
        else:
            def host_fn(k):
                return self.gather_or_zeros(np.asarray(k))

            out = jax.pure_callback(host_fn, out_shape, flat)
        return out.reshape(*keys_shape, self.dim)


class GroupAdamOptimizer:
    """Sparse Adam over a KvVariable (reference:
    ``GroupAdamOptimizer``, tfplus/training/group_adam.py:28) —
    moment tables share the key space; only touched keys update."""

    def __init__(self, table: KvVariable, learning_rate: float = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.0):
        self._lib = _load()
        self.table = table
        self.m = KvVariable(table.dim, name=f"{table.name}/m")
        self.v = KvVariable(table.dim, name=f"{table.name}/v")
        self.lr = learning_rate
        self.beta1, self.beta2 = beta1, beta2
        self.eps = eps
        self.weight_decay = weight_decay
        self.step = 0

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray):
        self.step += 1
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        self._lib.kv_apply_group_adam(
            self.table._handle, self.m._handle, self.v._handle,
            _i64(keys), _f32(grads), keys.size,
            self.lr, self.beta1, self.beta2, self.eps,
            self.weight_decay, self.step,
        )

    def enable_spill(self, directory: str, max_dram_rows: int) -> None:
        """Spill the moment tables alongside the (separately
        configured or not) parameter table — training past DRAM
        needs ALL per-key state bounded, not just the embeddings."""
        _enable_slot_spill(self, directory, max_dram_rows)

    def slot_tables(self):
        """Optimizer-state tables keyed by slot name — the sparse
        checkpoint adapter registers them next to the parameter table
        so a restore brings the moments back bit-exact."""
        return {"m": self.m, "v": self.v}

    def state_scalars(self):
        """Non-table optimizer state (the bias-correction step
        counter) — without it a restored Adam replays with the wrong
        correction and the loss trajectory forks from the control."""
        return {"step": int(self.step)}

    def load_state_scalars(self, scalars):
        self.step = int(scalars.get("step", self.step))


def _enable_slot_spill(optimizer, directory: str, max_dram_rows: int):
    """Shared slot-table spill wiring: every slot spills to its own
    record file named after the parameter table and the slot."""
    import os as _os

    base = optimizer.table.name.replace("/", "_")
    for slot, table in optimizer.slot_tables().items():
        table.enable_spill(
            _os.path.join(directory, f"{base}_{slot}.spill"),
            max_dram_rows,
        )


class GroupAdagradOptimizer:
    """Sparse Adagrad (reference: tfplus/training/adagrad.py)."""

    def __init__(self, table: KvVariable, learning_rate: float = 0.1,
                 initial_accumulator: float = 0.1, eps: float = 1e-10):
        self._lib = _load()
        self.table = table
        self.acc = KvVariable(table.dim, name=f"{table.name}/acc")
        self.lr = learning_rate
        self.init_acc = initial_accumulator
        self.eps = eps

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        self._lib.kv_apply_group_adagrad(
            self.table._handle, self.acc._handle, _i64(keys),
            _f32(grads), keys.size, self.lr, self.init_acc, self.eps,
        )

    def enable_spill(self, directory: str, max_dram_rows: int) -> None:
        _enable_slot_spill(self, directory, max_dram_rows)

    def slot_tables(self):
        return {"acc": self.acc}


class GroupFtrlOptimizer:
    """Sparse FTRL (reference: tfplus/training/group_ftrl.py)."""

    def __init__(self, table: KvVariable, learning_rate: float = 0.1,
                 l1: float = 0.0, l2: float = 0.0):
        self._lib = _load()
        self.table = table
        self.z = KvVariable(table.dim, name=f"{table.name}/z")
        self.n = KvVariable(table.dim, name=f"{table.name}/n")
        self.lr = learning_rate
        self.l1, self.l2 = l1, l2

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        self._lib.kv_apply_group_ftrl(
            self.table._handle, self.z._handle, self.n._handle,
            _i64(keys), _f32(grads), keys.size, self.lr, self.l1,
            self.l2, -0.5,
        )

    def enable_spill(self, directory: str, max_dram_rows: int) -> None:
        _enable_slot_spill(self, directory, max_dram_rows)

    def slot_tables(self):
        return {"z": self.z, "n": self.n}


class SparseSGDOptimizer:
    """Plain sparse SGD (reference: tfplus
    training/gradient_descent.py) — no slot tables; the cheapest
    sparse trainer for frequency-skewed tails."""

    def __init__(self, table: KvVariable, learning_rate: float = 0.1):
        self._lib = _load()
        self.table = table
        self.lr = learning_rate

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray):
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        self._lib.kv_apply_sparse_sgd(
            self.table._handle, _i64(keys), _f32(grads), keys.size,
            self.lr,
        )

    def slot_tables(self):
        return {}


class SparseAdamOptimizer:
    """Plain sparse Adam (reference: tfplus training/adam.py):
    standard Adam whose bias correction rides the learning rate
    (``lr_t = lr * sqrt(1-b2^t)/(1-b1^t)``), vs the group flavour's
    per-dimension moment correction + decoupled weight decay."""

    def __init__(self, table: KvVariable, learning_rate: float = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8):
        self._lib = _load()
        self.table = table
        self.m = KvVariable(table.dim, name=f"{table.name}/m")
        self.v = KvVariable(table.dim, name=f"{table.name}/v")
        self.lr = learning_rate
        self.beta1, self.beta2 = beta1, beta2
        self.eps = eps
        self.step = 0

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray):
        self.step += 1
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        self._lib.kv_apply_sparse_adam(
            self.table._handle, self.m._handle, self.v._handle,
            _i64(keys), _f32(grads), keys.size,
            self.lr, self.beta1, self.beta2, self.eps, self.step,
        )

    def enable_spill(self, directory: str, max_dram_rows: int) -> None:
        _enable_slot_spill(self, directory, max_dram_rows)

    def slot_tables(self):
        return {"m": self.m, "v": self.v}

    def state_scalars(self):
        return {"step": int(self.step)}

    def load_state_scalars(self, scalars):
        self.step = int(scalars.get("step", self.step))


class RectifiedAdamOptimizer:
    """Sparse RAdam (reference: tfplus training/rectified_adam.py /
    Liu et al. 2019): the adaptive term engages only once the
    variance rectification ``r_t`` is defined (``rho_t > 4``); early
    steps fall back to bias-corrected momentum SGD — warm-up without
    a schedule, exactly the regime a freshly inserted embedding row
    lives in."""

    def __init__(self, table: KvVariable, learning_rate: float = 1e-3,
                 beta1: float = 0.9, beta2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.0):
        self._lib = _load()
        self.table = table
        self.m = KvVariable(table.dim, name=f"{table.name}/m")
        self.v = KvVariable(table.dim, name=f"{table.name}/v")
        self.lr = learning_rate
        self.beta1, self.beta2 = beta1, beta2
        self.eps = eps
        self.weight_decay = weight_decay
        self.step = 0

    def apply_gradients(self, keys: np.ndarray, grads: np.ndarray):
        self.step += 1
        keys = np.ascontiguousarray(keys, dtype=np.int64).reshape(-1)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        self._lib.kv_apply_rectified_adam(
            self.table._handle, self.m._handle, self.v._handle,
            _i64(keys), _f32(grads), keys.size,
            self.lr, self.beta1, self.beta2, self.eps,
            self.weight_decay, self.step,
        )

    def enable_spill(self, directory: str, max_dram_rows: int) -> None:
        _enable_slot_spill(self, directory, max_dram_rows)

    def slot_tables(self):
        return {"m": self.m, "v": self.v}

    def state_scalars(self):
        return {"step": int(self.step)}

    def load_state_scalars(self, scalars):
        self.step = int(scalars.get("step", self.step))
