"""TPU kernels (Pallas) and native ops — the rebuild's equivalents of
the reference's CUDA/C++ kernel layer (TFPlus flash-attn binding,
ATorch quantization kernels; SURVEY.md §2.7)."""
