"""Pallas flash attention (forward + backward) for TPU.

The reference binds a prebuilt CUDA FMHA library
(``tfplus/tfplus/flash_attn/kernels/flash_attention_fwd_kernel.cc:29``,
ATorch's module swaps in ``atorch/modules/transformer/layers.py``);
the TPU rebuild implements the kernel itself in Pallas: online-softmax
tiling so the [seq, seq] score matrix never materializes in HBM, MXU
matmuls in bf16 with fp32 accumulators, causal block skipping.

Layout: q, k, v are [batch, seq, heads, head_dim] (the model's bqhd).
Internally folded to [batch*heads, seq, head_dim]; the grid walks
(batch*heads, q_block, k_block) with the k_block axis innermost so the
running max/denominator scratch carries across k steps.

On CPU (tests / virtual mesh) the kernel runs in interpreter mode.
"""

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

NEG_INF = -1e30
# v5e-measured fwd+bwd block sweep (bq x bk in {256,512,1024}^2, seq
# 1k/2k/4k, head_dim 64/128, constant token count): 1024x1024 wins or
# ties everywhere — e.g. seq 2048/d64: 10.6 ms vs 15.7 ms at the old
# 512x512 default (1.48x).  The table keeps the per-shape winners;
# unlisted shapes fall back to min(1024, seq).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_TUNED_BLOCKS = {
    # (seq, head_dim) -> (block_q, block_k)
    (1024, 64): (512, 1024),
    (2048, 64): (1024, 1024),
    (4096, 64): (1024, 1024),
    (1024, 128): (1024, 1024),
    (2048, 128): (1024, 1024),
    (4096, 128): (1024, 1024),
}


def tuned_blocks(seq: int, head_dim: int):
    """Measured-best (block_q, block_k) for this shape (v5e sweep);
    min(1024, seq) when unmeasured."""
    if (seq, head_dim) in _TUNED_BLOCKS:
        return _TUNED_BLOCKS[(seq, head_dim)]
    b = min(1024, seq)
    return b, b


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref,      # [1, block_q, d], [1, block_k, d] x2
    o_ref,                    # [1, block_q, d]
    lse_ref,                  # [1, block_q]
    m_scr, l_scr, acc_scr,    # VMEM scratch
    *, scale: float, block_q: int, block_k: int, causal: bool,
):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: process only blocks with kv_start <= q_end
    run = True
    if causal:
        run = kv_idx * block_k <= q_idx * block_q + (block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        logits = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1))
        p = jnp.exp(logits - m_new[:, None])
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=1)
        acc_scr[:] = (
            acc_scr[:] * correction[:, None]
            + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kv_idx == num_kv - 1)
    def _final():
        l = m_scr[:] * 0.0 + l_scr[:]  # keep shapes aligned
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[:] + jnp.log(safe_l)


def _fwd(
    q, k, v, scale: float, causal: bool, block_q: int, block_k: int,
    group: int = 1,
):
    bh, seq, d = q.shape
    num_q = seq // block_q
    num_kv = seq // block_k
    grid = (bh, num_q, num_kv)

    # GQA: k/v carry bh//group rows; `group` consecutive q heads read
    # the same kv row through the index map — the repeated kv tensor
    # never materializes in HBM
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, block_q=block_q,
            block_k=block_k, causal=causal,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec(
                (1, block_k, d),
                lambda b, i, j: (b // group, j, 0),
            ),
            pl.BlockSpec(
                (1, block_k, d),
                lambda b, i, j: (b // group, j, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse carried as [bh, 1, seq]: (1, 1, block_q) blocks satisfy
            # the TPU (8, 128) tiling rule on the last two dims
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q,), jnp.float32),
            _scratch((block_q,), jnp.float32),
            _scratch((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


def _scratch(shape, dtype):
    return pltpu.VMEM(shape, dtype)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    dq_scr,
    *, scale: float, block_q: int, block_k: int, causal: bool,
):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(1)
    num_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = kv_idx * block_k <= q_idx * block_q + (block_q - 1)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        logits = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kv_idx == num_kv - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, scale: float, block_q: int, block_k: int, causal: bool,
):
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(1)
    num_q = pl.num_programs(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        # q block must reach at least the kv block start
        run = q_idx * block_q + (block_q - 1) >= kv_idx * block_k

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        logits = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = kv_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])
        # dv += p^T @ do
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        # dk += ds^T @ q
        dk_scr[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(q_idx == num_q - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(
    scale, causal, block_q, block_k, group, residuals, dout
):
    q, k, v, out, lse = residuals
    bh, seq, d = q.shape
    delta = jnp.sum(
        out.astype(jnp.float32) * dout.astype(jnp.float32), axis=-1
    )[:, None, :]  # [bh, 1, seq] to match the lse tiling layout

    num_q = seq // block_q
    num_kv = seq // block_k

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, block_q=block_q,
            block_k=block_k, causal=causal,
        ),
        grid=(bh, num_q, num_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec(
                (1, block_k, d),
                lambda b, i, j: (b // group, j, 0),
            ),
            pl.BlockSpec(
                (1, block_k, d),
                lambda b, i, j: (b // group, j, 0),
            ),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda b, i, j: (b, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, block_q=block_q,
            block_k=block_k, causal=causal,
        ),
        grid=(bh, num_kv, num_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec(
                (1, block_k, d),
                lambda b, j, i: (b // group, j, 0),
            ),
            pl.BlockSpec(
                (1, block_k, d),
                lambda b, j, i: (b // group, j, 0),
            ),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), v.dtype),
        ],
        scratch_shapes=[
            _scratch((block_k, d), jnp.float32),
            _scratch((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, dout, lse, delta)
    if group > 1:
        # per-q-head kv grads -> per-kv-head (rows sharing a kv head
        # are the `group` consecutive q heads)
        dk = dk.reshape(bh // group, group, seq, d).astype(
            jnp.float32
        ).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(bh // group, group, seq, d).astype(
            jnp.float32
        ).sum(axis=1).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_mha(q, k, v, scale, causal, block_q, block_k, group=1):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, group)
    return out


def _flash_mha_fwd(q, k, v, scale, causal, block_q, block_k,
                   group=1):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, group)
    return out, (q, k, v, out, lse)


def _flash_mha_bwd(scale, causal, block_q, block_k, group,
                   residuals, dout):
    return _bwd(
        scale, causal, block_q, block_k, group, residuals, dout
    )


_flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)


def _fit_block(s: int, requested: int) -> int:
    """Largest divisor of ``s`` that is <= requested — so a seq that
    is a multiple of 128 but not of the (large) default block still
    works, just with a smaller tile."""
    block = min(requested, s)
    while block > 1 and s % block:
        block //= 2
    if s % block:  # odd seq lens: fall back to the full sequence
        return s
    return block


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    dtype: Any = None,  # accepted for model-pluggability; output dtype
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    Drop-in for :func:`dlrover_tpu.models.gpt.xla_causal_attention`.
    Sequence length must be divisible by the block sizes (the caller
    pads; GPT training shapes are powers of two).

    GQA: ``k``/``v`` may carry fewer heads than ``q`` (``kv_heads``
    dividing ``heads``, kv-head-major q layout as in the Llama
    family); the forward and dq kernels read each kv head once per
    group through their index maps, so the repeated kv tensor never
    materializes there.  The dkv backward still emits per-q-head
    gradients (a transient group-x temporary) before the group
    reduction.
    """
    b, s, h, d = q.shape
    kvh = k.shape[2]
    if v.shape[2] != kvh:
        raise ValueError(
            f"k has {kvh} heads but v has {v.shape[2]}"
        )
    if h % kvh:
        raise ValueError(
            f"q heads {h} not a multiple of kv heads {kvh}"
        )
    group = h // kvh
    scale = scale if scale is not None else d**-0.5
    if block_q is None or block_k is None:
        tq, tk = tuned_blocks(s, d)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(s, block_k)
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq len {s} must be divisible by blocks "
            f"({block_q},{block_k})"
        )

    def fold(x):
        hh = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * hh, s, d)

    out = _flash_mha(
        fold(q), fold(k), fold(v), scale, causal, block_q, block_k,
        group,
    )
    out = out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    if dtype is not None:
        out = out.astype(dtype)
    return out


# dispatch layers (LlamaAttention) key on this instead of the impl
# string: only the plain flash path accepts kv_heads < heads
# (ulysses all-to-alls heads across devices and needs the repeat)
flash_attention.gqa_aware = True
