"""Kubernetes operator: ElasticJob/ScalePlan reconciliation.

Reference: the Go kubebuilder operator (``dlrover/go/operator/`` —
``ElasticJobReconciler`` creating the master pod per ElasticJob,
``scaleplan_controller.go``; CRD types in
``api/v1alpha1/elasticjob_types.go:29-118``).  Rebuilt as a Python
controller against the same API surface: CRD manifests in
``dlrover_tpu/operator/crds/`` and a reconciler loop that creates the
job-master pod, tracks job phase, and applies ScalePlans.
"""

from dlrover_tpu.operator.reconciler import (
    ElasticJobReconciler,
    JobPhase,
)

__all__ = ["ElasticJobReconciler", "JobPhase"]
