"""ElasticJob reconciler.

Reference: ``ElasticJobReconciler.Reconcile``
(``dlrover/go/operator/pkg/controllers/elasticjob_controller.go:85``)
+ master pod factory (``pkg/controllers/master/master.go``): for every
ElasticJob CR, ensure the job-master pod exists, reflect its state
into the job's phase/conditions, and clean up on completion.  The
master then owns worker pods itself (PodScaler) or writes ScalePlans.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.scheduler.kubernetes import K8sClient


class JobPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


def master_pod_name(job_name: str) -> str:
    return f"elasticjob-{job_name}-master"


def build_master_pod(job_name: str, spec: Dict) -> Dict:
    """Reference: master pod factory, pkg/controllers/master/master.go."""
    worker_spec = spec.get("replicaSpecs", {}).get("worker", {})
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": master_pod_name(job_name),
            "labels": {
                "app": "dlrover-tpu",
                "job": job_name,
                "role": "master",
                "node-id": "-1",
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [
                {
                    "name": "master",
                    "command": [
                        "python", "-m", "dlrover_tpu.master.main",
                        "--job_name", job_name,
                        "--node_num",
                        str(worker_spec.get("replicas", 1)),
                        "--platform", "kubernetes",
                    ],
                    "env": [
                        {"name": NodeEnv.JOB_NAME, "value": job_name},
                    ],
                }
            ],
        },
    }


class ElasticJobReconciler:
    def __init__(self, client: K8sClient):
        self._client = client

    def reconcile_once(self, jobs: Dict[str, Dict]) -> Dict[str, str]:
        """Process {job_name: elasticjob_cr}; returns {name: phase}.

        Idempotent — exactly the reconcile contract of the Go
        controller (missing master pod -> create; completed master ->
        propagate phase).
        """
        phases: Dict[str, str] = {}
        existing = {
            p["metadata"]["name"]: p
            for p in self._client.list_pods("app=dlrover-tpu")
        }
        for name, cr in jobs.items():
            pod_name = master_pod_name(name)
            pod = existing.get(pod_name)
            if pod is None:
                body = build_master_pod(name, cr.get("spec", {}))
                self._client.create_pod(body)
                phases[name] = JobPhase.PENDING
                logger.info(
                    "created master pod %s for job %s", pod_name, name
                )
                continue
            phase = pod.get("status", {}).get("phase", "Pending")
            phases[name] = {
                "Pending": JobPhase.PENDING,
                "Running": JobPhase.RUNNING,
                "Succeeded": JobPhase.SUCCEEDED,
                "Failed": JobPhase.FAILED,
            }.get(phase, JobPhase.PENDING)
            cr.setdefault("status", {})["phase"] = phases[name]
            cr["status"]["masterPod"] = pod_name
        return phases

    def run(self, get_jobs, interval: float = 5.0, stop_event=None):
        """Controller loop: poll CRs and reconcile (list+watch in the
        real deployment; polling keeps the mock path simple)."""
        while stop_event is None or not stop_event.is_set():
            try:
                self.reconcile_once(get_jobs())
            except Exception:  # noqa: BLE001
                logger.exception("reconcile failed")
            time.sleep(interval)
