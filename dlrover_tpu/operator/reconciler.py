"""ElasticJob reconciler.

Reference: ``ElasticJobReconciler.Reconcile``
(``dlrover/go/operator/pkg/controllers/elasticjob_controller.go:85``)
+ master pod factory (``pkg/controllers/master/master.go``): for every
ElasticJob CR, ensure the job-master pod exists, reflect its state
into the job's phase/conditions, and clean up on completion.  The
master then owns worker pods itself (PodScaler) or writes ScalePlans.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.scheduler.kubernetes import K8sClient


class JobPhase:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


def master_pod_name(job_name: str) -> str:
    return f"elasticjob-{job_name}-master"


def owner_reference(job_name: str, uid: str) -> list:
    """A valid ownerReference needs the owning CR's uid (the API
    server rejects it otherwise) — emit none when the uid is unknown
    (mock / plan-driven paths); the reconciler's explicit GC covers
    cleanup there."""
    if not uid:
        return []
    return [
        {
            "apiVersion": "elastic.dlrover-tpu.org/v1alpha1",
            "kind": "ElasticJob",
            "name": job_name,
            "uid": uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }
    ]


def build_master_pod(job_name: str, spec: Dict, uid: str = "") -> Dict:
    """Reference: master pod factory, pkg/controllers/master/master.go."""
    worker_spec = spec.get("replicaSpecs", {}).get("worker", {})
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": master_pod_name(job_name),
            "labels": {
                "app": "dlrover-tpu",
                "job": job_name,
                "role": "master",
                "node-id": "-1",
            },
            "ownerReferences": owner_reference(job_name, uid),
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [
                {
                    "name": "master",
                    "command": [
                        "python", "-m", "dlrover_tpu.master.main",
                        "--job_name", job_name,
                        "--node_num",
                        str(worker_spec.get("replicas", 1)),
                        "--platform", "kubernetes",
                    ],
                    "env": [
                        {"name": NodeEnv.JOB_NAME, "value": job_name},
                    ],
                }
            ],
        },
    }


class ElasticJobReconciler:
    def __init__(self, client: K8sClient):
        self._client = client

    def reconcile_once(self, jobs: Dict[str, Dict]) -> Dict[str, str]:
        """Process {job_name: elasticjob_cr}; returns {name: phase}.

        Idempotent — exactly the reconcile contract of the Go
        controller (missing master pod -> create; completed master ->
        propagate phase).
        """
        phases: Dict[str, str] = {}
        existing = {
            p["metadata"]["name"]: p
            for p in self._client.list_pods("app=dlrover-tpu")
        }
        # GC: pods owned by jobs whose CR is gone (a real cluster does
        # this via ownerReferences cascade; the mock needs it explicit)
        for pod_name, pod in list(existing.items()):
            labels = pod.get("metadata", {}).get("labels", {})
            owner = labels.get("job", "")
            if owner and owner not in jobs:
                logger.info(
                    "garbage-collecting pod %s of deleted job %s",
                    pod_name, owner,
                )
                self._client.delete_pod(pod_name)
                existing.pop(pod_name, None)
        for name, cr in jobs.items():
            pod_name = master_pod_name(name)
            pod = existing.get(pod_name)
            if pod is None:
                body = build_master_pod(
                    name, cr.get("spec", {}),
                    uid=cr.get("metadata", {}).get("uid", ""),
                )
                self._client.create_pod(body)
                phases[name] = JobPhase.PENDING
                logger.info(
                    "created master pod %s for job %s", pod_name, name
                )
                continue
            phase = pod.get("status", {}).get("phase", "Pending")
            phases[name] = {
                "Pending": JobPhase.PENDING,
                "Running": JobPhase.RUNNING,
                "Succeeded": JobPhase.SUCCEEDED,
                "Failed": JobPhase.FAILED,
            }.get(phase, JobPhase.PENDING)
            cr.setdefault("status", {})["phase"] = phases[name]
            cr["status"]["masterPod"] = pod_name
        return phases

    def run(self, get_jobs, interval: float = 5.0, stop_event=None):
        """Polling controller loop (simple deployments / tests)."""
        while stop_event is None or not stop_event.is_set():
            try:
                self.reconcile_once(get_jobs())
            except Exception:  # noqa: BLE001
                logger.exception("reconcile failed")
            time.sleep(interval)

    def run_watch(
        self, get_jobs, stop_event, resync_interval: float = 30.0
    ):
        """Informer-style controller loop (the Go operator's
        controller-runtime contract): a pod watch stream triggers a
        reconcile immediately on any cluster change, and a periodic
        resync covers events the stream missed.  A dying watch
        stream degrades to resync-interval polling, never to a
        stopped controller."""
        import queue
        import threading

        wake: "queue.Queue[str]" = queue.Queue()
        _STOP = "__stop__"

        def pump():
            try:
                while not stop_event.is_set():
                    try:
                        for etype, _pod in self._client.watch_pods(
                            "app=dlrover-tpu"
                        ):
                            wake.put(etype)
                            if stop_event.is_set():
                                return
                    except Exception:  # noqa: BLE001
                        logger.exception(
                            "pod watch failed; retrying"
                        )
                    # stream ended (idle timeout / apiserver hiccup)
                    stop_event.wait(0.5)
            finally:
                # unblock the main loop so shutdown is prompt, not
                # delayed by up to resync_interval
                wake.put(_STOP)

        threading.Thread(
            target=pump, daemon=True, name="elasticjob-watch"
        ).start()
        while not stop_event.is_set():
            try:
                self.reconcile_once(get_jobs())
            except Exception:  # noqa: BLE001
                logger.exception("reconcile failed")
            try:
                if wake.get(timeout=resync_interval) == _STOP:
                    return
                while True:  # drain the burst into one reconcile
                    try:
                        if wake.get_nowait() == _STOP:
                            return
                    except queue.Empty:
                        break
            except queue.Empty:
                pass  # periodic resync


def build_worker_pod(job_name: str, item: Dict) -> Dict:
    """Worker pod body from a ScalePlan createPods entry (reference:
    pod factory in scaleplan_controller.go)."""
    node_id = int(item.get("id", 0))
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": item.get(
                "name", f"{job_name}-worker-{node_id}"
            ),
            "labels": {
                "app": "dlrover-tpu",
                "job": job_name,
                "elasticjob-name": job_name,
                "node-type": item.get("type", "worker"),
                "node-id": str(node_id),
                "rank": str(item.get("rankIndex", node_id)),
            },
            "ownerReferences": owner_reference(
                job_name, item.get("ownerUid", "")
            ),
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [
                {
                    "name": "worker",
                    "command": ["tpurun"],
                    "env": [
                        {"name": NodeEnv.JOB_NAME, "value": job_name},
                        {
                            "name": NodeEnv.NODE_ID,
                            "value": str(node_id),
                        },
                    ],
                }
            ],
        },
    }


class ScalePlanReconciler:
    """Operator side of the ScalePlan CRD: executes plans the master's
    ``ElasticJobScaler`` writes — creates/removes worker pods — and
    records the outcome in the CR status (reference:
    ``scaleplan_controller.go``; the master-side consumer of externally
    written plans is ``master.watcher.ScalePlanWatcher``)."""

    def __init__(self, client: K8sClient):
        self._client = client

    def reconcile_once(self) -> int:
        from dlrover_tpu.master.watcher import (
            SCALE_PLAN_TERMINAL_PHASES,
        )

        executed = 0
        for cr in self._client.list_scale_plan_crs():
            status = cr.get("status", {})
            if status.get("phase") in SCALE_PLAN_TERMINAL_PHASES:
                continue
            spec = cr.get("spec", {})
            job_name = spec.get("ownerJob", "")
            name = cr.get("metadata", {}).get("name", "unnamed")
            created, removed = 0, 0
            try:
                for item in spec.get("createPods", []):
                    if self._client.create_pod(
                        build_worker_pod(job_name, item)
                    ):
                        created += 1
                for item in spec.get("removePods", []):
                    if self._client.delete_pod(item.get("name", "")):
                        removed += 1
                cr.setdefault("status", {})["phase"] = "Succeeded"
            except Exception as e:  # noqa: BLE001
                logger.exception("scale plan %s failed", name)
                cr.setdefault("status", {})["phase"] = "Failed"
                cr["status"]["message"] = str(e)
            cr["status"]["createdPods"] = created
            cr["status"]["removedPods"] = removed
            self._client.patch_scale_plan_status(name, cr)
            executed += 1
            logger.info(
                "scale plan %s: created %s removed %s pods",
                name, created, removed,
            )
        return executed

    def run(self, interval: float = 3.0, stop_event=None):
        while stop_event is None or not stop_event.is_set():
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001
                logger.exception("scale-plan reconcile failed")
            time.sleep(interval)
