"""AOT executable cache: recovery deserializes instead of re-tracing.

The PR 10 budget proved the recovery cycle is tracing-bound: with the
persistent XLA compile cache HIT, the respawned trainer still pays
~1.1 s of pure Python tracing to rebuild the jitted step before the
cache can even answer.  This module removes tracing from the critical
path: the first incarnation serializes its compiled step executable
(``jax.jit(...).lower(...).compile()`` through the
``jax.experimental.serialize_executable`` pair — capability-probed in
:func:`dlrover_tpu.common.jax_compat.executable_serialization`), and
every later incarnation *deserializes* it — no trace, no lowering, no
XLA compile, ~10 ms instead of seconds.

Keyed like the persistent compile cache (same sharing contract: every
incarnation of a job resolves the same directory), with the entry key
derived from everything that could invalidate the binary:

- jax / jaxlib version strings (a binary compiled by one jax must
  never load under another);
- backend platform + local device count + process count + world size
  (the mesh/topology half of the key — a resized world re-traces);
- the abstract avals (shape / dtype / weak_type) and shardings of
  every flattened input, plus the input treedef;
- a caller-supplied label (two different step functions with equal
  avals stay distinct);
- a code-identity fingerprint of the step function — bytecode,
  literal constants and closure contents, recursively
  (:func:`fn_fingerprint`) — so editing the loss or an optimizer
  hyperparameter invalidates the entry even though the avals and
  label did not change.

**Strict fall-back-to-trace**: any key mismatch, corrupt entry,
unpicklable treedef or deserialization error returns "miss" and the
caller traces exactly as before — a cache problem can cost time,
never correctness and never a crash.  Entries are written atomically
(tmp + rename) so a killed writer can't leave a torn entry a later
incarnation trips over.

The forkserver template (``DLROVER_AOT_PRETRACE``) calls
:func:`preload_entries` after its module preload: entry BYTES are read
into this module's memory, and every forked worker inherits them —
the child's :func:`load_entry` deserializes from the inherited buffer
without touching disk.  (The template itself never deserializes: that
would initialize an XLA client whose threads do not survive the fork.)
"""

import hashlib
import json
import os
import pickle
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from dlrover_tpu.common import env_utils, jax_compat
from dlrover_tpu.common.log import default_logger as logger

AOT_CACHE_DIR_ENV = "DLROVER_AOT_CACHE_DIR"
AOT_PRETRACE_ENV = "DLROVER_AOT_PRETRACE"
ENTRY_SUFFIX = ".aotx"
# pickle framing of one entry file; bumped when the layout changes so
# an old entry reads as a miss, not an unpickling surprise
_ENTRY_VERSION = 1

# template-preloaded entry bytes (filename -> blob): populated by
# preload_entries() in the forkserver template, inherited by every
# forked worker — load_entry() serves from here before touching disk
_PRELOADED: Dict[str, bytes] = {}


def aot_cache_dir() -> str:
    """The AOT entry directory every incarnation of this job shares:
    ``DLROVER_AOT_CACHE_DIR`` when the operator chose, else ``aot/``
    under the persistent compile cache's job-keyed directory (so the
    two caches ride the same sharing contract, including the
    cross-host case where both point at job-shared storage)."""
    explicit = os.getenv(AOT_CACHE_DIR_ENV, "").strip()
    if explicit:
        return explicit
    from dlrover_tpu.common.compile_cache import job_cache_dir

    return os.path.join(job_cache_dir(), "aot")


def _leaf_desc(leaf: Any) -> List:
    """[shape, dtype, weak_type, sharding] of one abstract input leaf
    — works for concrete ``jax.Array``s, ``ShapeDtypeStruct``s and
    anything else carrying shape/dtype.  JSON-safe types only (lists,
    not tuples): descriptors round-trip through the label index's
    JSON, and equality against the pickled copy must survive it."""
    shape = [int(d) for d in getattr(leaf, "shape", ())]
    dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
    weak = bool(getattr(leaf, "weak_type", False))
    sharding = getattr(leaf, "sharding", None)
    return [shape, dtype, weak, repr(sharding) if sharding else ""]


def fn_fingerprint(fn: Any) -> str:
    """Code-identity component of the key: a hash over the function's
    bytecode, literal constants, and (recursively, bounded) the same
    for every function reachable through its closure — so editing the
    loss, changing an optimizer hyperparameter captured in a closure,
    or swapping the model config invalidates the entry even though
    label, avals and topology are unchanged.  Avals can't see code;
    without this, a persistent cache dir could silently serve an
    executable compiled from DIFFERENT code.  Deliberately
    conservative the other way too: values whose ``repr`` embeds a
    memory address contribute only their type name, so structurally
    identical closures hash identically across processes (the
    cross-process hit this cache exists for).  Unhashable oddities
    degrade to a sentinel — a stale-hit risk narrowed, never a crash.
    """
    h = hashlib.sha256()
    seen: set = set()

    def feed_callable(obj, depth):
        if depth > 8 or id(obj) in seen:
            return
        seen.add(id(obj))
        wrapped = getattr(obj, "__wrapped__", None)
        code = getattr(obj, "__code__", None)
        if code is None and wrapped is not None:
            feed_callable(wrapped, depth)
            return
        if code is None:
            feed_value(getattr(obj, "__call__", obj), depth + 1)
            return
        h.update(code.co_code)
        for const in code.co_consts:
            if isinstance(
                const, (int, float, str, bytes, bool, type(None))
            ):
                h.update(repr(const).encode("utf-8"))
            elif hasattr(const, "co_code"):
                h.update(const.co_code)
        for cell in getattr(obj, "__closure__", None) or ():
            try:
                feed_value(cell.cell_contents, depth + 1)
            except ValueError:  # empty cell
                continue

    def feed_value(v, depth):
        if depth > 8 or id(v) in seen:
            return
        if callable(v) and (
            hasattr(v, "__code__") or hasattr(v, "__wrapped__")
        ):
            feed_callable(v, depth)
            return
        if isinstance(v, (tuple, list)):
            seen.add(id(v))
            for item in v[:32]:
                feed_value(item, depth + 1)
            return
        if isinstance(v, dict):
            seen.add(id(v))
            for k in sorted(map(repr, v))[:32]:
                h.update(k.encode("utf-8"))
            for item in list(v.values())[:32]:
                feed_value(item, depth + 1)
            return
        try:
            r = repr(v)
        except Exception:  # noqa: BLE001 - repr is best-effort
            r = ""
        if " at 0x" in r:
            # address-bearing default repr: unstable across
            # processes — identity reduces to the type
            h.update(type(v).__name__.encode("utf-8"))
        else:
            h.update(r[:512].encode("utf-8"))

    try:
        feed_callable(fn, 0)
        return h.hexdigest()[:16]
    except Exception:  # noqa: BLE001 - never crash the resolve
        return "unhashable"


def describe(
    example_args: Tuple, label: str = "step", fn: Any = None
) -> Dict:
    """The invalidation descriptor an entry is keyed by (see module
    docstring).  ``example_args`` is the positional-argument tuple the
    step will be called with — concrete arrays or
    ``jax.ShapeDtypeStruct`` trees both work; ``fn`` contributes the
    code-identity component (see :func:`fn_fingerprint`)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(example_args)
    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", "")
    except ImportError:  # pragma: no cover - jaxlib rides with jax
        jaxlib_version = ""
    return {
        "v": _ENTRY_VERSION,
        "label": str(label),
        "fn": fn_fingerprint(fn) if fn is not None else "",
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": jax.default_backend(),
        "devices": jax.local_device_count(),
        "processes": int(os.getenv("DLROVER_NUM_PROCESSES", "1")),
        "world_size": env_utils.get_world_size(),
        "in_tree": str(treedef),
        "avals": [_leaf_desc(x) for x in leaves],
    }


def key_of(desc: Dict) -> str:
    blob = json.dumps(desc, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:32]


def entry_path(key: str, cache_dir: Optional[str] = None) -> str:
    cache_dir = cache_dir or aot_cache_dir()
    return os.path.join(cache_dir, key + ENTRY_SUFFIX)


def aot_entries(cache_dir: Optional[str] = None) -> int:
    """Number of serialized executables in the cache — the AOT half
    of the compile-cache hit witness."""
    cache_dir = cache_dir or aot_cache_dir()
    try:
        return sum(
            1 for f in os.listdir(cache_dir)
            if f.endswith(ENTRY_SUFFIX)
        )
    except OSError:
        return 0


# descriptor fields that do NOT need the example avals — the label
# index validates these cheaply on the warm fast path; the aval half
# is enforced by the loaded executable's own input validation at
# first call (with _GuardedCall falling back to trace on mismatch)
_ENV_FIELDS = (
    "v", "label", "jax", "jaxlib", "platform", "devices",
    "processes", "world_size",
)


def _index_path(label: str, cache_dir: str) -> str:
    safe = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in label
    )
    return os.path.join(cache_dir, safe + ".idx")


def _write_index(label: str, key: str, desc: Dict, cache_dir: str):
    """Label → (key, descriptor) sidecar: the warm fast path resolves
    by LABEL without re-deriving the avals (the ``eval_shape`` that
    would otherwise cost ~1 s of the recovery critical path)."""
    path = _index_path(label, cache_dir)
    try:
        fd, tmp = tempfile.mkstemp(dir=cache_dir, suffix=".idx.tmp")
        with os.fdopen(fd, "w") as f:
            json.dump({"key": key, "desc": desc}, f, default=str)
        os.replace(tmp, path)
    except OSError as e:
        logger.debug("aot index write failed (%s): %s", path, e)


def _read_index(label: str, cache_dir: str) -> Optional[Dict]:
    name = os.path.basename(_index_path(label, cache_dir))
    blob = _PRELOADED.get(name)
    if blob is None:
        try:
            with open(_index_path(label, cache_dir), "rb") as f:
                blob = f.read()
        except OSError:
            return None
    try:
        idx = json.loads(blob.decode("utf-8"))
        if not isinstance(idx.get("key"), str) or not isinstance(
            idx.get("desc"), dict
        ):
            return None
        return idx
    except (ValueError, UnicodeDecodeError):
        return None


def env_desc() -> Dict:
    """The aval-free half of :func:`describe` — everything cheap to
    compute on the warm fast path (backend init is the only cost)."""
    full = describe((), label="")
    return {
        k: full[k] for k in _ENV_FIELDS if k not in ("label",)
    }


def save_entry(
    key: str,
    desc: Dict,
    compiled: Any,
    cache_dir: Optional[str] = None,
) -> bool:
    """Serialize ``compiled`` (a ``Lowered.compile()`` result) under
    ``key``.  Atomic (tmp + rename) and non-fatal: any failure logs
    and returns False — the next incarnation traces, nothing worse."""
    serialize, _ = jax_compat.executable_serialization()
    if serialize is None:
        return False
    cache_dir = cache_dir or aot_cache_dir()
    path = entry_path(key, cache_dir)
    try:
        payload, in_tree, out_tree = serialize(compiled)
        blob = pickle.dumps({
            "v": _ENTRY_VERSION,
            "desc": desc,
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        })
        os.makedirs(cache_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=cache_dir, suffix=ENTRY_SUFFIX + ".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True
    except Exception as e:  # noqa: BLE001 - cache write is optional
        logger.warning("aot cache write failed (%s): %s", path, e)
        return False


def load_entry(
    key: str,
    desc: Dict,
    cache_dir: Optional[str] = None,
    timings: Optional[Dict[str, float]] = None,
) -> Optional[Any]:
    """Deserialize the entry under ``key`` into a ready-to-call
    loaded executable, or None on ANY problem (absent, corrupt,
    descriptor mismatch, unknown pytree nodes, deserializer error) —
    the caller falls back to tracing.  ``timings`` (optional dict)
    receives the read/unpickle/deserialize breakdown."""
    _, deserialize_and_load = jax_compat.executable_serialization()
    if deserialize_and_load is None:
        return None
    cache_dir = cache_dir or aot_cache_dir()
    name = key + ENTRY_SUFFIX
    t0 = time.perf_counter()
    blob = _PRELOADED.get(name)
    if blob is None:
        try:
            with open(entry_path(key, cache_dir), "rb") as f:
                blob = f.read()
        except OSError:
            return None
    if timings is not None:
        timings["read_s"] = time.perf_counter() - t0
    try:
        t0 = time.perf_counter()
        entry = pickle.loads(blob)
        if timings is not None:
            timings["unpickle_s"] = time.perf_counter() - t0
        if entry.get("v") != _ENTRY_VERSION:
            return None
        if entry.get("desc") != desc:
            # filename collisions are cryptographically unlikely; a
            # mismatch here means a hand-copied or stale entry — the
            # binary must not run against the wrong avals/topology
            return None
        t0 = time.perf_counter()
        c0 = time.thread_time()
        loaded = deserialize_and_load(
            entry["payload"], entry["in_tree"], entry["out_tree"]
        )
        if timings is not None:
            timings["deserialize_s"] = time.perf_counter() - t0
            # wall ≫ cpu here means the deserialize was CPU-starved
            # by the rest of the recovery, not slow by itself
            timings["deserialize_cpu_s"] = time.thread_time() - c0
        return loaded
    except Exception as e:  # noqa: BLE001 - strict fall-back-to-trace
        logger.warning("aot cache entry %s unusable: %s", name, e)
        return None


def preload_entries(
    cache_dir: Optional[str] = None,
    max_bytes: int = 512 * 2**20,
) -> Tuple[int, int]:
    """Read every entry's BYTES into module memory (forkserver
    template path: forked workers inherit the buffers and skip the
    disk read).  Incremental — already-preloaded names are skipped,
    so the template can re-scan cheaply before every fork and pick up
    the entry the PREVIOUS incarnation wrote.  Bounded by
    ``max_bytes`` total; returns ``(new_entries, new_bytes)``.
    Never raises and never touches jax — the template must not
    initialize an XLA client."""
    cache_dir = cache_dir or aot_cache_dir()
    count = total = 0
    try:
        names = sorted(os.listdir(cache_dir))
    except OSError:
        return (0, 0)
    for name in names:
        if not name.endswith((ENTRY_SUFFIX, ".idx")):
            continue
        if name.endswith(ENTRY_SUFFIX) and name in _PRELOADED:
            # entries are content-keyed and immutable: cache by name.
            # Index files are MUTATED in place (os.replace on every
            # miss) — always re-read them, or a resize/retrace would
            # leave every later fork resolving through stale bytes
            continue
        try:
            with open(os.path.join(cache_dir, name), "rb") as f:
                blob = f.read(max_bytes - total + 1)
        except OSError:
            continue
        if total + len(blob) > max_bytes:
            logger.warning(
                "aot preload budget (%d MB) reached; %s and later "
                "entries stay on disk", max_bytes >> 20, name,
            )
            break
        _PRELOADED[name] = blob
        count += 1
        total += len(blob)
    return (count, total)


def preloaded_entries() -> int:
    """How many entries the template preloaded (inherited over
    fork) — the pre-trace path's witness."""
    return len(_PRELOADED)


def pretrace_enabled() -> bool:
    return os.getenv(AOT_PRETRACE_ENV, "").strip().lower() in (
        "1", "true", "yes", "on",
    )


@dataclass
class Resolution:
    """What :func:`resolve_step` decided.

    ``fn`` is always callable with the original arguments.  ``source``
    is ``"aot"`` (deserialized executable — no trace anywhere),
    ``"trace"`` (traced+compiled, either eagerly inside the resolve
    when ``deferred`` is False, or at first call when True) or
    ``"off"`` (serialization unavailable — plain jit semantics)."""

    fn: Any
    source: str
    key: str = ""
    dir: str = ""
    hit: bool = False
    wrote: bool = False
    deferred: bool = False
    load_s: float = 0.0
    trace_s: float = 0.0
    save_s: float = 0.0
    reason: str = ""
    preloaded: bool = False
    extra: Dict = field(default_factory=dict)


class _GuardedCall:
    """First-call safety net over a deserialized executable: if the
    very first invocation fails (an aval drift the key missed, a
    backend refusing the binary), fall back to the original traced
    path PERMANENTLY instead of crashing the recovery.  After one
    success the guard is a single attribute check per step."""

    __slots__ = ("_primary", "_fallback", "_proven")

    def __init__(self, primary, fallback):
        self._primary = primary
        self._fallback = fallback
        self._proven = False

    def __call__(self, *args, **kwargs):
        if self._primary is None:
            return self._fallback(*args, **kwargs)
        try:
            out = self._primary(*args, **kwargs)
            self._proven = True
            return out
        except Exception as e:  # noqa: BLE001 - never crash recovery
            if self._proven:
                raise  # a mid-training failure is not a cache problem
            logger.warning(
                "aot executable rejected at first call (%s); "
                "falling back to trace", e,
            )
            self._primary = None
            return self._fallback(*args, **kwargs)


def resolve_step(
    fn: Any,
    example_args,
    label: str = "step",
    cache_dir: Optional[str] = None,
) -> Resolution:
    """Resolve a jitted step function through the AOT cache.

    ``fn`` is the ``jax.jit`` wrapper (anything with ``.lower``);
    ``example_args`` the positional tuple it will be called with
    (concrete arrays or ``ShapeDtypeStruct`` trees) — or a ZERO-ARG
    CALLABLE returning that tuple, which arms the warm fast path:
    the label index resolves straight to an entry, the aval-free
    descriptor fields are validated, and the example build (the
    ``eval_shape`` that costs real critical-path time in a respawn)
    never runs; the aval half of the key is enforced by the loaded
    executable's own input validation at first call, with
    :class:`_GuardedCall` falling back to trace on mismatch.

    HIT: returns the deserialized executable (guarded).  MISS:
    traces+compiles NOW (``trace_s`` is the measured retrace) and
    WRITES the entry + label index so incarnation N+1 hits.
    Off/error: returns ``fn`` untouched with ``deferred=True`` — the
    first call traces exactly as without this module."""
    cache_dir = cache_dir or aot_cache_dir()
    serialize, _ = jax_compat.executable_serialization()
    if serialize is None:
        return Resolution(
            fn=fn, source="off", deferred=True, dir=cache_dir,
            reason="jax has no serialize_executable",
        )
    if callable(example_args) and not isinstance(
        example_args, (list, tuple)
    ):
        builder = example_args
        fast = _resolve_fast(fn, label, cache_dir)
        if fast is not None:
            return fast
        try:
            example_args = builder()
        except Exception as e:  # noqa: BLE001 - builder failed
            return Resolution(
                fn=fn, source="off", deferred=True, dir=cache_dir,
                reason=f"example builder failed: {e}",
            )
    try:
        desc = describe(example_args, label=label, fn=fn)
        key = key_of(desc)
    except Exception as e:  # noqa: BLE001 - odd example trees
        return Resolution(
            fn=fn, source="off", deferred=True, dir=cache_dir,
            reason=f"descriptor failed: {e}",
        )
    preloaded = (key + ENTRY_SUFFIX) in _PRELOADED
    t0 = time.perf_counter()
    loaded = load_entry(key, desc, cache_dir)
    load_s = time.perf_counter() - t0
    if loaded is not None:
        return Resolution(
            fn=_GuardedCall(loaded, fn), source="aot", key=key,
            dir=cache_dir, hit=True, load_s=load_s,
            preloaded=preloaded,
        )
    if not hasattr(fn, "lower"):
        return Resolution(
            fn=fn, source="off", key=key, dir=cache_dir,
            deferred=True, load_s=load_s,
            reason="fn has no .lower (not a jit wrapper)",
        )
    try:
        t0 = time.perf_counter()
        compiled = fn.lower(*example_args).compile()
        trace_s = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001 - abstract lowering failed
        return Resolution(
            fn=fn, source="trace", key=key, dir=cache_dir,
            deferred=True, load_s=load_s,
            reason=f"lower/compile failed: {e}",
        )
    t0 = time.perf_counter()
    wrote = save_entry(key, desc, compiled, cache_dir)
    if wrote:
        _write_index(label, key, desc, cache_dir)
    save_s = time.perf_counter() - t0
    return Resolution(
        # guarded like the hit path: the compile ran against the
        # ABSTRACT examples — if the real first-call avals drift from
        # them, fall back to the plain jit (which traces against the
        # actual arguments) instead of crashing the cold recovery
        fn=_GuardedCall(compiled, fn), source="trace", key=key,
        dir=cache_dir, wrote=wrote, load_s=load_s, trace_s=trace_s,
        save_s=save_s,
    )


def _resolve_fast(
    fn: Any, label: str, cache_dir: str
) -> Optional[Resolution]:
    """The warm fast path: label index → entry, no example build.
    Returns None when anything falls short (no index, env drift,
    unusable entry) — the caller runs the full keyed path."""
    idx = _read_index(label, cache_dir)
    if idx is None:
        return None
    try:
        env = env_desc()
    except Exception:  # noqa: BLE001 - no backend yet / odd jax
        return None
    desc = idx["desc"]
    if desc.get("label") != label:
        return None
    if desc.get("fn") != fn_fingerprint(fn):
        # the code changed since the entry was written: the binary
        # must not run, however well the avals would have matched
        return None
    for field_name in _ENV_FIELDS:
        if field_name == "label":
            continue
        if desc.get(field_name) != env.get(field_name):
            return None
    t0 = time.perf_counter()
    timings: Dict[str, float] = {}
    loaded = load_entry(idx["key"], desc, cache_dir, timings=timings)
    load_s = time.perf_counter() - t0
    if loaded is None:
        return None
    return Resolution(
        fn=_GuardedCall(loaded, fn), source="aot", key=idx["key"],
        dir=cache_dir, hit=True, load_s=load_s,
        preloaded=(idx["key"] + ENTRY_SUFFIX) in _PRELOADED,
        extra={"fast": True, **timings},
    )
