"""Framework-wide constants and the environment-variable contract.

Mirrors the role of ``dlrover/python/common/constants.py`` in the
reference (NodeType/NodeStatus/RendezvousName/NodeEnv/...), re-targeted
at TPU pod slices: accelerator types are TPU generations, the
communication fabric is ICI/DCN rather than NCCL, and the env contract
feeds ``jax.distributed.initialize`` instead of ``torch.distributed``.
"""


class NodeType:
    MASTER = "master"
    WORKER = "worker"
    # Parameter-server style roles kept for sparse/PS-parity jobs
    # (reference: common/constants.py NodeType).
    PS = "ps"
    CHIEF = "chief"
    EVALUATOR = "evaluator"


class NodeStatus:
    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    BREAKDOWN = "breakdown"
    UNKNOWN = "unknown"

    @classmethod
    def end_states(cls):
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED}


class NodeEventType:
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeExitReason:
    """Classified exit reasons (reference: k8s_watcher exit-reason
    classification + common/constants.py NodeExitReason)."""

    SUCCEEDED = "succeeded"
    KILLED = "killed"
    OOM = "oom"
    FATAL_ERROR = "fatal_error"
    HARDWARE_ERROR = "hardware_error"
    PREEMPTED = "preempted"          # TPU/GCE preemption signal
    RELAUNCHED = "relaunched"
    UNKNOWN = "unknown"


class RendezvousName:
    ELASTIC_TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class Accelerators:
    """TPU generations plus CPU for local testing.

    Reference keys NVIDIA_GPU/ASCEND_NPU (common/constants.py) become
    TPU generations; the health-check payload and mesh topology depend
    on this.
    """

    TPU_V4 = "tpu-v4"
    TPU_V5E = "tpu-v5e"
    TPU_V5P = "tpu-v5p"
    TPU_V6E = "tpu-v6e"
    CPU = "cpu"


class TrainingExceptionLevel:
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    WARNING = "warning"
    INFO = "info"


class ErrorMonitorConstants:
    TYPE_INFO = "info"
    TYPE_WARN = "warn"
    TYPE_ERROR = "error"
    ACTION_RELAUNCH = "relaunch"
    ACTION_ABORT = "abort"
    ACTION_ISOLATE = "isolate"
    ACTION_NONE = "none"


class MasterAction:
    """Actions the master piggybacks on a heartbeat ack for the agent
    to execute (the diagnosis chain's culprit-only relaunch path and
    the elastic world-resize drain)."""

    RESTART_WORKERS = "restart_workers"
    # elastic world-resize: stop the local workers and re-join the
    # rendezvous so the job reconverges at the master's new target
    # world size (a planned drain, not a failure — no restart budget)
    RESIZE = "resize"


class CheckpointConstant:
    """Flash-checkpoint file naming (reference:
    common/constants.py CheckpointConstant + ckpt_saver commit files)."""

    CKPT_NAME_PREFIX = "checkpoint-"
    TRACKER_FILE = "latest_checkpointed_iteration.txt"
    DONE_FILE_PREFIX = ".done_"
    MODEL_STATES_NAME = "model_states"
    SAVE_TIMEOUT = 600


class JobExitReason:
    SUCCEEDED = "succeeded"
    CODE_ERROR = "code_error"
    HANG_ERROR = "hang_error"
    RDZV_ERROR = "rdzv_error"
    UNKNOWN_ERROR = "unknown_error"


class NodeEnv:
    """Env-var contract between agent and training process.

    The agent exports these before spawning training processes; the
    in-process library reads them.  Reference: common/constants.py
    NodeEnv (DLROVER_MASTER_ADDR, NODE_RANK, ...), retargeted so that
    training processes can call ``jax.distributed.initialize`` with the
    coordinator negotiated through the master rendezvous.
    """

    MASTER_ADDR = "DLROVER_MASTER_ADDR"
    JOB_NAME = "DLROVER_JOB_NAME"
    NODE_ID = "DLROVER_NODE_ID"
    NODE_RANK = "DLROVER_NODE_RANK"
    NODE_NUM = "DLROVER_NODE_NUM"
    # jax.distributed coordinates, set by the agent after rendezvous.
    COORDINATOR_ADDR = "DLROVER_COORDINATOR_ADDR"
    PROCESS_ID = "DLROVER_PROCESS_ID"
    NUM_PROCESSES = "DLROVER_NUM_PROCESSES"
    LOCAL_RANK = "DLROVER_LOCAL_RANK"
    LOCAL_WORLD_SIZE = "DLROVER_LOCAL_WORLD_SIZE"
    RANK = "DLROVER_RANK"
    WORLD_SIZE = "DLROVER_WORLD_SIZE"
    # Restart accounting
    RESTART_COUNT = "DLROVER_RESTART_COUNT"
    # Fault injection for tests (reference: node_check/utils.py
    # MOCK_ERR_RANK mock_error()).
    MOCK_ERR_RANK = "MOCK_ERR_RANK"
    # Monitoring
    MONITOR_ENABLED = "DLROVER_MONITOR_ENABLED"
    # Paral-config file path for runtime auto-tuning
    PARAL_CONFIG_PATH = "DLROVER_PARAL_CONFIG_PATH"
    # Accelerator type (Accelerators.*)
    ACCELERATOR = "DLROVER_ACCELERATOR"


class GRPC:
    """Transport limits for the master<->agent message channel."""

    MAX_MESSAGE_BYTES = 512 * 1024 * 1024


class RendezvousConstant:
    DEFAULT_TIMEOUT = 600
    WAITING_TIMEOUT = 60
    JOIN_INTERVAL = 3


class NetworkCheckConstant:
    # Straggler rule: elapsed > STRAGGLER_FACTOR * median
    # (reference: rdzv_manager.py:550-565 _detect_stragglers).
    STRAGGLER_FACTOR = 2.0
    MAX_CHECK_ROUNDS = 2
    CHECK_TIMEOUT = 300


class TrainingLoopConstant:
    # Seconds without a step report before the master calls the
    # job hung (reference: dist_master.py:242-248, global_context).
    HANG_TIMEOUT = 1800


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "kubernetes"
    RAY = "ray"


class DistributionStrategy:
    ALLREDUCE = "AllreduceStrategy"  # SPMD data-parallel family
    PS = "ParameterServerStrategy"
    LOCAL = "Local"


class ReporterType:
    LOG = "log"
    MASTER = "master"


class TaskType:
    """Dynamic data-sharding task types (reference:
    elastic_training.proto TaskType + shard managers)."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    NONE = "none"


class DefaultPorts:
    MASTER = 51051
    COORDINATOR = 52525
