"""Socket message transport between master, agents and trainers.

The reference runs a gRPC service with a single generic ``report``/
``get`` RPC pair whose payloads are pickled dataclasses
(``dlrover/proto/elastic_training.proto:31-34``,
``dlrover/python/common/grpc.py``).  We keep exactly that contract —
two verbs, typed dataclass payloads — over a plain threaded TCP server
with length-prefixed frames: no proto codegen, same dispatch model, and
the unpickler is restricted to the message schema so a stray client
cannot execute arbitrary reduce callables.

Frame format: 8-byte big-endian length + pickle of
``(verb, node_id, node_type, req_id, message[, trace_ctx])``; response
frame is a pickled response message (``get``) or a bool ack
(``report``).  The ``req_id`` makes retries safe: the server caches
responses by id and replays them instead of re-executing a handler
whose response frame was lost, so reconnect-and-resend is exactly-once
for non-idempotent requests (KV ``add`` barriers, failure reports,
queue gets).  ``trace_ctx`` is the optional telemetry trace context
(``{"trace_id", "span_id"}``): the server adopts it while dispatching
so a handler-opened span is a child of the caller's span; 5-tuple
frames from older peers still dispatch.
"""

import io
import os
import pickle
import random
import socket
import socketserver
import struct
import threading
import time
import traceback
import uuid
from collections import OrderedDict
from typing import Optional

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.common.constants import GRPC, NodeEnv
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.telemetry import tracing as _tracing
from dlrover_tpu.telemetry.metrics import get_registry as _get_registry

_RPC_RETRIES_TOTAL = _get_registry().counter(
    "dlrover_rpc_client_retries_total",
    "Client roundtrips that failed and entered backoff, by verb",
)
_RPC_RECONNECTS_TOTAL = _get_registry().counter(
    "dlrover_rpc_client_reconnects_total",
    "TCP connections the client established (first + after drops)",
)
_RPC_RESYNC_PARKS_TOTAL = _get_registry().counter(
    "dlrover_rpc_resync_parks_total",
    "Roundtrips that exhausted retries and parked awaiting a "
    "master respawn",
)
_RPC_RESYNC_RECONNECTS_TOTAL = _get_registry().counter(
    "dlrover_rpc_resync_reconnects_total",
    "Parked clients that found the master back and resumed",
)
# fleet fan-in visibility: the threaded server spawns one thread per
# connection — with hundreds of agents that pile-up was invisible.
# state: accepted (lifetime), active (now), peak (high-water)
_CONNS_GAUGE = _get_registry().gauge(
    "dlrover_master_connections",
    "Message-server connections by state (accepted/active/peak)",
)
_CONNS_REJECTED_TOTAL = _get_registry().counter(
    "dlrover_master_conns_rejected_total",
    "Connects refused by the DLROVER_MASTER_MAX_CONNS guard",
)
# server-side turnaround per bare verb (frame decode -> response
# sent), next to the handler-only dlrover_rpc_seconds: the difference
# is dispatch overhead (response cache, chaos hook, pickling, send)
_RPC_SERVER_SECONDS = _get_registry().histogram(
    "dlrover_rpc_server_seconds",
    "Server-side request turnaround by bare verb (frame decode to "
    "response sent); subtracting the handler-only "
    "dlrover_rpc_seconds leaves the dispatch overhead",
)

# connection-guard knob: reject connects beyond this many concurrent
# connections with a clean RemoteError frame instead of a silent
# thread pile-up; 0 = unlimited (the historical behaviour)
MAX_CONNS_ENV = "DLROVER_MASTER_MAX_CONNS"

# reconnect-hardening knobs (chaos partition scenarios hammer this
# path; prod defaults preserve the former envelope: 0.5 s doubling,
# capped at 8 s)
RPC_RETRIES_ENV = "DLROVER_RPC_RETRIES"
RPC_BACKOFF_BASE_ENV = "DLROVER_RPC_BACKOFF_BASE"
RPC_BACKOFF_MAX_ENV = "DLROVER_RPC_BACKOFF_MAX"
# master crash recovery: when > 0, a client whose retry envelope is
# exhausted does NOT give up — it parks in a bounded re-resolve/
# reconnect loop (the master may be respawning; its address may have
# moved, so DLROVER_MASTER_ADDR is re-read every probe) and, once the
# master answers again, replays a session-resync handshake before
# resuming the original request
RPC_RESYNC_TIMEOUT_ENV = "DLROVER_MASTER_RESYNC_TIMEOUT"


def compute_backoff(
    attempt: int,
    base: float = 0.5,
    cap: float = 8.0,
    rng: Optional[random.Random] = None,
) -> float:
    """Jittered exponential backoff: ``base * 2**attempt`` capped at
    ``cap``, with equal jitter (uniform over the upper half) so a
    partition that drops N clients at once does not resynchronize them
    into a reconnect stampede against a just-recovered master."""
    # clamp the exponent BEFORE exponentiating: with env-tuned retry
    # counts in the thousands (riding out a long partition), a bare
    # 2.0**attempt overflows to OverflowError mid-retry-loop
    b = min(base * (2.0 ** min(attempt, 60)), cap)
    rng = rng or random
    return b / 2.0 + rng.uniform(0.0, b / 2.0)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default

_LEN = struct.Struct(">Q")
_MAX_FRAME = GRPC.MAX_MESSAGE_BYTES

# Strict allowlist: dataclass message schema, container/scalar literals,
# and the numpy array reconstructors.  builtins is NOT broadly allowed —
# getattr/__import__ would be a remote-code-execution hole.
_ALLOWED_MODULE_PREFIXES = ("dlrover_tpu.",)
_ALLOWED_GLOBALS = {
    ("builtins", "set"),
    ("builtins", "frozenset"),
    ("builtins", "list"),
    ("builtins", "dict"),
    ("builtins", "tuple"),
    ("builtins", "bytearray"),
    ("builtins", "complex"),
    ("builtins", "bool"),
    ("builtins", "int"),
    ("builtins", "float"),
    ("builtins", "str"),
    ("builtins", "bytes"),
    ("builtins", "slice"),
    ("builtins", "range"),
    ("collections", "OrderedDict"),
    ("collections", "defaultdict"),
    ("collections", "deque"),
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    # contiguous-array fast path (protocol 5 pickles of ndarrays):
    # a pure reconstructor, builds an ndarray from raw bytes
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _ALLOWED_GLOBALS:
            return super().find_class(module, name)
        if module.startswith("numpy") and name in ("dtype", "ndarray"):
            return super().find_class(module, name)
        if any(module.startswith(p) for p in _ALLOWED_MODULE_PREFIXES):
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"forbidden global {module}.{name} in message"
        )


def _loads(data: bytes):
    return _RestrictedUnpickler(io.BytesIO(data)).load()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _send_frame(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > _MAX_FRAME:
        raise ValueError(f"frame too large: {len(payload)} bytes")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket):
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > _MAX_FRAME:
        raise ValueError(f"frame too large: {length} bytes")
    return _loads(_recv_exact(sock, length))


def find_free_port(host: str = "") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def addr_connected(addr: str, timeout: float = 2.0) -> bool:
    """Telnet-style reachability probe (reference:
    elastic_run.py:326 _check_to_use_dlrover_run)."""
    try:
        host, port = addr.rsplit(":", 1)
        with socket.create_connection((host, int(port)), timeout=timeout):
            return True
    except OSError:
        return False


class RequestHandler:
    """Interface the server dispatches to (master servicer implements it)."""

    def report(self, node_id: int, node_type: str, message) -> bool:
        raise NotImplementedError

    def get(self, node_id: int, node_type: str, message):
        raise NotImplementedError


class RemoteError(Exception):
    """A handler-side failure, shipped as plain strings so it survives
    pickling/allowlisting regardless of the original exception type."""

    def __init__(self, type_name: str, message: str, tb: str = ""):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.remote_message = message
        self.remote_traceback = tb

    def __reduce__(self):
        # Exception's default reduce replays ``args`` (the single
        # joined string) into the two-arg __init__ — every error
        # frame un-pickled client-side died with a TypeError instead
        # of surfacing the typed remote failure
        return (
            RemoteError,
            (self.type_name, self.remote_message,
             self.remote_traceback),
        )


class ResponseCache:
    """LRU of response frames keyed by request id, shared by every
    connection of a server, so a retried request is answered from cache
    instead of re-executing its handler."""

    def __init__(self, capacity: int = 8192):
        self._capacity = capacity
        self._cache: "OrderedDict[str, object]" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, req_id: str):
        with self._lock:
            if req_id in self._cache:
                self._cache.move_to_end(req_id)
                return True, self._cache[req_id]
            return False, None

    def put(self, req_id: str, resp):
        if not req_id:
            return
        with self._lock:
            self._cache[req_id] = resp
            while len(self._cache) > self._capacity:
                self._cache.popitem(last=False)


class _Connection(socketserver.BaseRequestHandler):
    def handle(self):
        server: "MessageServer" = self.server  # type: ignore[assignment]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = _recv_frame(sock)
            except (ConnectionError, OSError):
                return
            except Exception:
                logger.exception("malformed frame; dropping connection")
                return
            t_dispatch = time.perf_counter()
            bare_verb = "?"
            try:
                verb, node_id, node_type, req_id, message = frame[:5]
                bare_verb = verb if verb in ("get", "report") else "?"
                trace_ctx = frame[5] if len(frame) > 5 else None
                try:
                    # server-side chaos: a drop kills the connection
                    # BEFORE dispatch, so the client's retry replays
                    # the request against an intact handler (the
                    # response cache covers the executed-but-unacked
                    # case); a delay just stretches dispatch
                    _chaos.fire(
                        "rpc.server.dispatch",
                        verb=verb, node_id=node_id,
                    )
                except ConnectionError:
                    return
                hit, resp = server.response_cache.get(req_id)
                if not hit:
                    with _tracing.attach_context(trace_ctx):
                        if verb == "get":
                            resp = server.handler.get(
                                node_id, node_type, message
                            )
                        elif verb == "report":
                            resp = server.handler.report(
                                node_id, node_type, message
                            )
                        else:
                            resp = RemoteError(
                                "ValueError", f"unknown verb {verb!r}"
                            )
                    server.response_cache.put(req_id, resp)
            except Exception as e:
                logger.exception("handler error for frame %r", frame[:1])
                resp = RemoteError(
                    type(e).__name__, str(e), traceback.format_exc()
                )
            try:
                _send_frame(sock, resp)
                _RPC_SERVER_SECONDS.observe(
                    time.perf_counter() - t_dispatch, verb=bare_verb
                )
            except (ConnectionError, OSError):
                return
            except Exception:
                # unpicklable handler response: report instead of dying
                logger.exception("unpicklable response %r", type(resp))
                try:
                    _send_frame(
                        sock,
                        RemoteError(
                            "PicklingError",
                            f"unpicklable response of type {type(resp)}",
                        ),
                    )
                except (ConnectionError, OSError):
                    return


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    """Thread-per-connection server with connection accounting and
    an optional concurrency guard.

    The base class spawns an unbounded thread per accepted socket
    with zero visibility — under fleet-scale fan-in (hundreds of
    persistent agent connections) that is both the resource to watch
    and the one to bound.  Accounting feeds the
    ``dlrover_master_connections`` gauge; ``max_conns`` (ctor /
    ``DLROVER_MASTER_MAX_CONNS``) rejects over-limit connects with a
    clean :class:`RemoteError` frame (the client surfaces it as a
    typed exception instead of a hang) before any thread is spawned.
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, handler_cls, max_conns: int = 0):
        self.max_conns = int(max_conns)
        self._conn_lock = threading.Lock()
        self._conns_active = 0
        self._conns_accepted = 0
        self._conns_peak = 0
        super().__init__(addr, handler_cls)

    def _publish_conn_stats(self):
        # caller holds _conn_lock
        _CONNS_GAUGE.set(self._conns_accepted, state="accepted")
        _CONNS_GAUGE.set(self._conns_active, state="active")
        _CONNS_GAUGE.set(self._conns_peak, state="peak")

    def process_request(self, request, client_address):
        with self._conn_lock:
            if self.max_conns and self._conns_active >= self.max_conns:
                reject = True
            else:
                reject = False
                self._conns_active += 1
                self._conns_accepted += 1
                self._conns_peak = max(
                    self._conns_peak, self._conns_active
                )
            self._publish_conn_stats()
        if reject:
            _CONNS_REJECTED_TOTAL.inc()
            logger.warning(
                "connection from %s rejected: %d active >= "
                "max_conns %d", client_address, self._conns_active,
                self.max_conns,
            )
            # the handshake runs on a SHORT-LIVED thread (bounded by
            # the rejection rate, not the connection count — the
            # guard's point stands): the client's first request must
            # be DRAINED before closing, or close() on a socket with
            # unread bytes RSTs and can discard the queued error
            # frame — the client would then see ECONNRESET and burn
            # its whole retry envelope instead of failing typed
            threading.Thread(
                target=self._reject_conn,
                args=(request,),
                daemon=True,
                name="conn-reject",
            ).start()
            return
        super().process_request(request, client_address)

    def _reject_conn(self, request):
        try:
            request.settimeout(2.0)
            try:
                _recv_frame(request)  # drain the first request
            except Exception:  # noqa: BLE001 - any garbage is fine,
                pass  # the point is emptying the receive queue
            _send_frame(request, RemoteError(
                "ResourceExhausted",
                f"master connection limit {self.max_conns} "
                "reached",
            ))
            try:
                request.shutdown(socket.SHUT_WR)
            except OSError:
                pass
        except (OSError, ValueError):
            pass
        finally:
            self.shutdown_request(request)

    def finish_request(self, request, client_address):
        # runs on the per-connection thread; the finally fires when
        # the handler returns, so `active` tracks live threads (the
        # reject path never incremented and never lands here)
        try:
            super().finish_request(request, client_address)
        finally:
            with self._conn_lock:
                self._conns_active = max(0, self._conns_active - 1)
                self._publish_conn_stats()


class MessageServer:
    """Threaded request server (role of create_master_service,
    reference servicer.py:630)."""

    def __init__(
        self,
        port: int,
        handler: RequestHandler,
        host: str = "0.0.0.0",
        cache_capacity: int = 8192,
        max_conns: Optional[int] = None,
    ):
        """``cache_capacity`` bounds the idempotent-retry response
        cache; servers whose responses are LARGE (e.g. the coworker
        data service shipping whole batches) should size it to what
        memory affords x the retry window they must cover.
        ``max_conns`` (default ``DLROVER_MASTER_MAX_CONNS``, 0 =
        unlimited) bounds concurrent connections — each costs a
        server thread, and fleet-scale fan-in must degrade with a
        clean typed error instead of a thread pile-up."""
        self.handler = handler
        if max_conns is None:
            max_conns = int(_env_float(MAX_CONNS_ENV, 0))
        self._server = _ThreadingTCPServer(
            (host, port), _Connection, max_conns=max_conns
        )
        self._server.handler = handler  # type: ignore[attr-defined]
        self._server.response_cache = ResponseCache(  # type: ignore[attr-defined]
            capacity=cache_capacity
        )
        self._thread: Optional[threading.Thread] = None
        self.port = self._server.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="message-server",
            daemon=True,
        )
        self._thread.start()
        logger.info("MessageServer listening on port %s", self.port)

    def stop(self):
        # shutdown() blocks forever if serve_forever never ran (stop
        # before start); only the socket close is needed then
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()


class MessageClient:
    """Persistent client connection with retry (role of MasterClient's
    channel layer, reference elastic_agent/master_client.py:28
    retry_grpc_request)."""

    def __init__(
        self,
        addr: str,
        node_id: int = -1,
        node_type: str = "",
        timeout: float = 60.0,
        retries: Optional[int] = None,
        backoff_base: Optional[float] = None,
        backoff_max: Optional[float] = None,
        resync_timeout: Optional[float] = None,
    ):
        self._addr = addr
        self._node_id = node_id
        self._node_type = node_type
        self._timeout = timeout
        self._retries = max(1, int(
            retries if retries is not None
            else _env_float(RPC_RETRIES_ENV, 10)
        ))
        self._backoff_base = (
            backoff_base if backoff_base is not None
            else _env_float(RPC_BACKOFF_BASE_ENV, 0.5)
        )
        self._backoff_max = (
            backoff_max if backoff_max is not None
            else _env_float(RPC_BACKOFF_MAX_ENV, 8.0)
        )
        # 0 disables the park-for-respawn loop (the generic default:
        # ad-hoc clients should fail fast); the agent's MasterClient
        # turns it on so a master crash/restart is survivable
        self._resync_timeout = (
            resync_timeout if resync_timeout is not None
            else _env_float(RPC_RESYNC_TIMEOUT_ENV, 0.0)
        )
        self._session_resync_cb = None
        self._in_resync = False
        self._last_resync = -1e9
        self._rng = random.Random()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def set_session_resync(self, callback):
        """Register the handshake replayed after a master comes back
        from a crash (the agent's MasterClient sends node id, restart
        count, last reported step and last acked task so the recovered
        master rebuilds live state without restarting trainers)."""
        self._session_resync_cb = callback

    def _connect(self) -> socket.socket:
        host, port = self._addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _RPC_RECONNECTS_TOTAL.inc()
        return sock

    def _roundtrip(self, verb: str, message):
        """One logical request, surviving both transient drops and a
        full master crash/restart.

        The inner attempt loop walks the jittered-backoff envelope.
        When it is exhausted and a resync window is configured, the
        client parks: it re-resolves the master address and probes
        reachability until the (re)spawned master answers or the
        window closes, replays the session-resync handshake, then
        retries the request — same req id, so a request the dead
        master executed-but-never-acked is answered from the response
        cache (or harmlessly re-executed by the recovered master,
        whose journal replay made the handlers idempotent)."""
        # one id for all attempts: a retry of an executed-but-unacked
        # request is answered from the server's response cache
        req_id = uuid.uuid4().hex
        try:
            return self._attempt_loop(verb, message, req_id)
        except (ConnectionError, OSError) as e:
            if self._resync_timeout <= 0:
                raise
            if not self._await_master(e):
                raise ConnectionError(
                    f"master at {self._addr} did not come back within "
                    f"the {self._resync_timeout:.0f}s resync window: "
                    f"{e}"
                ) from e
            if not self._in_resync:
                self._run_session_resync()
            return self._attempt_loop(verb, message, req_id)

    def _await_master(self, cause: Exception) -> bool:
        """Bounded re-resolve/reconnect park: the master process died
        (or a long partition outlived the retry envelope).  Re-read
        the ambient master address every probe — a respawned master
        may come back elsewhere — and return once it accepts
        connections."""
        _RPC_RESYNC_PARKS_TOTAL.inc()
        logger.warning(
            "master at %s unreachable (%s); parking up to %.0fs for "
            "a respawn", self._addr, cause, self._resync_timeout,
        )
        deadline = time.monotonic() + self._resync_timeout
        while time.monotonic() < deadline:
            env_addr = os.environ.get(NodeEnv.MASTER_ADDR, "")
            if env_addr and env_addr != self._addr:
                logger.warning(
                    "master address re-resolved: %s -> %s",
                    self._addr, env_addr,
                )
                self._addr = env_addr
            if addr_connected(self._addr, timeout=1.0):
                _RPC_RESYNC_RECONNECTS_TOTAL.inc()
                logger.info(
                    "master back at %s; resuming", self._addr
                )
                return True
            time.sleep(0.2 + self._rng.uniform(0.0, 0.2))
        return False

    def _run_session_resync(self):
        cb = self._session_resync_cb
        if cb is None:
            return
        self._in_resync = True
        try:
            cb()
        except Exception as e:  # noqa: BLE001 - the resync is
            # best-effort state rebuild; the original request decides
            # success
            logger.warning("session resync handshake failed: %s", e)
        finally:
            self._in_resync = False

    def _note_recovered(self):
        """A request succeeded AFTER at least one connection-level
        failure: the master may be a respawned incarnation that knows
        nothing of this session (its response cache and live state
        died with its predecessor), so replay the resync handshake.
        Rate-limited: a flaky window produces many reconnects but one
        handshake rebuilds everything."""
        if self._session_resync_cb is None or self._in_resync:
            return
        now = time.monotonic()
        if now - self._last_resync < 2.0:
            return
        self._last_resync = now
        self._run_session_resync()

    def _attempt_loop(self, verb: str, message, req_id: str):
        """Bounded, jittered-backoff retries of one request.

        Every attempt may fail at connect, send or receive — repeated
        connect failures (master rescheduling, RPC partition) walk the
        same exponential envelope as mid-stream drops, the sleep is
        jittered so a partition's worth of clients cannot reconnect in
        lockstep, and the final attempt raises immediately instead of
        paying one more backoff it can never use."""
        last_err: Optional[Exception] = None
        for attempt in range(self._retries):
            try:
                # chaos hook: a drop/partition rule raises
                # ConnectionError here and exercises exactly this
                # retry path; a delay rule stretches the roundtrip
                _chaos.fire(
                    "rpc.client.roundtrip", verb=verb, addr=self._addr
                )
                with self._lock:
                    if self._sock is None:
                        self._sock = self._connect()
                    # append the trace field only when a span is
                    # active: the common no-span frame stays a
                    # 5-tuple an un-upgraded server can unpack
                    trace_ctx = _tracing.inject_context()
                    frame = (
                        verb, self._node_id, self._node_type,
                        req_id, message,
                    )
                    if trace_ctx is not None:
                        frame += (trace_ctx,)
                    _send_frame(self._sock, frame)
                    resp = _recv_frame(self._sock)
                if isinstance(resp, Exception):
                    raise resp
                if last_err is not None:
                    # recovered after a connection-level failure: the
                    # server may be a fresh master incarnation —
                    # replay the session-resync handshake
                    self._note_recovered()
                return resp
            except (ConnectionError, OSError) as e:
                last_err = e
                _RPC_RETRIES_TOTAL.inc(verb=verb)
                with self._lock:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                if attempt + 1 >= self._retries:
                    break
                backoff = compute_backoff(
                    attempt, self._backoff_base, self._backoff_max,
                    self._rng,
                )
                logger.warning(
                    "connection to %s failed (%s); retry %d/%d in %.1fs",
                    self._addr, e, attempt + 1, self._retries, backoff,
                )
                time.sleep(backoff)
        raise ConnectionError(
            f"cannot reach master at {self._addr} after "
            f"{self._retries} attempts: {last_err}"
        )

    def get(self, message):
        return self._roundtrip("get", message)

    def report(self, message) -> bool:
        return bool(self._roundtrip("report", message))

    def close(self):
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
