"""Node models shared by master components.

Role of ``dlrover/python/common/node.py``: the master's in-memory view
of each node (status, resources, rank, restart accounting) plus the
group-resource description used by scale plans.
"""

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.constants import NodeStatus


@dataclass
class NodeResource:
    cpu: float = 0.0
    memory_mb: float = 0.0
    # TPU chips attached to this host (v5p TPU-VM: 4 chips/host)
    chips: int = 0
    chip_type: str = ""

    def to_dict(self) -> Dict:
        return {
            "cpu": self.cpu,
            "memory_mb": self.memory_mb,
            "chips": self.chips,
            "chip_type": self.chip_type,
        }


@dataclass
class NodeGroupResource:
    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)


@dataclass
class Node:
    type: str = "worker"
    id: int = 0
    rank_index: int = 0
    name: str = ""
    status: str = NodeStatus.INITIAL
    config_resource: NodeResource = field(default_factory=NodeResource)
    used_resource: NodeResource = field(default_factory=NodeResource)
    host_ip: str = ""
    create_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    exit_reason: str = ""
    relaunch_count: int = 0
    max_relaunch_count: int = 3
    relaunchable: bool = True
    critical: bool = False
    is_released: bool = False
    heartbeat_time: float = 0.0
    # elapsed time reported by the node health check
    check_elapsed: float = 0.0

    def update_status(self, status: str):
        self.status = status
        if status == NodeStatus.RUNNING and not self.start_time:
            self.start_time = time.time()
        if status in NodeStatus.end_states():
            self.finish_time = time.time()

    def is_alive(self) -> bool:
        return self.status in (NodeStatus.PENDING, NodeStatus.RUNNING)

    def inc_relaunch_count(self):
        self.relaunch_count += 1

    def exceeded_max_relaunch(self) -> bool:
        return self.relaunch_count >= self.max_relaunch_count


@dataclass
class NodeEvent:
    event_type: str
    node: Node


def new_worker(node_id: int, rank: int = -1, chips: int = 0) -> Node:
    return Node(
        type="worker",
        id=node_id,
        rank_index=rank if rank >= 0 else node_id,
        name=f"worker-{node_id}",
        create_time=time.time(),
        config_resource=NodeResource(chips=chips),
    )
