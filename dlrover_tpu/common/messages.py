"""Control-plane message schema between master, agents and trainers.

The reference serializes ~45 ``@dataclass`` message types with pickle
inside a generic proto ``Message.data`` and dispatches on type in the
servicer (``dlrover/python/common/grpc.py:129-``,
``dlrover/proto/elastic_training.proto:20-34``).  We keep the same
shape — one ``report`` (fire-and-forget ack) and one ``get``
(request/response) verb, typed dataclasses dispatched by class — over
the socket transport in :mod:`dlrover_tpu.common.comm`.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class Message:
    """Marker base class for control-plane messages."""


# ---------------------------------------------------------------------------
# Generic / envelope
# ---------------------------------------------------------------------------


@dataclass
class BaseRequest(Message):
    node_id: int = -1
    node_type: str = ""
    data: object = None


@dataclass
class BaseResponse(Message):
    success: bool = True
    message: str = ""


# ---------------------------------------------------------------------------
# Rendezvous (reference: servicer._join_rendezvous / rdzv_manager)
# ---------------------------------------------------------------------------


@dataclass
class JoinRendezvousRequest(Message):
    node_id: int = 0
    node_rank: int = 0
    local_world_size: int = 1
    rdzv_name: str = ""
    node_ip: str = ""


@dataclass
class JoinRendezvousResponse(Message):
    round: int = 0


@dataclass
class CommWorldRequest(Message):
    node_id: int = 0
    node_rank: int = 0
    rdzv_name: str = ""


@dataclass
class CommWorldResponse(Message):
    rdzv_round: int = 0
    group: int = 0
    # {node_rank: local_world_size}, empty while rendezvous incomplete
    world: Dict[int, int] = field(default_factory=dict)
    # coordinator address for jax.distributed.initialize; chosen by the
    # master as the lowest-rank node's ip:port once the round completes.
    coordinator: str = ""


@dataclass
class NumNodesWaitingRequest(Message):
    rdzv_name: str = ""


@dataclass
class NumNodesWaitingResponse(Message):
    num_nodes: int = 0


@dataclass
class NetworkReadyRequest(Message):
    pass


@dataclass
class NetworkStatusRequest(Message):
    node_id: int = 0
    normal: bool = True
    elapsed_time: float = 0.0


@dataclass
class NetworkCheckResultRequest(Message):
    node_id: int = 0


@dataclass
class NetworkCheckResultResponse(Message):
    normal: bool = True
    # nodes the master has diagnosed as faulty / straggling this round
    fault_nodes: List[int] = field(default_factory=list)
    straggler_nodes: List[int] = field(default_factory=list)
    reason: str = ""


# ---------------------------------------------------------------------------
# KV store (rendezvous bootstrap store; reference: master_kv_store.py)
# ---------------------------------------------------------------------------


@dataclass
class KeyValuePair(Message):
    key: str = ""
    value: bytes = b""


@dataclass
class KeyValueGetRequest(Message):
    key: str = ""


@dataclass
class KeyValueAddRequest(Message):
    key: str = ""
    amount: int = 0


@dataclass
class KeyValueAddResponse(Message):
    value: int = 0


# ---------------------------------------------------------------------------
# Dynamic data sharding (reference: shard/task_manager.py, proto Task)
# ---------------------------------------------------------------------------


@dataclass
class DatasetShardParams(Message):
    batch_size: int = 0
    num_epochs: int = 1
    dataset_size: int = 0
    shuffle: bool = False
    num_minibatches_per_shard: int = 2
    dataset_name: str = ""
    task_type: str = ""
    storage_type: str = "text"


@dataclass
class ShardTask(Message):
    task_id: int = -1
    task_type: str = ""
    dataset_name: str = ""
    start: int = 0
    end: int = 0
    # optional shuffled per-sample index list for this shard
    indices: Optional[List[int]] = None

    @property
    def shard_size(self) -> int:
        return self.end - self.start


@dataclass
class GetShardTaskRequest(Message):
    worker_id: int = 0
    dataset_name: str = ""


@dataclass
class ReportTaskResultRequest(Message):
    task_id: int = -1
    dataset_name: str = ""
    worker_id: int = 0
    success: bool = True
    error: str = ""


@dataclass
class DatasetCheckpointRequest(Message):
    dataset_name: str = ""


@dataclass
class DatasetCheckpointResponse(Message):
    content: str = ""


@dataclass
class RestoreDatasetCheckpointRequest(Message):
    dataset_name: str = ""
    content: str = ""


# ---------------------------------------------------------------------------
# Metrics / monitoring (reference: servicer report paths, SpeedMonitor)
# ---------------------------------------------------------------------------


@dataclass
class GlobalStepRecord(Message):
    node_id: int = 0
    global_step: int = 0
    timestamp: float = 0.0


@dataclass
class NodeResourceStats(Message):
    node_id: int = 0
    node_type: str = ""
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    # per-chip HBM/duty-cycle stats when available
    chip_stats: List[Dict[str, float]] = field(default_factory=list)


@dataclass
class ModelInfo(Message):
    num_params: int = 0
    dtype: str = ""
    flops_per_step: float = 0.0
    extra: Dict[str, float] = field(default_factory=dict)


@dataclass
class HeartbeatRequest(Message):
    node_id: int = 0
    timestamp: float = 0.0
    # step-report piggybacking (fleet fan-in relief): a client with
    # DLROVER_STEP_PIGGYBACK armed folds its latest global step into
    # the heartbeat instead of paying a second RPC; -1 = none riding
    global_step: int = -1
    step_timestamp: float = 0.0


@dataclass
class HeartbeatResponse(Message):
    # master can piggyback an action on the heartbeat ack
    action: str = ""


# ---------------------------------------------------------------------------
# Failure / diagnosis (reference: report_failures, error_monitor)
# ---------------------------------------------------------------------------


@dataclass
class NodeFailure(Message):
    node_id: int = 0
    node_rank: int = 0
    error_data: str = ""
    level: str = ""
    restart_count: int = 0


@dataclass
class DiagnosisData(Message):
    node_id: int = 0
    data_type: str = ""  # "stack" | "log" | "chip_metrics" | "step_time"
    content: str = ""
    timestamp: float = 0.0


# ---------------------------------------------------------------------------
# Node lifecycle / elasticity
# ---------------------------------------------------------------------------


@dataclass
class NodeEventReport(Message):
    node_id: int = 0
    node_type: str = ""
    event_type: str = ""
    status: str = ""
    exit_reason: str = ""


@dataclass
class ReadyToExitRequest(Message):
    node_id: int = 0
    reason: str = ""


@dataclass
class ParallelConfigRequest(Message):
    node_id: int = 0


@dataclass
class ParallelConfig(Message):
    """Runtime-tunable knobs written by master, polled by trainer
    (reference: paral_config_tuner.py ParallelConfig JSON)."""

    dataloader_workers: int = 0
    micro_batch_size: int = 0
    gradient_accumulation: int = 0
    version: int = 0


@dataclass
class ScaleRequest(Message):
    """Request the master to scale the worker group (tests/tools)."""

    node_type: str = "worker"
    count: int = 0


@dataclass
class ResizeRequest(Message):
    """Operator-requested world resize: ask the master's resize
    coordinator to reconverge the job at ``target`` nodes (the manual
    flavour of the alive-count-driven decision; reference: ScalePlan
    CRD written by an operator)."""

    target: int = 0
    reason: str = "operator"


@dataclass
class JobExitRequest(Message):
    reason: str = ""


@dataclass
class SessionResyncRequest(Message):
    """Agent -> recovered master handshake: everything the master
    needs to rebuild this node's live state after a crash/restart —
    identity, incarnation, and the last durable progress marks — so
    healthy trainers keep running instead of being restarted."""

    node_id: int = 0
    node_rank: int = 0
    node_type: str = "worker"
    local_world_size: int = 1
    restart_count: int = 0
    last_step: int = 0
    last_acked_dataset: str = ""
    last_acked_task: int = -1
    # every ack the mirror's group-commit lag could have lost — the
    # single last_acked_* pair (kept for older agents) misses earlier
    # acks when several complete inside one commit window
    recent_acked_tasks: List[Tuple[str, int]] = field(
        default_factory=list
    )


@dataclass
class SessionResyncResponse(Message):
    """``incarnation`` identifies the master process instance; a
    change tells the agent a recovery happened (it logs/emits, it
    does NOT restart healthy workers)."""

    incarnation: str = ""
    rdzv_round: int = 0
    recoveries: int = 0
    success: bool = True


# (node_id, node_type, message) -> response message tuple alias
Request = Tuple[int, str, Message]
