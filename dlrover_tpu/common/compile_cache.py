"""Job-keyed persistent XLA compilation cache.

Retrace is the last big serial term of a worker recovery: the
respawned trainer re-traces its jitted step and, without a persistent
compilation cache, re-COMPILES it — seconds on CPU, minutes for XL
models through a device tunnel.  jax ships the cache
(``jax_compilation_cache_dir``); what the elastic stack must supply is
the *sharing contract*: every incarnation of a job — including a
replacement worker on a different host after a resize — must resolve
the SAME cache directory, so the first incarnation's compile
pre-populates what every later one hits.

Resolution order for :func:`job_cache_dir`:

1. ``DLROVER_COMPILE_CACHE_DIR`` — the operator's explicit choice
   (point it at job-shared storage for cross-host hits);
2. an ambient ``JAX_COMPILATION_CACHE_DIR`` (the user already chose);
3. ``<tmpdir>/dlrover_jax_cache_<job>`` keyed off the job identity
   (``DLROVER_JOB_NAME`` or the IPC socket-dir hash — the same
   namespace rule the shm segments use), so two jobs on one host
   never share entries but every incarnation of one job does.

Hit detection (:func:`cache_entries` + the trainer's retrace monitor)
counts ``*-cache`` files: jax writes one per compiled executable and
touches only the ``-atime`` sibling on a hit, so "no new entries
across the first post-restore step" IS the cache-hit witness — checked
from the filesystem, robust across jax versions.

The witness distinguishes THREE outcomes since the AOT executable
cache (:mod:`dlrover_tpu.common.aot_cache`) landed, surfaced as the
``status`` field of every ``compile_cache`` event:

- ``aot-hit`` — the step was deserialized whole; no trace, no XLA
  compile, this cache was never consulted;
- ``xla-cache-hit`` — traced, but the compile came from this cache
  (no new ``*-cache`` entries over a warm dir);
- ``cold`` — traced AND compiled from scratch.

:func:`aot_entries` counts the AOT half so both witnesses read from
one module.
"""

import os
import tempfile
from typing import Dict, Optional

from dlrover_tpu.common.log import default_logger as logger

CACHE_DIR_ENV = "JAX_COMPILATION_CACHE_DIR"
DLROVER_CACHE_DIR_ENV = "DLROVER_COMPILE_CACHE_DIR"

# every executable should land in the cache: recovery needs the whole
# step function back, not just the slow-to-compile subset
_CACHE_TUNING = {
    "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "0",
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.0",
}


def job_cache_dir() -> str:
    """The cache directory every incarnation of this job shares."""
    explicit = os.getenv(DLROVER_CACHE_DIR_ENV, "").strip()
    if explicit:
        return explicit
    ambient = os.getenv(CACHE_DIR_ENV, "").strip()
    if ambient:
        return ambient
    from dlrover_tpu.checkpoint.shm_handler import default_job_suffix

    return os.path.join(
        tempfile.gettempdir(),
        f"dlrover_jax_cache_{default_job_suffix()}",
    )


def cache_env(cache_dir: str = "") -> Dict[str, str]:
    """Env block a worker spawn exports so its jax import freezes the
    shared cache on (the forkserver additionally pushes these through
    ``jax.config`` for template forks whose jax imported earlier)."""
    return {
        CACHE_DIR_ENV: cache_dir or job_cache_dir(),
        **_CACHE_TUNING,
    }


def enable_persistent_cache(cache_dir: str = "") -> str:
    """In-process activation (idempotent): create the directory and
    push the config through ``jax.config`` — the path for processes
    whose jax imported before the env was exported.  Returns the
    active directory, or ``""`` when jax refused the options."""
    cache_dir = cache_dir or job_cache_dir()
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError as e:
        logger.warning(
            "compile cache dir %s not creatable: %s", cache_dir, e
        )
        return ""
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes", 0
        )
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0
        )
    except Exception as e:  # noqa: BLE001 - old jax / no option
        logger.warning("persistent compile cache unavailable: %s", e)
        return ""
    return cache_dir


def cache_entries(cache_dir: Optional[str] = None) -> int:
    """Number of compiled executables in the cache (``*-cache``
    files; the ``-atime`` siblings are hit markers, not entries).

    Deliberately a names-only ``listdir`` of the top directory (jax
    writes the cache flat): a recursive walk stats every entry, and
    on a sandboxed filesystem with a cold dentry cache that costs
    ~5 ms per file — measured at 0.7 s of the recovery critical path
    for a ~100-entry cache, swamping the very retrace it witnesses."""
    cache_dir = cache_dir if cache_dir is not None else job_cache_dir()
    try:
        return sum(
            1 for f in os.listdir(cache_dir) if f.endswith("-cache")
        )
    except OSError:
        return 0


def aot_entries(cache_dir: Optional[str] = None) -> int:
    """Number of serialized step executables in the AOT cache — the
    second half of the hit witness (an ``aot-hit`` consults no
    ``*-cache`` file at all, so counting only those would read a
    fully-warm recovery as suspiciously idle)."""
    from dlrover_tpu.common.aot_cache import aot_entries as _entries

    return _entries(cache_dir)
