"""Checkpoint storage abstraction + deletion strategies.

Role of ``dlrover/python/common/storage.py``: a small write/read/
safe-move/commit surface the async saver uses so POSIX disk, NFS and
object stores are interchangeable.  GCS support is provided through
``tensorstore``/``etils`` when available; on TPU-VMs checkpoints land
on local SSD first and the commit step moves them into place
atomically.
"""

import os
import shutil
import tempfile
import threading
from typing import List, Optional

from dlrover_tpu import chaos as _chaos
from dlrover_tpu.common.log import default_logger as logger


class CheckpointDeletionStrategy:
    """Decides which persisted steps to clean after a new commit."""

    def clean_up(self, step: int, delete_fn):
        raise NotImplementedError


class KeepStepIntervalStrategy(CheckpointDeletionStrategy):
    """Keep checkpoints whose step % interval == 0, delete the rest
    (reference: storage.py:203).  Deletion is deferred by one commit —
    the step just persisted is never removed, only the previously
    committed one once a newer checkpoint exists (reference
    storage.py:301-305 tracks pre_step for exactly this)."""

    def __init__(self, keep_interval: int, checkpoint_dir: str):
        self._keep_interval = max(1, keep_interval)
        self._dir = checkpoint_dir
        self._pre_step = -1

    def clean_up(self, step: int, delete_fn):
        prev, self._pre_step = self._pre_step, step
        if prev < 0 or prev == step or prev % self._keep_interval == 0:
            return
        delete_fn(os.path.join(self._dir, str(prev)))


class KeepLatestStepStrategy(CheckpointDeletionStrategy):
    """Keep at most N latest step dirs (reference: storage.py:231)."""

    def __init__(self, max_to_keep: int, checkpoint_dir: str):
        self._max_to_keep = max(1, max_to_keep)
        self._dir = checkpoint_dir
        self._steps: List[int] = []
        self._lock = threading.Lock()

    def clean_up(self, step: int, delete_fn):
        with self._lock:
            self._steps.append(step)
            while len(self._steps) > self._max_to_keep:
                stale = self._steps.pop(0)
                delete_fn(os.path.join(self._dir, str(stale)))


class CheckpointStorage:
    """Abstract storage (reference: storage.py CheckpointStorage ABC)."""

    def write(self, content, path: str):
        raise NotImplementedError

    def read(self, path: str, mode: str = "rb"):
        raise NotImplementedError

    def read_view(self, path: str):
        """Bytes-like view of ``path`` for the restore pipeline.  The
        base implementation is an eager :meth:`read`; backends with a
        lazy option (posix mmap) override so page-in overlaps the
        assembly stage instead of serializing in front of it."""
        return self.read(path)

    def safe_move(self, src: str, dst: str):
        raise NotImplementedError

    def safe_makedirs(self, path: str):
        raise NotImplementedError

    def safe_rmtree(self, path: str):
        raise NotImplementedError

    def commit(self, step: int, success: bool):
        """Hook called after all shards of ``step`` are persisted."""

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError


class PosixDiskStorage(CheckpointStorage):
    """Local disk / NFS storage (reference: storage.py:128)."""

    def __init__(
        self,
        deletion_strategy: Optional[CheckpointDeletionStrategy] = None,
    ):
        self._deletion_strategy = deletion_strategy

    def write(self, content, path: str):
        # chaos hook: an io_error rule raises OSError into the saver's
        # per-shard error path; a stall rule models a hung NFS/disk
        _chaos.fire("storage.write", path=path)
        mode = "wb" if isinstance(content, (bytes, bytearray, memoryview)) else "w"
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # write-to-temp + rename so readers never observe partial files
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, mode) as f:
                f.write(content)
            os.replace(tmp, path)
        except Exception:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def read(self, path: str, mode: str = "rb"):
        _chaos.fire("storage.read", path=path)
        if not os.path.exists(path):
            return None
        with open(path, mode) as f:
            return f.read()

    def read_view(self, path: str):
        """mmap the file read-only: attaching is O(1) and pages fault
        in lazily, so the restore pipeline's chunked parallel copies
        overlap disk read-ahead with assembly and H2D instead of
        waiting for a full eager read first.  The mapping outlives the
        fd (closed immediately) and is released when the last
        ``frombuffer`` view drops."""
        _chaos.fire("storage.read", path=path)
        if not os.path.exists(path):
            return None
        import mmap

        with open(path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            if size == 0:
                return b""
            return mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)

    def safe_move(self, src: str, dst: str):
        _chaos.fire("storage.move", path=dst)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        if os.path.exists(dst):
            self.safe_rmtree(dst)
        shutil.move(src, dst)

    def safe_makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def safe_rmtree(self, path: str):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def commit(self, step: int, success: bool):
        if not success or self._deletion_strategy is None:
            return
        try:
            self._deletion_strategy.clean_up(step, self.safe_rmtree)
        except Exception:
            logger.exception("checkpoint clean-up failed for step %s", step)


class FsspecStorage(CheckpointStorage):
    """Object-store storage over fsspec — ``gs://`` buckets (gcsfs),
    ``s3://``, or ``memory://`` for tests (reference: the pluggable
    storage factory, storage.py:320; the north star persists Llama
    checkpoints to GCS).

    Atomicity model: a GCS object PUT is atomic (readers see either
    nothing or the whole object), so shard writes need no temp+rename;
    the tracker file is one small object PUT, which replaces the
    reference's rename-based commit."""

    def __init__(
        self,
        deletion_strategy: Optional[CheckpointDeletionStrategy] = None,
        fs=None,
        protocol: str = "gs",
    ):
        import fsspec

        self._fs = fs or fsspec.filesystem(protocol)
        self._deletion_strategy = deletion_strategy

    def write(self, content, path: str):
        _chaos.fire("storage.write", path=path)
        mode = "wb" if isinstance(
            content, (bytes, bytearray, memoryview)
        ) else "w"
        with self._fs.open(path, mode) as f:
            f.write(content)

    def read(self, path: str, mode: str = "rb"):
        if not self._fs.exists(path):
            return None
        with self._fs.open(path, mode) as f:
            return f.read()

    def safe_move(self, src: str, dst: str):
        if self._fs.exists(dst):
            self.safe_rmtree(dst)
        self._fs.mv(src, dst, recursive=True)

    def safe_makedirs(self, path: str):
        # object stores have no real directories; makedirs is a no-op
        # beyond fsspec's bookkeeping
        try:
            self._fs.makedirs(path, exist_ok=True)
        except Exception:  # noqa: BLE001 - some backends reject it
            pass

    def safe_rmtree(self, path: str):
        try:
            if self._fs.exists(path):
                self._fs.rm(path, recursive=True)
        except FileNotFoundError:
            pass

    def exists(self, path: str) -> bool:
        return bool(self._fs.exists(path))

    def listdir(self, path: str) -> List[str]:
        if not self._fs.exists(path):
            return []
        names = []
        for entry in self._fs.ls(path, detail=False):
            name = str(entry).rstrip("/").rsplit("/", 1)[-1]
            if name:
                names.append(name)
        return sorted(names)

    def commit(self, step: int, success: bool):
        if not success or self._deletion_strategy is None:
            return
        try:
            self._deletion_strategy.clean_up(step, self.safe_rmtree)
        except Exception:  # noqa: BLE001
            logger.exception(
                "checkpoint clean-up failed for step %s", step
            )


def get_checkpoint_storage(
    deletion_strategy: Optional[CheckpointDeletionStrategy] = None,
    path: str = "",
) -> CheckpointStorage:
    """Factory dispatching on the checkpoint path (reference:
    get_checkpoint_storage, storage.py:320): ``gs://...`` (or any
    ``proto://``) selects the fsspec object-store backend, everything
    else the POSIX backend (covers NFS and FUSE-mounted buckets)."""
    if "://" in path:
        protocol = path.split("://", 1)[0]
        return FsspecStorage(deletion_strategy, protocol=protocol)
    return PosixDiskStorage(deletion_strategy)
