"""Singleton master configuration (role of
dlrover/python/common/global_context.py): ports, thresholds and feature
flags, overridable from env for tests."""

import os

from dlrover_tpu.common.constants import DefaultPorts
from dlrover_tpu.common.env_utils import _get_float as _env_float
from dlrover_tpu.common.singleton import Singleton


class Context(Singleton):
    def __init__(self):
        self.master_port = int(
            os.getenv("DLROVER_MASTER_PORT", DefaultPorts.MASTER)
        )
        # rendezvous
        self.rdzv_default_timeout = 600
        self.seconds_to_wait_pending_pod = 900
        # heartbeat: node considered dead after this silence window
        # (reference: dist_job_manager.py:355 300s window).  Env-
        # overridable: the elastic-resize chaos scenario shrinks it so
        # a SIGKILLed node (no failure report possible) is detected in
        # seconds and the resize decision path can play out tier-1
        self.hang_detection_seconds = _env_float(
            "DLROVER_HANG_DETECTION_S", 300
        )
        # master main-loop hang checks (env-overridable: the chaos
        # hang scenario shrinks both so a tier-1 run diagnoses a
        # frozen trainer in seconds, not half an hour)
        self.seconds_to_check_hang = _env_float(
            "DLROVER_SECONDS_TO_CHECK_HANG", 30
        )
        self.hang_timeout = _env_float("DLROVER_HANG_TIMEOUT", 1800)
        # network check
        self.network_check_timeout = 300
        self.straggler_factor = 2.0
        # relaunch policy
        self.relaunch_on_worker_failure = 3
        self.relaunch_always = False
        # speed monitor
        self.train_speed_record_num = 50
        # auto tuning / scaling
        self.auto_tuning_enabled = False
        self.auto_scaling_enabled = False
        self.seconds_interval_to_optimize = 300
        # checkpoint
        self.checkpoint_commit_timeout = 600

    @classmethod
    def instance(cls) -> "Context":
        return cls.singleton_instance()
