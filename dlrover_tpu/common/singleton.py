"""Thread-safe singleton base (role of dlrover/python/common/singleton.py)."""

import threading


class Singleton:
    _instance_lock = threading.Lock()

    @classmethod
    def singleton_instance(cls, *args, **kwargs):
        if not hasattr(cls, "_instance"):
            with cls._instance_lock:
                if not hasattr(cls, "_instance"):
                    cls._instance = cls(*args, **kwargs)
        return cls._instance

    @classmethod
    def reset_singleton(cls):
        """Drop the cached instance (tests)."""
        with cls._instance_lock:
            if hasattr(cls, "_instance"):
                del cls._instance
