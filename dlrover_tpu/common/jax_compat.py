"""Version shims over moved JAX APIs.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map``, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` in the same era; depending on the
installed jax only one spelling of each exists.  Every in-repo caller
goes through this module and uses the NEW spellings; the shim rewrites
them for an older jax.

Resolution is lazy (first call) so importing a module that merely
*mentions* shard_map — e.g. the agent's node_check — does not pay the
jax import in processes that never run device code.
"""

import inspect
import os

_shard_map = None
_check_kwarg = "check_vma"


def shard_map(*args, **kwargs):
    global _shard_map, _check_kwarg
    if _shard_map is None:
        import jax

        try:
            _shard_map = jax.shard_map
        except AttributeError:  # pre-graduation jax (< 0.6)
            from jax.experimental.shard_map import (
                shard_map as _experimental,
            )

            _shard_map = _experimental
        try:
            params = inspect.signature(_shard_map).parameters
            if "check_vma" not in params and "check_rep" in params:
                _check_kwarg = "check_rep"
        except (TypeError, ValueError):  # builtin/odd signature
            pass
    if _check_kwarg != "check_vma" and "check_vma" in kwargs:
        kwargs[_check_kwarg] = kwargs.pop("check_vma")
    return _shard_map(*args, **kwargs)


def memory_placement(kind: str):
    """A ``jax.device_put`` destination meaning "same sharding, memory
    ``kind``" for in-jit transfers.

    Newer jax spells it ``jax.memory.Space``; before that the same
    transfer is requested with ``TransferToMemoryKind`` (kinds
    ``pinned_host`` / ``device``).
    """
    try:
        from jax.memory import Space

        return Space.Host if kind == "pinned_host" else Space.Device
    except ImportError:
        from jax._src.sharding_impls import TransferToMemoryKind

        return TransferToMemoryKind(kind)


def supports_memory_kind(kind: str) -> bool:
    """Whether the default backend can place arrays in ``kind``
    memory (the cpu backend of older jax only has unpinned_host)."""
    import jax.numpy as jnp

    try:
        jnp.ones((1,)).sharding.with_memory_kind(kind)
        return True
    except (ValueError, NotImplementedError):
        return False


def ensure_cpu_collectives():
    """Multi-process collectives on the CPU backend need a transport.

    Newer jax defaults ``jax_cpu_collectives_implementation`` to gloo;
    on 0.4.x the default is ``none`` and a cross-process psum/ppermute
    blocks forever.  Select gloo before ``jax.distributed.initialize``
    when running on CPU; a no-op where the option is gone (gloo is the
    default there) or the backend already initialized.
    """
    import jax

    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu":
        return
    current = getattr(
        jax.config, "jax_cpu_collectives_implementation", None
    )
    if current not in (None, "none"):
        return  # something already picked a real transport
    try:
        jax.config.update(
            "jax_cpu_collectives_implementation", "gloo"
        )
    except (AttributeError, ValueError, RuntimeError):
        pass  # option gone (newer jax defaults to gloo)


def executable_serialization():
    """Capability probe for whole-executable AOT serialization.

    Returns ``(serialize, deserialize_and_load)`` — the
    ``jax.experimental.serialize_executable`` pair that round-trips a
    ``Lowered.compile()`` result through bytes, including the compiled
    XLA binary (no re-trace AND no re-compile at load) — or
    ``(None, None)`` on a jax without it.  Callers must treat the
    ``(None, None)`` answer as "AOT cache off", never as an error: the
    trace-at-first-call path is always correct, just slower.
    """
    try:
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
            serialize,
        )

        return serialize, deserialize_and_load
    except ImportError:
        return None, None


def cost_analysis(compiled) -> dict:
    """``Compiled.cost_analysis()`` normalized to one flat dict.

    Newer jax returns a dict; older jax returns a list with one dict
    per program (a single entry for an unpartitioned module).  Merge
    by summing so per-program flops/bytes aggregate the same way XLA
    reports them for the whole module.
    """
    cost = compiled.cost_analysis()
    if cost is None:
        return {}
    if isinstance(cost, dict):
        return cost
    merged: dict = {}
    for entry in cost:
        for key, value in entry.items():
            try:
                merged[key] = merged.get(key, 0.0) + float(value)
            except (TypeError, ValueError):
                merged.setdefault(key, value)
    return merged
