"""Cross-process IPC primitives between the elastic agent and trainers.

The reference implements unix-socket backed ``SharedLock`` /
``SharedQueue`` / ``SharedDict`` (server lives in the agent process,
clients in the training processes) plus a ``SharedMemory`` subclass
that survives process exit by skipping resource-tracker unlinking
(``dlrover/python/common/multi_process.py:225-609``).  This module
provides the same four primitives with the same ownership model: the
agent owns the state, trainers are thin clients, and checkpoint shared
memory outlives a crashed trainer so the agent can still persist it.
"""

import os
import pickle
import queue
import socket
import threading
import time
import uuid
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, Optional

from dlrover_tpu.common.comm import (
    RemoteError,
    ResponseCache,
    _recv_frame,
    _send_frame,
)
from dlrover_tpu.common.log import default_logger as logger


def socket_dir() -> str:
    d = os.getenv(
        "DLROVER_SHARED_DIR",
        os.path.join("/tmp", f"dlrover_tpu_{os.getuid()}", "sockets"),
    )
    os.makedirs(d, exist_ok=True)
    return d


def _socket_path(name: str) -> str:
    return os.path.join(socket_dir(), f"{name}.sock")


class LocalSocketComm:
    """Base for agent-hosted IPC objects.

    ``create=True`` (agent side) starts a unix-socket server thread;
    ``create=False`` (trainer side) is a client of the same name.
    """

    def __init__(self, name: str, create: bool):
        self._name = name
        self._create = create
        self._path = _socket_path(name)
        self._server: Optional[socket.socket] = None
        self._response_cache = ResponseCache()
        if create:
            self._start_server()

    # -- server ------------------------------------------------------------

    def _start_server(self):
        if os.path.exists(self._path):
            os.unlink(self._path)
        self._server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._server.bind(self._path)
        self._server.listen(128)
        t = threading.Thread(
            target=self._serve, name=f"ipc-{self._name}", daemon=True
        )
        t.start()

    def _serve(self):
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket):
        with conn:
            while True:
                try:
                    req_id, request = _recv_frame(conn)
                except (ConnectionError, OSError, EOFError):
                    return
                except Exception:
                    logger.exception("bad IPC frame on %s", self._name)
                    return
                # replay cached response for a retried request so
                # non-idempotent ops (queue get/put) are exactly-once
                hit, resp = self._response_cache.get(req_id)
                if not hit:
                    try:
                        resp = self._handle(request)
                    except Exception as e:  # surface errors to client
                        resp = RemoteError(type(e).__name__, str(e))
                    self._response_cache.put(req_id, resp)
                try:
                    _send_frame(conn, resp)
                except (ConnectionError, OSError):
                    return

    def _handle(self, request):
        raise NotImplementedError

    # -- client ------------------------------------------------------------

    def _request(self, *request, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        req_id = uuid.uuid4().hex
        while True:
            try:
                with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                    s.settimeout(max(0.1, deadline - time.monotonic()))
                    s.connect(self._path)
                    _send_frame(s, (req_id, request))
                    resp = _recv_frame(s)
                if isinstance(resp, Exception):
                    raise resp
                return resp
            except (ConnectionError, OSError, FileNotFoundError):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"IPC server {self._name} unreachable at {self._path}"
                    )
                time.sleep(0.1)

    def close(self):
        if self._server is not None:
            try:
                self._server.close()
            finally:
                self._server = None
            if os.path.exists(self._path):
                try:
                    os.unlink(self._path)
                except OSError:
                    pass


class SharedLock(LocalSocketComm):
    """Cross-process lock (reference multi_process.py:225 SharedLock).

    The server side only ever does non-blocking try-acquire; blocking
    semantics are a client-side poll loop.  A server thread therefore
    never blocks on behalf of a client, so a client that times out or
    dies mid-acquire cannot orphan the lock in an un-releasable state.
    """

    _POLL_INTERVAL = 0.05

    def __init__(self, name: str, create: bool):
        self._lock = threading.Lock() if create else None
        self._owner: Optional[str] = None
        super().__init__(name, create)

    def _handle(self, request):
        verb = request[0]
        if verb == "try_acquire":
            (_, owner) = request
            ok = self._lock.acquire(blocking=False)
            if ok:
                self._owner = owner
            return ok
        if verb == "release":
            (_, owner) = request
            # only the holder (or a force-release, e.g. agent cleanup
            # after a trainer died) may release
            if self._lock.locked() and (
                owner == self._owner or owner == "__force__"
            ):
                self._owner = None
                self._lock.release()
                return True
            return False
        if verb == "locked":
            return self._lock.locked()
        raise ValueError(f"unknown lock verb {verb}")

    def _try_acquire(self, owner: str) -> bool:
        if self._create:
            return self._handle(("try_acquire", owner))
        return self._request("try_acquire", owner)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        owner = f"pid-{os.getpid()}"
        if not blocking:
            return self._try_acquire(owner)
        deadline = None if timeout < 0 else time.monotonic() + timeout
        while True:
            if self._try_acquire(owner):
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(self._POLL_INTERVAL)

    def release(self, force: bool = False) -> bool:
        """Release if held by this process; ``force=True`` breaks a
        dead holder's lock (agent cleanup after a trainer crash)."""
        owner = "__force__" if force else f"pid-{os.getpid()}"
        if self._create:
            return self._handle(("release", owner))
        return self._request("release", owner)

    def locked(self) -> bool:
        if self._create:
            return self._handle(("locked",))
        return self._request("locked")


class SharedQueue(LocalSocketComm):
    """Cross-process FIFO (reference multi_process.py:346 SharedQueue)."""

    def __init__(self, name: str, create: bool, maxsize: int = 0):
        self._queue: Optional[queue.Queue] = (
            queue.Queue(maxsize) if create else None
        )
        super().__init__(name, create)

    def _handle(self, request):
        verb = request[0]
        if verb == "put":
            self._queue.put(request[1])
            return True
        if verb == "get":
            (_, timeout) = request
            try:
                return ("ok", self._queue.get(timeout=timeout))
            except queue.Empty:
                return ("empty", None)
        if verb == "qsize":
            return self._queue.qsize()
        raise ValueError(f"unknown queue verb {verb}")

    def put(self, obj):
        if self._create:
            return self._handle(("put", obj))
        return self._request("put", obj)

    def get(self, timeout: float = 300.0):
        if self._create:
            status, obj = self._handle(("get", timeout))
        else:
            status, obj = self._request(
                "get", timeout, timeout=timeout + 30.0
            )
        if status == "empty":
            raise queue.Empty
        return obj

    def qsize(self) -> int:
        if self._create:
            return self._handle(("qsize",))
        return self._request("qsize")

    def empty(self) -> bool:
        return self.qsize() == 0


class SharedDict(LocalSocketComm):
    """Cross-process dict (reference multi_process.py:453 SharedDict)."""

    def __init__(self, name: str, create: bool):
        self._dict: Optional[Dict] = {} if create else None
        self._dict_lock = threading.Lock() if create else None
        super().__init__(name, create)

    def _handle(self, request):
        verb = request[0]
        with self._dict_lock:
            if verb == "update":
                self._dict.update(request[1])
                return True
            if verb == "set":
                self._dict = dict(request[1])
                return True
            if verb == "getall":
                return dict(self._dict)
        raise ValueError(f"unknown dict verb {verb}")

    def update(self, d: Dict):
        if self._create:
            return self._handle(("update", d))
        return self._request("update", d)

    def set(self, d: Dict):
        if self._create:
            return self._handle(("set", d))
        return self._request("set", d)

    def get(self, default_if_absent: bool = False) -> Dict:
        """``default_if_absent=True`` returns {} immediately when no
        server socket exists (e.g. reading checkpoint meta before any
        saver was created) instead of polling for 300 s."""
        if self._create:
            return self._handle(("getall",))
        if default_if_absent and not os.path.exists(self._path):
            return {}
        return self._request("getall")


class PersistentSharedMemory(shared_memory.SharedMemory):
    """POSIX shared memory that survives the creating process.

    CPython's resource tracker unlinks shm segments when the creating
    process exits; the reference subclasses SharedMemory to skip that so
    a checkpoint written by a crashed trainer can still be persisted and
    restored by the agent (``multi_process.py:537``).  Python 3.12 has
    no ``track=`` kwarg yet, so we unregister from the tracker
    explicitly.  Call :meth:`unlink` when a segment is truly retired.
    """

    def __init__(self, name: str, create: bool = False, size: int = 0):
        super().__init__(name=name, create=create, size=size)
        try:
            resource_tracker.unregister(self._name, "shared_memory")
        except Exception:
            pass

    def unlink(self):
        # re-register so the tracker's cache stays consistent when the
        # base-class unlink unregisters again
        try:
            resource_tracker.register(self._name, "shared_memory")
        except Exception:
            pass
        super().unlink()

    def close(self):
        """Like the base close, but tolerant of still-exported buffer
        views: a consumer (e.g. a zero-copy device_put alias or a
        lingering np.frombuffer view awaiting GC) keeping the mapping
        alive is not an error for our lifecycle — the mapping dies
        with the last reference; without this, interpreter-shutdown
        ``__del__`` spews ``BufferError: cannot close exported
        pointers exist`` tracebacks."""
        try:
            super().close()
        except BufferError:
            pass


def get_or_create_shm(name: str, size: int) -> PersistentSharedMemory:
    """Attach to ``name`` if it exists with sufficient size, else
    (re)create it."""
    try:
        shm = PersistentSharedMemory(name=name)
        if shm.size >= size:
            return shm
        shm.close()
        shm.unlink()
    except FileNotFoundError:
        pass
    return PersistentSharedMemory(name=name, create=True, size=size)
