"""Env accessors for the agent<->trainer contract (role of
dlrover/python/common/env_utils.py), plus the shared /proc/<pid>/stat
field parser the process-supervision paths rely on."""

import os
from typing import List, Optional

from dlrover_tpu.common.constants import NodeEnv


def _get_int(name: str, default: int = 0) -> int:
    try:
        return int(os.getenv(name, default))
    except (TypeError, ValueError):
        return default


def _get_float(name: str, default: float = 0.0) -> float:
    try:
        return float(os.getenv(name, default))
    except (TypeError, ValueError):
        return default


def proc_stat_fields(pid: int) -> Optional[List[bytes]]:
    """Fields of ``/proc/<pid>/stat`` AFTER the comm field, or None
    when the pid is gone.  comm (field 2) may itself contain spaces or
    ``)``, so fields are split after the LAST ``)`` — index 0 is field
    3 (state), index 1 is field 4 (ppid), index 19 is field 22
    (starttime in clock ticks).  One parser for every consumer
    (forkserver pid-reuse guard, chaos orphan scan) so the escaping
    caveat lives in exactly one place."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        return data.rsplit(b")", 1)[1].split()
    except (OSError, IndexError):
        return None


def get_node_id() -> int:
    return _get_int(NodeEnv.NODE_ID)


def get_node_rank() -> int:
    return _get_int(NodeEnv.NODE_RANK)


def get_node_num() -> int:
    return _get_int(NodeEnv.NODE_NUM, 1)


def get_rank() -> int:
    return _get_int(NodeEnv.RANK)


def get_world_size() -> int:
    return _get_int(NodeEnv.WORLD_SIZE, 1)


def get_local_rank() -> int:
    return _get_int(NodeEnv.LOCAL_RANK)


def get_local_world_size() -> int:
    return _get_int(NodeEnv.LOCAL_WORLD_SIZE, 1)


def get_master_addr() -> str:
    return os.getenv(NodeEnv.MASTER_ADDR, "")


def get_coordinator_addr() -> str:
    return os.getenv(NodeEnv.COORDINATOR_ADDR, "")


def get_job_name() -> str:
    return os.getenv(NodeEnv.JOB_NAME, "local-job")


def get_restart_count() -> int:
    return _get_int(NodeEnv.RESTART_COUNT)
